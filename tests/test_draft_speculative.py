"""Two-model (draft) speculative decoding
(engine/generate.decode_draft_speculative + engine.set_draft).

Correctness bar: identical to plain greedy decode in this suite's fp32
CPU environment — every emitted token is the TARGET's argmax given the
accepted context; the draft model only changes how many land per target
forward. draft == target must accept everything (draft_len tokens per
verify, plus bonus when partial). The reference has no analogue (no
speculation, no KV cache at all — /root/reference/Worker1.py:132-134);
this is a beyond-parity TPU feature: batch-1 decode is HBM-bound, so a
T=1+g verify forward costs ~one normal step.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llm_inference_tpu import EngineConfig, create_engine
from distributed_llm_inference_tpu.engine import generate as G
from distributed_llm_inference_tpu.models import api as M
from distributed_llm_inference_tpu.models.registry import get_model_config

# fast-tier exclusion: two-model compiles; run the full suite (plain
# `pytest`) to include it
pytestmark = pytest.mark.slow

MAX_SEQ = 256


def _greedy_reference(cfg, params, tokens, plen, steps, key):
    sampling = G.default_sampling(greedy=True)
    cache = M.init_kv_cache(cfg, 1, max_seq=MAX_SEQ)
    first, _, cache = G.prefill(
        cfg, params, tokens, jnp.int32(plen), cache, key, sampling
    )
    out, n, _ = G.decode(
        cfg, params, first, cache, jnp.int32(plen), jnp.int32(steps),
        key, sampling, max_steps=steps,
    )
    return first, out, n


def _draft_spec(cfg, params, dcfg, dparams, tokens, plen, steps, key,
                draft_len=4):
    sampling = G.default_sampling(greedy=True)
    cache = M.init_kv_cache(cfg, 1, max_seq=MAX_SEQ)
    first, _, cache = G.prefill(
        cfg, params, tokens, jnp.int32(plen), cache, key, sampling
    )
    dcache = M.init_kv_cache(dcfg, 1, max_seq=MAX_SEQ)
    _, _, dcache = G.prefill(
        dcfg, dparams, tokens, jnp.int32(plen), dcache, key, sampling
    )
    out, n, _, _ = G.decode_draft_speculative(
        cfg, params, dcfg, dparams, first, cache, dcache,
        jnp.int32(plen), jnp.int32(steps), max_steps=steps,
        draft_len=draft_len,
    )
    return first, out, n


def _ids(cfg, plen, seed=0, bucket=32):
    rng = np.random.default_rng(seed)
    ids = rng.integers(3, cfg.vocab_size, size=plen).tolist()
    tokens = jnp.asarray(
        [ids + [cfg.pad_token_id] * (bucket - plen)], jnp.int32
    )
    return ids, tokens


@pytest.mark.parametrize("draft_len", [2, 4])
def test_weak_draft_matches_plain_greedy(draft_len):
    """A DIFFERENT draft model (other init seed — mostly-rejected
    proposals) must still emit exactly the target's greedy tokens."""
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    dparams = M.init_params(cfg, jax.random.PRNGKey(7))
    ids, tokens = _ids(cfg, 11)
    key = jax.random.PRNGKey(1)
    steps = 24
    _, ref_out, ref_n = _greedy_reference(cfg, params, tokens, 11, steps, key)
    _, out, n = _draft_spec(
        cfg, params, cfg, dparams, tokens, 11, steps, key, draft_len
    )
    assert int(n[0]) == int(ref_n[0])
    np.testing.assert_array_equal(
        np.asarray(out[0][: int(n[0])]), np.asarray(ref_out[0][: int(ref_n[0])])
    )


def test_perfect_draft_accepts_everything():
    """draft == target: every verify accepts the full draft (+ bonus when
    partial), so the loop runs ~steps/draft_len iterations — observable as
    identical output with full acceptance."""
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    ids, tokens = _ids(cfg, 9)
    key = jax.random.PRNGKey(1)
    steps = 20
    _, ref_out, ref_n = _greedy_reference(cfg, params, tokens, 9, steps, key)
    _, out, n = _draft_spec(cfg, params, cfg, params, tokens, 9, steps, key)
    assert int(n[0]) == int(ref_n[0])
    np.testing.assert_array_equal(
        np.asarray(out[0][: int(n[0])]), np.asarray(ref_out[0][: int(ref_n[0])])
    )


def test_draft_smaller_model():
    """A genuinely smaller draft (fewer layers/heads, same vocab) — the
    production shape — still produces the target's exact greedy tokens."""
    cfg = get_model_config("test-llama-tiny")
    dcfg = cfg.replace(n_layers=1, name="draft-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    dparams = M.init_params(dcfg, jax.random.PRNGKey(3))
    ids, tokens = _ids(cfg, 10)
    key = jax.random.PRNGKey(1)
    steps = 16
    _, ref_out, ref_n = _greedy_reference(cfg, params, tokens, 10, steps, key)
    _, out, n = _draft_spec(cfg, params, dcfg, dparams, tokens, 10, steps, key)
    assert int(n[0]) == int(ref_n[0])
    np.testing.assert_array_equal(
        np.asarray(out[0][: int(n[0])]), np.asarray(ref_out[0][: int(ref_n[0])])
    )


def test_engine_draft_end_to_end():
    """create_engine(draft_model=...) serves speculative requests through
    the draft path (envelope says so) and matches the plain greedy text,
    including across repeated requests (draft cache reuse) and a chunked
    prompt (draft-side extend ingest)."""
    dcfg = get_model_config("test-llama-tiny").replace(
        n_layers=1, name="draft-tiny"
    )
    engine = create_engine(
        "test-llama-tiny",
        engine_cfg=EngineConfig(prefill_buckets=(16, 32)),
        draft_model=dcfg,
    )
    # second prompt: ~41 tokens > the 32-token bucket -> chunked ingest on
    # both the target and draft caches (within max_seq_len 128)
    for prompt in ["hello tiny world", "a b c d e f g h i j " * 2]:
        plain = engine.generate(
            prompt, max_tokens=12, greedy=True, chat=False
        )
        spec = engine.generate(
            prompt, max_tokens=12, greedy=True, chat=False, speculative=True
        )
        assert spec["status"] == "success"
        assert spec["speculative"] is True
        assert spec["draft_model"] == "draft-tiny"
        assert spec["response"] == plain["response"], prompt
        assert spec["tokens_generated"] == plain["tokens_generated"]


def test_engine_draft_warmup_covers_draft_path():
    """warmup() on a draft-attached engine compiles the draft ingest +
    combined verify programs (the ones speculative requests actually
    run), and the engine serves correctly right after."""
    dcfg = get_model_config("test-llama-tiny").replace(
        n_layers=1, name="draft-tiny"
    )
    engine = create_engine(
        "test-llama-tiny",
        engine_cfg=EngineConfig(prefill_buckets=(16, 32)),
        draft_model=dcfg,
    )
    stats = engine.warmup(decode_buckets=(16,))
    assert stats["programs"] > 0
    spec = engine.generate(
        "after warm", max_tokens=6, greedy=True, chat=False, speculative=True
    )
    assert spec["status"] == "success"
    assert spec["draft_model"] == "draft-tiny"
    plain = engine.generate("after warm", max_tokens=6, greedy=True, chat=False)
    assert spec["response"] == plain["response"]


def test_engine_draft_vocab_mismatch_rejected():
    from distributed_llm_inference_tpu.engine.engine import InferenceEngine

    cfg = get_model_config("test-llama-tiny")
    eng = InferenceEngine(cfg)
    with pytest.raises(ValueError, match="vocab"):
        eng.set_draft(cfg.replace(vocab_size=cfg.vocab_size + 7))


def test_draft_stops_at_eos():
    """EOS inside an accepted window ends generation before the budget."""
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    ids, tokens = _ids(cfg, 8, seed=5)
    key = jax.random.PRNGKey(2)
    steps = 48
    _, ref_out, ref_n = _greedy_reference(cfg, params, tokens, 8, steps, key)
    _, out, n = _draft_spec(cfg, params, cfg, params, tokens, 8, steps, key)
    assert int(n[0]) == int(ref_n[0])
    # whatever the reference emitted (EOS-stopped or budget-stopped),
    # the speculative run emitted the same
    np.testing.assert_array_equal(
        np.asarray(out[0][: int(n[0])]), np.asarray(ref_out[0][: int(ref_n[0])])
    )
