"""Real-weights serving, end to end through the CLI (round-2 review #1).

The reference's core demo is serving actual checkpoint weights over HTTP
(/root/reference/orchestration.py:34-47 loads TinyLlama, Worker1.py:60-65
slices it). Here: save a tiny checkpoint store, launch the ACTUAL server
CLI (`python -m ...serving.server --checkpoint DIR --pp 2`) in a
subprocess on a 2-device CPU mesh, and verify /generate returns the same
greedy tokens an in-process engine produces from the same weights.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import jax
import pytest

from distributed_llm_inference_tpu import MeshConfig, create_engine
from distributed_llm_inference_tpu.models import api as M
from distributed_llm_inference_tpu.models import checkpoint as ckpt
from distributed_llm_inference_tpu.models.registry import get_model_config

pytestmark = pytest.mark.slow  # subprocess pays its own jit compile


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _post(url, payload, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _wait_healthy(port, proc, deadline_s=180):
    t0 = time.time()
    while time.time() - t0 < deadline_s:
        if proc.poll() is not None:
            out = proc.stdout.read().decode(errors="replace")
            raise AssertionError(f"server exited rc={proc.returncode}:\n{out}")
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health", timeout=5
            ) as r:
                if json.loads(r.read())["status"] == "healthy":
                    return
        except (urllib.error.URLError, OSError):
            time.sleep(0.5)
    raise AssertionError("server never became healthy")


def _spawn_server(extra_args, port, n_cpu_devices=2):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_cpu_devices}"
    return subprocess.Popen(
        [sys.executable, "-m", "distributed_llm_inference_tpu.serving.server",
         "--host", "127.0.0.1", "--port", str(port), *extra_args],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )


def test_serve_checkpoint_cli_pp2(tmp_path):
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(21))
    store = str(tmp_path / "store")
    ckpt.save_params(store, cfg, params)

    # expected greedy continuation from the same weights, in-process
    expected = create_engine(cfg, params=params).generate(
        "real weights", max_tokens=6, temperature=0.0, seed=0
    )

    port = _free_port()
    proc = _spawn_server(["--checkpoint", store, "--pp", "2"], port)
    try:
        _wait_healthy(port, proc)
        r = _post(
            f"http://127.0.0.1:{port}/generate",
            {"prompt": "real weights", "max_tokens": 6, "temperature": 0.0,
             "seed": 0},
            timeout=120,
        )
        assert r["status"] == "success"
        assert r["response"] == expected["response"]
        assert r["tokens_generated"] == expected["tokens_generated"]
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_bad_tokenizer_path_fails_loudly(tmp_path):
    """strict tokenizer loading: a mis-pointed --tokenizer must abort
    startup, not silently serve byte-garbled text (round-2 weak #6)."""
    cfg = get_model_config("test-llama-tiny")
    store = str(tmp_path / "store")
    ckpt.save_params(store, cfg, M.init_params(cfg, jax.random.PRNGKey(0)))
    port = _free_port()
    proc = _spawn_server(
        ["--checkpoint", store, "--tokenizer", str(tmp_path / "nope")], port,
        n_cpu_devices=1,
    )
    try:
        rc = proc.wait(timeout=120)
        assert rc != 0
    finally:
        if proc.poll() is None:
            proc.kill()


def test_bad_checkpoint_dir_fails_loudly(tmp_path):
    port = _free_port()
    proc = _spawn_server(
        ["--checkpoint", str(tmp_path / "empty_nothing")], port, n_cpu_devices=1
    )
    try:
        rc = proc.wait(timeout=120)
        out = proc.stdout.read().decode(errors="replace")
        assert rc != 0
        assert "neither a local store" in out
    finally:
        if proc.poll() is None:
            proc.kill()
