"""Deterministic beam search (engine/generate.decode_beam) vs HF
`generate(num_beams=N, do_sample=False)` — token-exact on tiny-random
models. Beyond-reference completeness: the reference only samples
(/root/reference/orchestration.py:168).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from distributed_llm_inference_tpu import EngineConfig, get_model_config
from distributed_llm_inference_tpu.engine import generate as G
from distributed_llm_inference_tpu.engine.engine import InferenceEngine
from distributed_llm_inference_tpu.models import api as M
from distributed_llm_inference_tpu.models.convert import params_from_hf_model


def _tiny_hf(seed=0):
    cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=10000.0,
        pad_token_id=0, eos_token_id=2, bos_token_id=1,
        attn_implementation="eager",
    )
    torch.manual_seed(seed)
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    return model


def _ours_beam(cfg, params, prompt_ids, steps, num_beams,
               length_penalty=1.0, early_stopping=False):
    bucket = 16
    row = prompt_ids + [cfg.pad_token_id] * (bucket - len(prompt_ids))
    tokens = jnp.asarray([row] * num_beams, jnp.int32)
    cache = M.init_kv_cache(cfg, num_beams, max_seq=64)
    sampling = G.default_sampling(greedy=True)
    _, logits, cache = G.prefill(
        cfg, params, tokens, jnp.int32(len(prompt_ids)), cache,
        jax.random.PRNGKey(0), sampling,
    )
    out, n_gen, scores, _ = G.decode_beam(
        cfg, params, logits, cache, jnp.int32(len(prompt_ids)),
        jnp.int32(steps), jnp.float32(length_penalty), max_steps=steps,
        num_beams=num_beams, early_stopping=early_stopping,
    )
    return [int(t) for t in np.asarray(out[0][: int(n_gen[0])])]


def _hf_beam(hf, prompt_ids, steps, num_beams, length_penalty=1.0,
             early_stopping=False):
    with torch.no_grad():
        seq = hf.generate(
            torch.tensor([prompt_ids]), max_new_tokens=steps,
            num_beams=num_beams, do_sample=False,
            length_penalty=length_penalty, early_stopping=early_stopping,
            pad_token_id=0,
        )[0, len(prompt_ids):].numpy().tolist()
    eos = hf.config.eos_token_id
    if eos in seq:
        seq = seq[: seq.index(eos)]
    while seq and seq[-1] == 0:  # HF right-pads shorter beam outputs
        seq = seq[:-1]
    return seq


@pytest.mark.parametrize("num_beams", [2, 4])
@pytest.mark.parametrize("early_stopping", [True, False])
@pytest.mark.slow
def test_beam_matches_hf(num_beams, early_stopping):
    hf = _tiny_hf()
    cfg, params = params_from_hf_model(hf, dtype="float32")
    rng = np.random.default_rng(5)
    prompt = rng.integers(3, cfg.vocab_size, size=7, dtype=np.int64).tolist()
    steps = 8
    want = _hf_beam(hf, prompt, steps, num_beams, early_stopping=early_stopping)
    got = _ours_beam(cfg, params, prompt, steps, num_beams,
                     early_stopping=early_stopping)
    assert got == want


@pytest.mark.parametrize("length_penalty", [0.5, 2.0])
@pytest.mark.slow
def test_beam_length_penalty_matches_hf(length_penalty):
    hf = _tiny_hf(seed=3)
    cfg, params = params_from_hf_model(hf, dtype="float32")
    rng = np.random.default_rng(9)
    prompt = rng.integers(3, cfg.vocab_size, size=6, dtype=np.int64).tolist()
    steps = 8
    want = _hf_beam(hf, prompt, steps, 3, length_penalty=length_penalty,
                    early_stopping=True)
    got = _ours_beam(cfg, params, prompt, steps, 3,
                     length_penalty=length_penalty, early_stopping=True)
    assert got == want


@pytest.mark.slow
def test_beam_beats_or_equals_greedy_score():
    """The best beam's sum-logprob must be >= the greedy path's (num_beams
    explores a superset of greedy's single path)."""
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    prompt = [5, 9, 13, 21]
    steps = 6
    bucket = 16
    row = prompt + [cfg.pad_token_id] * (bucket - len(prompt))
    sampling = G.default_sampling(greedy=True)

    def seq_logprob(token_ids):
        # score a generated continuation under the model, teacher-forced
        cache = M.init_kv_cache(cfg, 1, max_seq=64)
        toks = jnp.asarray([row], jnp.int32)
        _, logits, cache = G.prefill(
            cfg, params, toks, jnp.int32(len(prompt)), cache,
            jax.random.PRNGKey(0), sampling,
        )
        total, pos = 0.0, len(prompt)
        cur_logits = logits
        for t in token_ids:
            lp = jax.nn.log_softmax(cur_logits[0].astype(jnp.float32))
            total += float(lp[t])
            step_tok = jnp.asarray([[t]], jnp.int32)
            x = M.embed(cfg, params, step_tok, jnp.int32(pos))
            x, cache = M.forward_layers(
                cfg, params["layers"], x, cache, jnp.int32(pos)
            )
            cur_logits = M.unembed(cfg, params, x)[:, 0, :]
            pos += 1
        return total

    greedy_cache = M.init_kv_cache(cfg, 1, max_seq=64)
    toks1 = jnp.asarray([row], jnp.int32)
    f, _, greedy_cache = G.prefill(
        cfg, params, toks1, jnp.int32(len(prompt)), greedy_cache,
        jax.random.PRNGKey(0), sampling,
    )
    g_out, g_n, _ = G.decode(
        cfg, params, f, greedy_cache, jnp.int32(len(prompt)),
        jnp.int32(steps - 1), jax.random.PRNGKey(1), sampling,
        max_steps=steps,
    )
    greedy_ids = [int(f[0])] + [int(t) for t in np.asarray(g_out[0][: int(g_n[0])])]

    beam_ids = _ours_beam(cfg, params, prompt, steps, 4)
    if len(beam_ids) == len(greedy_ids):  # same length -> raw sums compare
        assert seq_logprob(beam_ids) >= seq_logprob(greedy_ids) - 1e-4


@pytest.mark.slow  # re-tiered round 5 (fast-tier budget)
def test_beam_engine_envelope():
    cfg = get_model_config("test-llama-tiny")
    eng = InferenceEngine(cfg, engine_cfg=EngineConfig(prefill_buckets=(32,)))
    r = eng.generate("beam me up", max_tokens=6, num_beams=3, chat=False)
    assert r["status"] == "success", r
    assert r["num_beams"] == 3
    assert len(r["beams"]) == 3
    assert r["beams"][0]["text"] == r["response"]
    # beams come back best-first
    scores = [b["score"] for b in r["beams"]]
    assert scores == sorted(scores, reverse=True)
    # deterministic: same request, same answer
    r2 = eng.generate("beam me up", max_tokens=6, num_beams=3, chat=False)
    assert r2["response"] == r["response"]


def test_beam_engine_rejects_bad_params():
    cfg = get_model_config("test-llama-tiny")
    eng = InferenceEngine(cfg, engine_cfg=EngineConfig(prefill_buckets=(32,)))
    r = eng.generate("x", max_tokens=4, num_beams=99, chat=False)
    assert r["status"] == "failed"
    assert r["error_type"] == "invalid_request"
