"""Logits parity: our JAX Llama vs a tiny-random HF LlamaForCausalLM.

This is the equivalence bar the reference never had (SURVEY.md §4): the HF
torch model is the behavioral spec for RMSNorm/RoPE/GQA/SwiGLU numerics and
for the converter's weight layout. Runs fully offline — the HF model is
built from a config, not downloaded.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from distributed_llm_inference_tpu.models import llama
from distributed_llm_inference_tpu.models.convert import params_from_hf_model


def _tiny_hf_llama(n_kv_heads: int):
    cfg = transformers.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=4,
        num_attention_heads=4,
        num_key_value_heads=n_kv_heads,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    return model


@pytest.mark.parametrize("n_kv_heads", [4, 2])  # MHA and GQA
@pytest.mark.slow
def test_logits_match_hf(n_kv_heads):
    hf = _tiny_hf_llama(n_kv_heads)
    cfg, params = params_from_hf_model(hf, dtype="float32")
    assert cfg.n_kv_heads == n_kv_heads

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(2, 17), dtype=np.int64)

    with torch.no_grad():
        hf_logits = hf(torch.from_numpy(tokens)).logits.numpy()

    cache = llama.init_kv_cache(cfg, batch=2, max_seq=32)
    logits, _ = llama.forward(
        cfg, params, jnp.asarray(tokens, jnp.int32), cache, jnp.int32(0)
    )
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_llama3_rope_scaling_logits_match_hf():
    """Llama-3.1/3.2 checkpoints ship "llama3" rope_scaling that HF applies
    to the RoPE frequencies at EVERY position; the converter must pick it up
    and the model must reproduce it or real 3.2 weights decode garbage."""
    cfg_hf = transformers.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=256,
        rms_norm_eps=1e-5,
        rope_theta=500000.0,
        rope_scaling={
            "rope_type": "llama3",
            "factor": 32.0,
            "low_freq_factor": 1.0,
            "high_freq_factor": 4.0,
            "original_max_position_embeddings": 64,
        },
        tie_word_embeddings=True,
        attn_implementation="eager",
    )
    torch.manual_seed(3)
    hf = transformers.LlamaForCausalLM(cfg_hf)
    hf.eval()
    cfg, params = params_from_hf_model(hf, dtype="float32")
    assert cfg.rope_scaling == "llama3" and cfg.rope_scaling_factor == 32.0
    assert cfg.rope_original_max_len == 64 and cfg.tie_embeddings

    rng = np.random.default_rng(7)
    # long enough that positions span all three scaling bands of the
    # original_max_position_embeddings=64 wavelength cutoffs
    tokens = rng.integers(0, cfg.vocab_size, size=(1, 96), dtype=np.int64)
    with torch.no_grad():
        hf_logits = hf(torch.from_numpy(tokens)).logits.numpy()
    cache = llama.init_kv_cache(cfg, batch=1, max_seq=128)
    logits, _ = llama.forward(
        cfg, params, jnp.asarray(tokens, jnp.int32), cache, jnp.int32(0)
    )
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=2e-4, atol=2e-4)


def test_unsupported_rope_scaling_rejected():
    """Non-llama3 scaling types must fail loudly at conversion, not silently
    produce a model with wrong frequencies."""
    from distributed_llm_inference_tpu.models.convert import config_from_hf

    cfg_hf = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
        rope_scaling={"rope_type": "yarn", "factor": 4.0},
    )
    with pytest.raises(ValueError, match="rope_scaling"):
        config_from_hf(cfg_hf)


@pytest.mark.slow
def test_qwen2_logits_match_hf():
    """Qwen2 family = llama arch + q/k/v biases + tied option; parity vs a
    tiny-random HF Qwen2ForCausalLM validates the bias path end to end."""
    cfg_hf = transformers.Qwen2Config(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rms_norm_eps=1e-6,
        rope_theta=1000000.0,
        tie_word_embeddings=False,
        use_sliding_window=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf = transformers.Qwen2ForCausalLM(cfg_hf)
    hf.eval()
    cfg, params = params_from_hf_model(hf, dtype="float32")
    assert cfg.attn_qkv_bias and cfg.attn_window is None
    assert "bq" in params["layers"]

    rng = np.random.default_rng(5)
    tokens = rng.integers(0, cfg.vocab_size, size=(2, 13), dtype=np.int64)
    with torch.no_grad():
        hf_logits = hf(torch.from_numpy(tokens)).logits.numpy()
    cache = llama.init_kv_cache(cfg, batch=2, max_seq=32)
    logits, _ = llama.forward(
        cfg, params, jnp.asarray(tokens, jnp.int32), cache, jnp.int32(0)
    )
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=2e-4, atol=2e-4)


@pytest.mark.slow  # re-tiered round 5: the engine greedy tests pin the
# same incremental-vs-full property through the serving path
def test_incremental_decode_matches_full_forward():
    """Prefill + T=1 decode steps through the KV cache must reproduce the
    full-sequence forward logits at every position (the property the
    reference forfeits by recomputing everything per token,
    /root/reference/Worker1.py:132-134)."""
    hf = _tiny_hf_llama(2)
    cfg, params = params_from_hf_model(hf, dtype="float32")
    rng = np.random.default_rng(1)
    T = 12
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(1, T)), jnp.int32)

    cache = llama.init_kv_cache(cfg, batch=1, max_seq=32)
    full_logits, _ = llama.forward(cfg, params, tokens, cache, jnp.int32(0))

    # prefill first 5, then decode one token at a time
    cache = llama.init_kv_cache(cfg, batch=1, max_seq=32)
    pre_logits, cache = llama.forward(cfg, params, tokens[:, :5], cache, jnp.int32(0))
    np.testing.assert_allclose(
        np.asarray(pre_logits), np.asarray(full_logits[:, :5]), rtol=1e-4, atol=1e-5
    )
    for t in range(5, T):
        step_logits, cache = llama.forward(
            cfg, params, tokens[:, t : t + 1], cache, jnp.int32(t)
        )
        np.testing.assert_allclose(
            np.asarray(step_logits[:, 0]),
            np.asarray(full_logits[:, t]),
            rtol=1e-4,
            atol=1e-5,
        )
