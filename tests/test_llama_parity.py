"""Logits parity: our JAX Llama vs a tiny-random HF LlamaForCausalLM.

This is the equivalence bar the reference never had (SURVEY.md §4): the HF
torch model is the behavioral spec for RMSNorm/RoPE/GQA/SwiGLU numerics and
for the converter's weight layout. Runs fully offline — the HF model is
built from a config, not downloaded.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from distributed_llm_inference_tpu.models import llama
from distributed_llm_inference_tpu.models.convert import params_from_hf_model


def _tiny_hf_llama(n_kv_heads: int):
    cfg = transformers.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=4,
        num_attention_heads=4,
        num_key_value_heads=n_kv_heads,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    return model


@pytest.mark.parametrize("n_kv_heads", [4, 2])  # MHA and GQA
def test_logits_match_hf(n_kv_heads):
    hf = _tiny_hf_llama(n_kv_heads)
    cfg, params = params_from_hf_model(hf, dtype="float32")
    assert cfg.n_kv_heads == n_kv_heads

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(2, 17), dtype=np.int64)

    with torch.no_grad():
        hf_logits = hf(torch.from_numpy(tokens)).logits.numpy()

    cache = llama.init_kv_cache(cfg, batch=2, max_seq=32)
    logits, _ = llama.forward(
        cfg, params, jnp.asarray(tokens, jnp.int32), cache, jnp.int32(0)
    )
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=2e-4, atol=2e-4)


def test_qwen2_logits_match_hf():
    """Qwen2 family = llama arch + q/k/v biases + tied option; parity vs a
    tiny-random HF Qwen2ForCausalLM validates the bias path end to end."""
    cfg_hf = transformers.Qwen2Config(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rms_norm_eps=1e-6,
        rope_theta=1000000.0,
        tie_word_embeddings=False,
        use_sliding_window=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf = transformers.Qwen2ForCausalLM(cfg_hf)
    hf.eval()
    cfg, params = params_from_hf_model(hf, dtype="float32")
    assert cfg.attn_qkv_bias and cfg.attn_window is None
    assert "bq" in params["layers"]

    rng = np.random.default_rng(5)
    tokens = rng.integers(0, cfg.vocab_size, size=(2, 13), dtype=np.int64)
    with torch.no_grad():
        hf_logits = hf(torch.from_numpy(tokens)).logits.numpy()
    cache = llama.init_kv_cache(cfg, batch=2, max_seq=32)
    logits, _ = llama.forward(
        cfg, params, jnp.asarray(tokens, jnp.int32), cache, jnp.int32(0)
    )
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=2e-4, atol=2e-4)


def test_incremental_decode_matches_full_forward():
    """Prefill + T=1 decode steps through the KV cache must reproduce the
    full-sequence forward logits at every position (the property the
    reference forfeits by recomputing everything per token,
    /root/reference/Worker1.py:132-134)."""
    hf = _tiny_hf_llama(2)
    cfg, params = params_from_hf_model(hf, dtype="float32")
    rng = np.random.default_rng(1)
    T = 12
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(1, T)), jnp.int32)

    cache = llama.init_kv_cache(cfg, batch=1, max_seq=32)
    full_logits, _ = llama.forward(cfg, params, tokens, cache, jnp.int32(0))

    # prefill first 5, then decode one token at a time
    cache = llama.init_kv_cache(cfg, batch=1, max_seq=32)
    pre_logits, cache = llama.forward(cfg, params, tokens[:, :5], cache, jnp.int32(0))
    np.testing.assert_allclose(
        np.asarray(pre_logits), np.asarray(full_logits[:, :5]), rtol=1e-4, atol=1e-5
    )
    for t in range(5, T):
        step_logits, cache = llama.forward(
            cfg, params, tokens[:, t : t + 1], cache, jnp.int32(t)
        )
        np.testing.assert_allclose(
            np.asarray(step_logits[:, 0]),
            np.asarray(full_logits[:, t]),
            rtol=1e-4,
            atol=1e-5,
        )
