"""OpenAI frequency/presence penalties (ops/sampling.apply_oai_penalties).

Semantics under test: logits -= freq_penalty * count + pres_penalty *
(count > 0), where counts cover GENERATED tokens only (the prompt is
excluded — OpenAI's published formula; the HF repetition penalty keeps
its separate prompt+output membership semantics). Applied pre-warper and
to the greedy argmax, on every topology that serves them (solo,
continuous fleet, pp mesh), with the same engine surface as every other
sampling knob.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_inference_tpu import EngineConfig, get_model_config
from distributed_llm_inference_tpu.engine.continuous import ContinuousEngine
from distributed_llm_inference_tpu.engine.engine import InferenceEngine
from distributed_llm_inference_tpu.ops.sampling import apply_oai_penalties

PROMPT = "the quick brown fox"


@pytest.fixture(scope="module")
def eng():
    cfg = get_model_config("test-llama-tiny")
    return InferenceEngine(
        cfg, engine_cfg=EngineConfig(prefill_buckets=(32, 64))
    )


def test_penalty_formula_exact():
    logits = jnp.asarray([[2.0, 1.0, 0.0, -1.0], [0.5, 0.5, 0.5, 0.5]])
    counts = jnp.asarray([[3, 0, 1, 0], [0, 2, 0, 0]], jnp.int32)
    got = np.asarray(apply_oai_penalties(logits, counts, 0.5, 0.7))
    want = np.asarray(
        [[2.0 - 1.5 - 0.7, 1.0, -0.5 - 0.7, -1.0],
         [0.5, 0.5 - 1.0 - 0.7, 0.5, 0.5]]
    )
    np.testing.assert_allclose(got, want, rtol=1e-6)
    # 0/0 disables exactly (bit-identical logits)
    off = np.asarray(apply_oai_penalties(logits, counts, 0.0, 0.0))
    np.testing.assert_array_equal(off, np.asarray(logits))
    # negative penalties ENCOURAGE repetition (OpenAI allows down to -2)
    enc = np.asarray(apply_oai_penalties(logits, counts, -0.5, 0.0))
    assert enc[0, 0] > float(logits[0, 0])


def _gen_ids(eng, out):
    return eng.tokenizer.encode(out["response"]) if out["response"] else []


def test_huge_presence_penalty_never_repeats(eng):
    """With a presence penalty far above any logit gap, greedy decode can
    never emit the same token twice — the defining property of the
    penalty, checked on the raw device token ids (solo decode loop)."""
    from distributed_llm_inference_tpu.engine import generate as G

    cfg, be = eng.cfg, eng.backend
    ids = eng.tokenizer.encode(PROMPT)
    plen = len(ids)
    tokens = jnp.asarray(
        [ids + [cfg.pad_token_id] * (32 - plen)], jnp.int32
    )
    sampling = G.default_sampling(greedy=True, pres_penalty=1000.0)
    cache = be.init_cache(1, 128)
    first, _, cache = be.prefill(
        tokens, jnp.int32(plen), cache, jax.random.PRNGKey(0), sampling
    )
    counts = G.count_update(
        jnp.zeros((1, cfg.vocab_size), jnp.int32), first.reshape(1)
    )
    out, n_gen, _ = be.decode(
        first, cache, jnp.int32(plen), jnp.int32(16),
        jax.random.PRNGKey(1), sampling, counts=counts, max_steps=16,
    )
    stream = [int(first[0])] + [int(t) for t in np.asarray(out[0])[: int(n_gen[0])]]
    assert len(stream) >= 8  # random-init tiny model should not EOS early
    assert len(stream) == len(set(stream))


def test_penalty_changes_greedy_stream(eng):
    base = eng.generate(PROMPT, greedy=True, chat=False, max_tokens=12)
    pen = eng.generate(
        PROMPT, greedy=True, chat=False, max_tokens=12,
        frequency_penalty=2.0, presence_penalty=2.0,
    )
    assert pen["status"] == "success"
    assert pen["response"] != base["response"]


@pytest.mark.slow
def test_penalty_disables_speculation(eng):
    """Speculative verify compares against the UNPENALIZED argmax — the
    engine must fall back to plain decode, emitting the penalized
    stream (same gate as repetition_penalty/logit_bias)."""
    plain = eng.generate(
        PROMPT, greedy=True, chat=False, max_tokens=12,
        frequency_penalty=1.5,
    )
    spec = eng.generate(
        PROMPT, greedy=True, chat=False, max_tokens=12,
        frequency_penalty=1.5, speculative=True,
    )
    assert spec["response"] == plain["response"]


@pytest.mark.slow
def test_continuous_matches_solo(eng):
    want = eng.generate(
        PROMPT, greedy=True, chat=False, max_tokens=12,
        frequency_penalty=1.0, presence_penalty=0.5,
    )
    cont = ContinuousEngine(eng, n_slots=2, chunk_steps=4, slot_max_seq=96)
    try:
        got = cont.submit(
            PROMPT, greedy=True, chat=False, max_tokens=12,
            frequency_penalty=1.0, presence_penalty=0.5,
        )
    finally:
        cont.close()
    assert got["status"] == "success"
    assert got["response"] == want["response"]


@pytest.mark.slow
def test_batched_matches_solo(eng):
    want = eng.generate(
        PROMPT, greedy=True, chat=False, max_tokens=10,
        frequency_penalty=1.0,
    )
    batch = eng.generate_batch(
        [PROMPT, "hello world"], greedy=True, chat=False, max_tokens=10,
        frequency_penalty=1.0,
    )
    assert batch["status"] == "success"
    assert batch["results"][0]["response"] == want["response"]


@pytest.mark.slow
def test_pp_mesh_matches_solo(eng, eight_devices):
    from distributed_llm_inference_tpu.parallel.mesh import MeshConfig
    from distributed_llm_inference_tpu.runtime import create_engine

    pp = create_engine(
        eng.cfg, mesh_cfg=MeshConfig(pp=2),
        engine_cfg=EngineConfig(prefill_buckets=(32, 64)),
        params=eng.backend.params,
    )
    want = eng.generate(
        PROMPT, greedy=True, chat=False, max_tokens=10,
        frequency_penalty=1.0, presence_penalty=0.5,
    )
    got = pp.generate(
        PROMPT, greedy=True, chat=False, max_tokens=10,
        frequency_penalty=1.0, presence_penalty=0.5,
    )
    assert got["status"] == "success"
    assert got["response"] == want["response"]


def test_openai_route_accepts_and_validates():
    """/v1/completions accepts in-range penalties and 400s out-of-range
    ones with the OpenAI error envelope."""
    from distributed_llm_inference_tpu.serving.openai_api import (
        OpenAIError, _common_kwargs, _reject_unsupported,
    )

    data = {"prompt": "x", "frequency_penalty": 1.5, "presence_penalty": -1.0}
    _reject_unsupported(data, chat=False)
    kw = _common_kwargs(data, cap=30)
    assert kw["frequency_penalty"] == 1.5
    assert kw["presence_penalty"] == -1.0
    with pytest.raises(OpenAIError, match="between"):
        _reject_unsupported({"frequency_penalty": 3.0}, chat=False)
    with pytest.raises(OpenAIError, match="between"):
        _reject_unsupported({"presence_penalty": -2.5}, chat=False)


def test_beam_plus_penalty_rejected(eng):
    """num_beams > 1 has no per-beam count tracking: combining it with a
    nonzero frequency/presence penalty must reject loudly (400 envelope),
    not silently return unpenalized output (advisor round-3)."""
    out = eng.generate(PROMPT, max_tokens=4, num_beams=2,
                       frequency_penalty=0.5)
    assert out["status"] == "failed"
    assert out.get("error_type") == "invalid_request"
    assert "num_beams" in out["error"]
    out = eng.generate(PROMPT, max_tokens=4, num_beams=2,
                       presence_penalty=-0.5)
    assert out["status"] == "failed"
    assert out.get("error_type") == "invalid_request"
