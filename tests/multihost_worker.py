"""Worker process for the 2-process jax.distributed integration test
(tests/test_multihost.py::test_two_process_pipelined_generate).

Each process contributes ONE virtual CPU device; jax.distributed joins
them into a 2-device global mesh (DCN analogue of the reference's two
ngrok-wired Colab workers, /root/reference/orchestration.py:22-24). The
checkpoint is restored with load_params_sharded, so each process mmap-
reads ONLY its own stage's layer pages — the multi-host loading story
the serving CLI uses, exercised for real across process boundaries.

Usage: multihost_worker.py <process_id> <coordinator_port> <ckpt_dir>
Prints one line: RESULT:{json}
"""

import json
import os
import sys

pid = int(sys.argv[1])
port = sys.argv[2]
ckpt_dir = sys.argv[3]

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
)
assert jax.device_count() == 2, jax.devices()
assert len(jax.local_devices()) == 1

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_llm_inference_tpu import MeshConfig, create_engine  # noqa: E402
from distributed_llm_inference_tpu.models.checkpoint import (  # noqa: E402
    load_params_sharded,
)
from distributed_llm_inference_tpu.parallel.mesh import build_mesh  # noqa: E402

mesh = build_mesh(MeshConfig(pp=2))
cfg, params = load_params_sharded(ckpt_dir, mesh)
engine = create_engine(cfg, mesh_cfg=MeshConfig(pp=2), params=params)
r = engine.generate("multi host hello", max_tokens=5, temperature=0.0, seed=0)
print(
    "RESULT:" + json.dumps({
        "pid": pid,
        "status": r["status"],
        "response": r.get("response"),
        "tokens": r.get("tokens_generated"),
        "n_devices": jax.device_count(),
    }),
    flush=True,
)
