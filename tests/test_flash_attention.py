"""Pallas flash attention vs the XLA einsum path (interpret mode on CPU).

The XLA `attend` is itself verified against HF numerics by the parity
tests, so flash == attend pins the kernel to the same spec."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llm_inference_tpu.ops.attention import attend, causal_mask
from distributed_llm_inference_tpu.ops.flash_attention import flash_attend


@pytest.mark.parametrize(
    "B,T,H,KV,Dh,S,pos",
    [
        (2, 16, 8, 2, 64, 64, 0),  # GQA prefill at 0
        (1, 16, 8, 2, 64, 64, 13),  # GQA chunk mid-sequence
        (2, 1, 8, 2, 64, 64, 17),  # GQA decode
        (2, 7, 4, 4, 32, 64, 5),  # MHA, ragged T vs block sizes
        (1, 1, 4, 4, 128, 256, 255),  # decode at the last cache slot
        (1, 5, 2, 1, 16, 32, 3),  # 1 kv head (max group fan-in)
    ],
)
@pytest.mark.slow
def test_flash_matches_xla_attend(B, T, H, KV, Dh, S, pos):
    ks = jax.random.split(jax.random.PRNGKey(B * T + H + pos), 3)
    q = jax.random.normal(ks[0], (B, T, H, Dh), jnp.float32)
    ck = jax.random.normal(ks[1], (B, KV, S, Dh), jnp.float32)
    cv = jax.random.normal(ks[2], (B, KV, S, Dh), jnp.float32)
    p = jnp.int32(pos)
    ref = attend(q, ck, cv, causal_mask(p, T, S))
    got = flash_attend(q, ck, cv, p)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=2e-5)


@pytest.mark.parametrize("block_t,block_k", [(4, 16), (16, 32), (3, 8)])
def test_flash_block_size_invariance(block_t, block_k):
    """Output must not depend on tiling choices (incl. non-dividing tiles)."""
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    B, T, H, KV, Dh, S, pos = 1, 10, 4, 2, 32, 64, 7
    q = jax.random.normal(ks[0], (B, T, H, Dh), jnp.float32)
    ck = jax.random.normal(ks[1], (B, KV, S, Dh), jnp.float32)
    cv = jax.random.normal(ks[2], (B, KV, S, Dh), jnp.float32)
    p = jnp.int32(pos)
    ref = attend(q, ck, cv, causal_mask(p, T, S))
    got = flash_attend(q, ck, cv, p, block_t=block_t, block_k=block_k)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=2e-5)


@pytest.mark.parametrize("model", ["test-llama-tiny", "test-gpt2-tiny"])
@pytest.mark.slow
def test_model_forward_pallas_equals_xla(model):
    """Full-model logits identical under attn_impl='pallas' vs 'xla'."""
    from distributed_llm_inference_tpu.models import api as M
    from distributed_llm_inference_tpu.models.registry import get_model_config

    cfg_x = get_model_config(model)
    cfg_p = cfg_x.replace(attn_impl="pallas")
    params = M.init_params(cfg_x, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 3, cfg_x.vocab_size)
    tokens = tokens.astype(jnp.int32)

    def run(cfg):
        cache = M.init_kv_cache(cfg, 2, max_seq=32)
        logits, cache = M.forward(cfg, params, tokens, cache, jnp.int32(0))
        # one decode step on top of the prefilled cache
        step = tokens[:, -1:]
        logits2, _ = M.forward(cfg, params, step, cache, jnp.int32(12))
        return logits, logits2

    lx, lx2 = run(cfg_x)
    lp, lp2 = run(cfg_p)
    np.testing.assert_allclose(np.asarray(lp), np.asarray(lx), rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(np.asarray(lp2), np.asarray(lx2), rtol=1e-5, atol=1e-4)


@pytest.mark.slow
def test_flash_ragged_valid_start_matches_masked_attend():
    """Per-row valid_start (left-padded batch) in the kernel == 3D-mask XLA."""
    from distributed_llm_inference_tpu.ops.attention import ragged_causal_mask

    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    B, T, H, KV, Dh, S = 3, 8, 4, 2, 32, 32
    q = jax.random.normal(ks[0], (B, T, H, Dh), jnp.float32)
    ck = jax.random.normal(ks[1], (B, KV, S, Dh), jnp.float32)
    cv = jax.random.normal(ks[2], (B, KV, S, Dh), jnp.float32)
    p = jnp.int32(0)
    vs = jnp.asarray([0, 3, 6], jnp.int32)
    ref = np.asarray(attend(q, ck, cv, ragged_causal_mask(p, T, S, vs)))
    got = np.asarray(flash_attend(q, ck, cv, p, vs))
    # pad-QUERY rows (t < vs[b]) are garbage by design in both paths (their
    # mask row is empty; the two impls fill differently) — compare only the
    # real query rows, which is all the model ever reads.
    for b in range(B):
        lo = int(vs[b])
        np.testing.assert_allclose(
            got[b, lo:], ref[b, lo:], rtol=1e-5, atol=2e-5
        )


@pytest.mark.slow
def test_model_forward_pallas_ragged_batch():
    """Batched ragged prefill+decode: pallas == xla end to end."""
    from distributed_llm_inference_tpu.engine import generate as G
    from distributed_llm_inference_tpu.models import api as M
    from distributed_llm_inference_tpu.models.registry import get_model_config

    def run(cfg):
        params = M.init_params(cfg, jax.random.PRNGKey(0))
        pad = cfg.pad_token_id
        rows = [[5, 6, 7], [8, 9, 10, 11, 12, 13]]
        bucket = 8
        tokens = jnp.asarray(
            [[pad] * (bucket - len(r)) + r for r in rows], jnp.int32
        )
        vs = jnp.asarray([bucket - len(r) for r in rows], jnp.int32)
        sampling = G.default_sampling(greedy=True)
        kp, kd = jax.random.split(jax.random.PRNGKey(4))
        cache = M.init_kv_cache(cfg, 2, max_seq=32)
        first, logits, cache = G.prefill(
            cfg, params, tokens, jnp.int32(bucket), cache, kp, sampling, vs
        )
        out, n, _ = G.decode(
            cfg, params, first, cache, jnp.int32(bucket), jnp.int32(4),
            kd, sampling, vs, max_steps=4,
        )
        return np.asarray(first), np.asarray(logits), np.asarray(out)

    cfg_x = get_model_config("test-llama-tiny")
    fx, lx, ox = run(cfg_x)
    fp, lp_, op = run(cfg_x.replace(attn_impl="pallas"))
    np.testing.assert_allclose(lp_, lx, rtol=1e-4, atol=1e-4)
    assert fp.tolist() == fx.tolist() and op.tolist() == ox.tolist()


@pytest.mark.slow  # re-tiered round 5: fast tier budget (4 min); the
# mixed-window model tests below pin the same kernel features end to end
def test_pallas_scale_softcap_window_dyn_match_xla():
    """Round-5: the chunk kernel covers score-scale overrides (Gemma query
    scaling, Granite attention_multiplier), Gemma-2 softcapping, and a
    TRACED per-layer window width (window_dyn, the scalar-prefetch operand
    mixed patterns feed from the scan) — each must match the XLA attend
    exactly, and the dynamic-window spelling must match the static one."""
    from distributed_llm_inference_tpu.ops.attention import causal_mask

    B, T, H, KV, Dh, S = 2, 8, 4, 2, 8, 24
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(k1, (B, T, H, Dh), jnp.float32)
    ck = jax.random.normal(k2, (B, KV, S, Dh), jnp.float32)
    cv = jax.random.normal(k3, (B, KV, S, Dh), jnp.float32)
    pos = jnp.int32(5)
    for W, sc, cap in [
        (3, None, None),       # window only
        (3, 0.3, 10.0),        # window + scale override + softcap
        (None, 0.25, 5.0),     # full causal + overrides
    ]:
        ref = np.asarray(attend(
            q, ck, cv, causal_mask(pos, T, S, W), scale=sc, softcap=cap
        ))
        got = np.asarray(flash_attend(
            q, ck, cv, pos, window=W, scale=sc, softcap=cap, interpret=True
        ))
        np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5, err_msg=str((W, sc, cap)))
        got_dyn = np.asarray(flash_attend(
            q, ck, cv, pos, None, jnp.int32(W if W else -1),
            scale=sc, softcap=cap, interpret=True,
        ))
        np.testing.assert_allclose(got_dyn, ref, rtol=2e-5, atol=2e-5, err_msg=str((W, sc, cap)))


@pytest.mark.slow  # re-tiered round 5: fast-tier budget
@pytest.mark.parametrize("name", ["test-gemma2-tiny", "test-gemma3-tiny"])
def test_pallas_mixed_window_models_match_xla(name):
    """Gemma-2 (softcap + even-pattern windows + query scaling) and
    Gemma-3 (layer-type windows + dual RoPE) run under attn_impl='pallas':
    per-layer widths ride the kernel's window_dyn operand, softcap and the
    scale override are static kernel params. Prefill logits and greedy
    decode must match the XLA path."""
    from distributed_llm_inference_tpu import get_model_config
    from distributed_llm_inference_tpu.engine import generate as G
    from distributed_llm_inference_tpu.models import api as M

    # window=4 so the sliding layers actually bind inside a 12-token prompt
    cfg_x = get_model_config(name, eos_token_id=-1).replace(attn_window=4)
    params = M.init_params(cfg_x, jax.random.PRNGKey(1))
    tokens = jnp.asarray([[cfg_x.bos_token_id] + [7, 9, 11, 13, 5, 8] * 2], jnp.int32)
    plen = jnp.int32(tokens.shape[1])
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(2))

    def run(cfg):
        cache = M.init_kv_cache(cfg, 1, max_seq=32)
        first, logits, cache = G.prefill(cfg, params, tokens, plen, cache, kp, sampling)
        out, n, _ = G.decode(
            cfg, params, first, cache, plen, jnp.int32(4), kd, sampling,
            max_steps=4,
        )
        return np.asarray(first), np.asarray(logits), np.asarray(out)

    fx, lx, ox = run(cfg_x)
    fp, lp_, op = run(cfg_x.replace(attn_impl="pallas"))
    np.testing.assert_allclose(lp_, lx, rtol=1e-4, atol=1e-4)
    assert fp.tolist() == fx.tolist() and op.tolist() == ox.tolist()


@pytest.mark.slow
def test_pallas_serves_prefill_only_never_decode(monkeypatch):
    """Regression pin for the T>1 gate: under attn_impl='pallas' the flash
    kernel must trace into prefill (T=bucket) but NEVER into a T=1 decode
    step — the kernel inside the decode loop measured 15x slower than the
    XLA einsum on v5e, so 'auto'/'pallas' must stay prefill-only there."""
    from distributed_llm_inference_tpu import EngineConfig, get_model_config
    from distributed_llm_inference_tpu.engine.engine import InferenceEngine
    from distributed_llm_inference_tpu.models import llama as L

    calls = []
    orig = L.flash_attend

    def spy(q, *a, **k):
        calls.append(int(q.shape[1]))
        return orig(q, *a, **k)

    monkeypatch.setattr(L, "flash_attend", spy)
    # max_seq_len tweak -> a cfg no other test compiled, so THIS process
    # traces the programs fresh and the spy actually observes the calls
    cfg = get_model_config(
        "test-llama-tiny", attn_impl="pallas", max_seq_len=120
    )
    eng = InferenceEngine(cfg, engine_cfg=EngineConfig(prefill_buckets=(32,)))
    out = eng.generate("the quick brown fox", greedy=True, chat=False,
                       max_tokens=8)
    assert out["status"] == "success"
    assert calls, "prefill under pallas should trace through flash_attend"
    assert all(t > 1 for t in calls), f"flash traced at T=1 decode: {calls}"
