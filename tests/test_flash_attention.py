"""Pallas flash attention vs the XLA einsum path (interpret mode on CPU).

The XLA `attend` is itself verified against HF numerics by the parity
tests, so flash == attend pins the kernel to the same spec."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llm_inference_tpu.ops.attention import attend, causal_mask
from distributed_llm_inference_tpu.ops.flash_attention import flash_attend


@pytest.mark.parametrize(
    "B,T,H,KV,Dh,S,pos",
    [
        (2, 16, 8, 2, 64, 64, 0),  # GQA prefill at 0
        (1, 16, 8, 2, 64, 64, 13),  # GQA chunk mid-sequence
        (2, 1, 8, 2, 64, 64, 17),  # GQA decode
        (2, 7, 4, 4, 32, 64, 5),  # MHA, ragged T vs block sizes
        (1, 1, 4, 4, 128, 256, 255),  # decode at the last cache slot
        (1, 5, 2, 1, 16, 32, 3),  # 1 kv head (max group fan-in)
    ],
)
def test_flash_matches_xla_attend(B, T, H, KV, Dh, S, pos):
    ks = jax.random.split(jax.random.PRNGKey(B * T + H + pos), 3)
    q = jax.random.normal(ks[0], (B, T, H, Dh), jnp.float32)
    ck = jax.random.normal(ks[1], (B, KV, S, Dh), jnp.float32)
    cv = jax.random.normal(ks[2], (B, KV, S, Dh), jnp.float32)
    p = jnp.int32(pos)
    ref = attend(q, ck, cv, causal_mask(p, T, S))
    got = flash_attend(q, ck, cv, p)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=2e-5)


@pytest.mark.parametrize("block_t,block_k", [(4, 16), (16, 32), (3, 8)])
def test_flash_block_size_invariance(block_t, block_k):
    """Output must not depend on tiling choices (incl. non-dividing tiles)."""
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    B, T, H, KV, Dh, S, pos = 1, 10, 4, 2, 32, 64, 7
    q = jax.random.normal(ks[0], (B, T, H, Dh), jnp.float32)
    ck = jax.random.normal(ks[1], (B, KV, S, Dh), jnp.float32)
    cv = jax.random.normal(ks[2], (B, KV, S, Dh), jnp.float32)
    p = jnp.int32(pos)
    ref = attend(q, ck, cv, causal_mask(p, T, S))
    got = flash_attend(q, ck, cv, p, block_t=block_t, block_k=block_k)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=2e-5)


@pytest.mark.parametrize("model", ["test-llama-tiny", "test-gpt2-tiny"])
def test_model_forward_pallas_equals_xla(model):
    """Full-model logits identical under attn_impl='pallas' vs 'xla'."""
    from distributed_llm_inference_tpu.models import api as M
    from distributed_llm_inference_tpu.models.registry import get_model_config

    cfg_x = get_model_config(model)
    cfg_p = cfg_x.replace(attn_impl="pallas")
    params = M.init_params(cfg_x, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 3, cfg_x.vocab_size)
    tokens = tokens.astype(jnp.int32)

    def run(cfg):
        cache = M.init_kv_cache(cfg, 2, max_seq=32)
        logits, cache = M.forward(cfg, params, tokens, cache, jnp.int32(0))
        # one decode step on top of the prefilled cache
        step = tokens[:, -1:]
        logits2, _ = M.forward(cfg, params, step, cache, jnp.int32(12))
        return logits, logits2

    lx, lx2 = run(cfg_x)
    lp, lp2 = run(cfg_p)
    np.testing.assert_allclose(np.asarray(lp), np.asarray(lx), rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(np.asarray(lp2), np.asarray(lx2), rtol=1e-5, atol=1e-4)
