"""KV fabric + prefill/decode disaggregation suite (serving/kv_fabric.py,
the /kv surface in serving/server.py, the continuous engine's remote-hit
path, and the router's two-phase handoff).

Layers:
  * wire-format units: encode/decode round trip, the content-key recheck
    (tampered tokens, wrong digest, truncation, block-size drift all
    REJECT — cold prefill, never wrong KV);
  * shadow digest index units (engine/shadow.py): O(1) digest lookups,
    chain export ordering, eviction hygiene;
  * engine-level remote hits over real HTTP: a replica that misses a
    prefix pulls the chain from the resident peer and its greedy output
    is bit-identical to a local cold run — plus every rung of the
    fallback ladder (dead peer, wedged peer under the fetch deadline,
    corrupt payload) degrading to that same cold-run output;
  * router units: residency purge on ejection, the byte->token digest
    bridge that steers fabric pulls;
  * full-stack disaggregation (chaos, real subprocess replicas): fresh
    long-prompt work prefilled on the prefill-class replica, decoded on
    the decode-class one after a fabric pull — greedy bit-identical to
    single-replica serving, streaming included, and kill -9 of the
    prefill replica mid-handoff degrades to a local re-prefill with the
    SAME bytes out.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from distributed_llm_inference_tpu import create_engine
from distributed_llm_inference_tpu.config import EngineConfig
from distributed_llm_inference_tpu.engine.continuous import ContinuousEngine
from distributed_llm_inference_tpu.engine.shadow import ShadowStore
from distributed_llm_inference_tpu.serving import kv_fabric as KF
from distributed_llm_inference_tpu.serving.router import (
    EJECTED,
    Replica,
    Router,
    RouterServer,
    spawn_replicas,
)
from distributed_llm_inference_tpu.serving.server import InferenceServer

BS = 16  # kv block size everywhere in this file


# -- wire-format units --------------------------------------------------------

class _E:
    """Minimal stand-in for a ShadowStore entry (.leaves contract)."""

    def __init__(self, leaves):
        self.leaves = leaves


def _chain(n_blocks: int, bs: int = 4, base: int = 1):
    ids = [(base + i) % 250 + 1 for i in range(n_blocks * bs)]
    keys = [tuple(ids[: (i + 1) * bs]) for i in range(n_blocks)]
    entries = [
        _E([
            np.full((2, 3), i, np.float32),
            (np.arange(6, dtype=np.int8) + i).reshape(2, 3),
        ])
        for i in range(n_blocks)
    ]
    return ids, keys, entries


def test_wire_roundtrip():
    ids, keys, entries = _chain(3)
    data = KF.encode_chain(4, keys, entries)
    digest = KF.chain_digest(ids, 4)
    keys2, per_block = KF.decode_chain(data, 4, digest)
    assert keys2 == keys
    assert len(per_block) == 3
    for i in range(3):
        for j in range(2):
            np.testing.assert_array_equal(
                per_block[i][j], entries[i].leaves[j]
            )
            assert per_block[i][j].dtype == entries[i].leaves[j].dtype


def test_wire_rejects_wrong_digest():
    ids, keys, entries = _chain(3)
    data = KF.encode_chain(4, keys, entries)
    other = KF.chain_digest([9] * 12, 4)
    with pytest.raises(KF.FabricPayloadError, match="content-key recheck"):
        KF.decode_chain(data, 4, other)


def test_wire_rejects_tampered_tokens():
    """A peer answering with a DIFFERENT prefix under the requested
    digest (bitrot, a buggy peer, an impostor) fails the recheck."""
    ids, keys, entries = _chain(3)
    digest = KF.chain_digest(ids, 4)
    ids2 = list(ids)
    ids2[5] = (ids2[5] % 250) + 1  # one token off
    keys2 = [tuple(ids2[: (i + 1) * 4]) for i in range(3)]
    data = KF.encode_chain(4, keys2, entries)
    with pytest.raises(KF.FabricPayloadError, match="content-key recheck"):
        KF.decode_chain(data, 4, digest)


def test_wire_rejects_block_size_drift_and_garbage():
    ids, keys, entries = _chain(2)
    data = KF.encode_chain(4, keys, entries)
    with pytest.raises(KF.FabricPayloadError, match="block_size"):
        KF.decode_chain(data, 8, KF.chain_digest(ids, 4))
    with pytest.raises(KF.FabricPayloadError):
        KF.decode_chain(data[: len(data) // 2], 4, KF.chain_digest(ids, 4))
    with pytest.raises(KF.FabricPayloadError):
        KF.decode_chain(b"not an npz at all", 4, "ab12")


def test_valid_digest_gate():
    assert KF.valid_digest("0123abcdef")
    assert not KF.valid_digest("")
    assert not KF.valid_digest("../etc/passwd")
    assert not KF.valid_digest("A" * 20)  # uppercase never emitted
    assert not KF.valid_digest("a" * 65)


# -- shadow digest index units -----------------------------------------------

def test_shadow_digest_index_and_chain_export():
    st = ShadowStore(4, max_blocks=16)
    try:
        ids, keys, entries = _chain(4)
        st.put_host(keys, [e.leaves for e in entries], seq=7)
        digests = st.resident_digests()
        assert len(digests) == 4
        deep = st.digest_of(keys[-1])
        assert deep in digests
        got = st.chain_for_digest(deep)
        assert got is not None
        got_keys, got_entries = got
        assert got_keys == keys  # parents first
        np.testing.assert_array_equal(
            got_entries[2].leaves[0], entries[2].leaves[0]
        )
        # O(1) misses: unknown digest and structurally-invalid digest
        assert st.chain_for_digest("deadbeef00") is None
        # wire round trip straight off the store (the /kv body)
        data = KF.serve_chain(st, deep)
        assert data is not None
        keys2, _ = KF.decode_chain(data, 4, deep)
        assert keys2 == keys
        assert KF.serve_chain(st, "deadbeef00") is None
        assert KF.serve_chain(st, "../escape") is None
    finally:
        st.close()


def test_shadow_digest_index_tracks_eviction_and_clear():
    st = ShadowStore(4, max_blocks=4)
    try:
        _, keys_a, entries_a = _chain(4, base=1)
        st.put_host(keys_a, [e.leaves for e in entries_a], seq=0)
        deep_a = st.digest_of(keys_a[-1])
        assert st.chain_for_digest(deep_a) is not None
        # a second chain LRU-evicts the first; its digests must go too
        _, keys_b, entries_b = _chain(4, base=101)
        st.put_host(keys_b, [e.leaves for e in entries_b], seq=1)
        assert st.chain_for_digest(deep_a) is None
        assert st.chain_for_digest(st.digest_of(keys_b[-1])) is not None
        st.clear()
        assert st.resident_digests() == []
    finally:
        st.close()


# -- engine-level remote hits over real HTTP ---------------------------------

# >= 6 full 16-token blocks under the byte tokenizer, well inside the
# tiny model's 128-token window with max_tokens 10
PROMPT_A = "shared fabric preamble " * 4 + "tail one"
assert 96 <= len(PROMPT_A) <= 112

GEN = dict(max_tokens=10, greedy=True, chat=False)


def _mk_replica(cls, timeout_s=5.0, **cfg_kw):
    eng = create_engine(
        "test-llama-tiny",
        engine_cfg=EngineConfig(
            prefix_cache_entries=8, replica_class=cls,
            kv_fabric_timeout_s=timeout_s, **cfg_kw,
        ),
    )
    cont = ContinuousEngine(
        eng, n_slots=2, chunk_steps=4,
        kv_pool_blocks=48, kv_block_size=BS,
    )
    srv = InferenceServer(eng, "127.0.0.1", 0, max_tokens_cap=64,
                          continuous=cont)
    srv.start()
    return eng, cont, srv, f"http://127.0.0.1:{srv.port}"


@pytest.fixture(scope="module")
def ref_engine():
    return create_engine("test-llama-tiny")


@pytest.fixture(scope="module")
def holder():
    """Replica A: serves PROMPT_A once so its chain is shadow-resident,
    then acts as the fabric peer for every fetch test."""
    eng, cont, srv, url = _mk_replica("prefill")
    out = cont.submit(PROMPT_A, **GEN)
    assert out["status"] == "success"
    assert cont._shadow.flush(10.0)
    yield eng, cont, srv, url, out
    srv.shutdown()


def test_kv_http_roundtrip_and_404(holder):
    _, cont, _, url, out = holder
    digest = out["kv_digests"][-1]
    with urllib.request.urlopen(f"{url}/kv/{digest}", timeout=10) as r:
        assert r.status == 200
        assert r.headers["Content-Type"] == "application/octet-stream"
        assert int(r.headers["X-KV-Block-Size"]) == BS
        data = r.read()
    keys, per_block = KF.decode_chain(data, BS, digest)
    assert len(keys) == len(out["kv_digests"]) >= 6
    assert len(per_block) == len(keys)
    # digest miss -> 404 (the fetcher's "prefill locally" signal)
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(f"{url}/kv/{'0' * 20}", timeout=10)
    assert ei.value.code == 404
    # /health exposes the residency bootstrap surface
    with urllib.request.urlopen(f"{url}/health", timeout=10) as r:
        h = json.loads(r.read())
    assert h["replica_class"] == "prefill"
    assert digest in h["kv"]["resident_digests"]
    assert h["kv"]["block_size"] == BS


def test_remote_hit_bit_identical_to_local_cold(holder, ref_engine):
    """THE fabric acceptance property: a replica that has never seen
    PROMPT_A pulls the chain from the holder and produces byte-identical
    greedy output to a cold local run — and actually reused the prefix
    (imported blocks, exact-depth block-prefix hit, one fabric hit)."""
    _, _, _, peer_url, out = holder
    ref = ref_engine.generate(PROMPT_A, **GEN)
    _, cont_b, srv_b, _ = _mk_replica("decode")
    try:
        got = cont_b.submit(
            PROMPT_A, **GEN,
            kv_hint={"peer": peer_url, "digest": out["kv_digests"][-1]},
        )
        assert got["status"] == "success"
        assert got["response"] == ref["response"]
        assert got["tokens_generated"] == ref["tokens_generated"]
        assert got["kv_fabric_blocks"] >= 6
        assert got["prefix_cached_tokens"] >= 6 * BS
        st = cont_b.stats()["kv_fabric"]
        assert st["role"] == "decode"
        assert (st["fetches"], st["hits"], st["misses"]) == (1, 1, 0)
        assert st["bytes"] > 0
        # the fetched chain is onward-servable: B now answers /kv too
        assert out["kv_digests"][-1] in cont_b.fabric_digests()
    finally:
        srv_b.shutdown()


def test_dead_peer_degrades_to_cold_bit_identical(holder, ref_engine):
    _, _, _, _, out = holder
    ref = ref_engine.generate(PROMPT_A, **GEN)
    dead = f"http://127.0.0.1:{_free_port()}"  # nothing listens here
    _, cont_b, srv_b, _ = _mk_replica("decode", timeout_s=2.0)
    try:
        got = cont_b.submit(
            PROMPT_A, **GEN,
            kv_hint={"peer": dead, "digest": out["kv_digests"][-1]},
        )
        assert got["status"] == "success"
        assert got["response"] == ref["response"]
        assert "kv_fabric_blocks" not in got
        st = cont_b.stats()["kv_fabric"]
        assert (st["fetches"], st["hits"], st["misses"]) == (1, 0, 1)
    finally:
        srv_b.shutdown()


def test_wedged_peer_times_out_inside_deadline(holder, ref_engine):
    """A peer that ACCEPTS but never answers (wedged runtime) costs at
    most kv_fabric_timeout_s, then admission prefills locally — the
    deadline'd rung of the fallback ladder."""
    _, _, _, _, out = holder
    ref = ref_engine.generate(PROMPT_A, **GEN)
    wedge = socket.socket()
    wedge.bind(("127.0.0.1", 0))
    wedge.listen(4)
    wedge_url = f"http://127.0.0.1:{wedge.getsockname()[1]}"
    _, cont_b, srv_b, _ = _mk_replica("decode", timeout_s=0.5)
    try:
        t0 = time.perf_counter()
        got = cont_b.submit(
            PROMPT_A, **GEN,
            kv_hint={"peer": wedge_url, "digest": out["kv_digests"][-1]},
        )
        elapsed = time.perf_counter() - t0
        assert got["status"] == "success"
        assert got["response"] == ref["response"]
        assert "kv_fabric_blocks" not in got
        st = cont_b.stats()["kv_fabric"]
        assert st["misses"] == 1
        # 0.5s fetch deadline + the request's own work; generous bound
        # so slow CI never flakes, but a hung fetch (no deadline) would
        # blow way past it
        assert elapsed < 30.0
    finally:
        srv_b.shutdown()
        wedge.close()


def test_corrupt_payload_rejected_then_cold(holder, ref_engine):
    """A peer serving garbage under a valid digest fails the content-key
    recheck client-side; the request still completes cold."""
    _, _, _, _, out = holder
    ref = ref_engine.generate(PROMPT_A, **GEN)

    class Garbage(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_GET(self):
            body = b"\x00garbage, definitely not an npz"
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Garbage)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    _, cont_b, srv_b, _ = _mk_replica("decode", timeout_s=2.0)
    try:
        got = cont_b.submit(
            PROMPT_A, **GEN,
            kv_hint={
                "peer": f"http://127.0.0.1:{httpd.server_address[1]}",
                "digest": out["kv_digests"][-1],
            },
        )
        assert got["status"] == "success"
        assert got["response"] == ref["response"]
        assert "kv_fabric_blocks" not in got
        assert cont_b.stats()["kv_fabric"]["misses"] == 1
    finally:
        srv_b.shutdown()
        httpd.shutdown()
        httpd.server_close()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# -- router units ------------------------------------------------------------

def _stub_router(n=2, **kw):
    kw.setdefault("probe_interval_s", 3600.0)
    kw.setdefault("eject_threshold", 3)
    reps = [
        Replica(f"r{i}", f"http://127.0.0.1:{9000 + i}") for i in range(n)
    ]
    return Router(reps, **kw), reps


def test_residency_purged_on_ejection():
    """Satellite: digests pointing at an EJECTED replica must not
    survive the ejection and steer traffic (or fabric pulls) at a
    corpse."""
    router, (r0, r1) = _stub_router()
    router.record_residency(["b1", "b2"], "r0", token_digest="t0deep")
    router.record_residency(["b3"], "r1", token_digest="t1deep")
    router.record_kv_residency(["t0deep", "t0mid"], "r0")
    router.record_kv_residency(["t1deep"], "r1")
    assert router.residency_entries() == 3
    assert router.kv_residency_entries() == 3
    for _ in range(3):
        router.note_failure(r0, why="test")
    assert r0.state == EJECTED
    assert router.residency_entries() == 1  # only r1's byte entry
    assert router.kv_residency_entries() == 1
    # and the survivor's entries still route
    rep, _ = router.pick("x")
    assert rep is r1


def test_kv_hint_bridges_bytes_to_token_digest():
    router, (r0, r1) = _stub_router()
    key = "shared preamble " * 8
    import distributed_llm_inference_tpu.engine.block_prefix as BP

    digests = BP.chunk_digests(key, router.affinity_chunk, 32)
    router.record_residency(digests, "r0", token_digest="feedbead01")
    # dispatching to the holder needs no hint
    assert router._kv_hint(digests, r0) is None
    hint = router._kv_hint(digests, r1)
    assert hint == {
        "X-KV-Transfer-Peer": r0.url,
        "X-KV-Transfer-Digest": "feedbead01",
    }
    # a same-replica re-serve without digests keeps the token bridge
    router.record_residency(digests, "r0")
    assert router._kv_hint(digests, r1) is not None
    # a failover to r1 moves residency and drops the stale bridge
    router.record_residency(digests, "r1")
    assert router._kv_hint(digests, r0) is None


def test_candidate_roles_prefer_specialization_not_availability():
    router, reps = _stub_router(3)
    reps[0].replica_class = "prefill"
    reps[1].replica_class = "decode"
    reps[2].replica_class = "mixed"
    decode = router._candidates((), role="decode")
    assert reps[0] not in decode and set(decode) == {reps[1], reps[2]}
    prefill = router._candidates((), role="prefill")
    assert prefill == [reps[0]]
    assert router.handoff_topology()
    # availability beats specialization: with every non-prefill replica
    # gone, the token loop falls back to the prefill tier
    for r in (reps[1], reps[2]):
        for _ in range(3):
            router.note_failure(r, why="test")
    assert router._candidates((), role="decode") == [reps[0]]
    assert not router.handoff_topology()


# -- full-stack disaggregation (real subprocess replicas) --------------------

FLEET_ARGS = [
    "--model", "test-llama-tiny", "--continuous", "2",
    "--continuous-chunk", "4", "--kv-pool-blocks", "48",
    "--kv-block-size", str(BS), "--prefix-cache", "8",
    "--max-tokens-cap", "64",
]


def _spawn_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("DLI_FAULTS", None)
    return env


@pytest.fixture(scope="module")
def disagg_fleet():
    """1 prefill-class + 1 decode-class REAL engine server behind an
    in-process router — the two-class topology from the README."""
    pre = spawn_replicas(1, FLEET_ARGS, env=_spawn_env(),
                         replica_class="prefill", name_prefix="p")[0]
    dec = spawn_replicas(1, FLEET_ARGS, env=_spawn_env(),
                         replica_class="decode", name_prefix="d")[0]
    router = Router(
        [pre, dec], eject_threshold=3, probe_interval_s=0.25,
        probe_timeout_s=2.0, request_timeout_s=120.0,
        handoff_min_bytes=64,
    )
    server = RouterServer(router, host="127.0.0.1", port=0)
    server.start()
    try:
        yield router, server, f"http://127.0.0.1:{server.port}", pre, dec
    finally:
        server.shutdown()
        for rep in (pre, dec):
            if rep.proc is not None:
                try:
                    rep.proc.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    rep.proc.kill()


def _post(base, payload, path="/generate", timeout=120, headers=None):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _handoffs(router, outcome):
    return router.metrics.get(
        "dli_router_handoffs_total"
    ).labels(outcome=outcome).value


PROMPT_HANDOFF = "fresh disaggregated workload " * 3 + "alpha"
PROMPT_STREAMED = "streamed disaggregated workload " * 3


@pytest.mark.chaos
def test_prefill_decode_handoff_bit_exact(disagg_fleet, ref_engine):
    """Fresh long-prompt work: phase 1 prefills on the prefill-class
    replica, phase 2 decodes on the decode-class one after a fabric
    pull — greedy output bit-identical to serving the whole request on
    one replica."""
    router, _, base, pre, dec = disagg_fleet
    ref = ref_engine.generate(PROMPT_HANDOFF, **GEN)
    code, body, _ = _post(base, {"prompt": PROMPT_HANDOFF, **GEN})
    assert code == 200 and body["status"] == "success", body
    assert body["replica"] == "d0"  # the token loop ran on the decode tier
    # the chain reached the decode replica over the fabric: pulled at
    # admission (kv_fabric_blocks) or proactively pushed at the phase-1
    # boundary and promoted out of the host tier (kv_promoted_blocks)
    assert (
        body.get("kv_fabric_blocks", 0) + body.get("kv_promoted_blocks", 0)
        >= 5
    ), body
    assert body["response"] == ref["response"]
    assert body["tokens_generated"] == ref["tokens_generated"]
    assert _handoffs(router, "handoff") >= 1
    # residency learned in both spaces, naming the replica that SERVED
    assert router.kv_residency_entries() > 0
    # a repeat of the same prompt skips the handoff (prefix resident,
    # deep byte hit) and lands straight on the decode replica warm
    before = _handoffs(router, "handoff")
    code, body2, _ = _post(base, {"prompt": PROMPT_HANDOFF, **GEN})
    assert code == 200 and body2["replica"] == "d0"
    assert body2["response"] == ref["response"]
    assert body2.get("prefix_cached_tokens", 0) >= 5 * BS
    assert _handoffs(router, "handoff") == before


@pytest.mark.chaos
def test_streaming_handoff_transparent_bit_exact(disagg_fleet, ref_engine):
    """A streamed request hands off transparently: phase 1 is forced
    non-streamed on the prefill replica, the client's ONE stream comes
    from the decode replica, and the joined deltas equal the
    single-replica response byte for byte."""
    router, _, base, _, _ = disagg_fleet
    ref = ref_engine.generate(PROMPT_STREAMED, **GEN)
    req = urllib.request.Request(
        base + "/generate",
        data=json.dumps(
            {"prompt": PROMPT_STREAMED, "stream": True, **GEN}
        ).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    deltas, final = [], None
    with urllib.request.urlopen(req, timeout=120) as r:
        assert r.status == 200
        for line in r:
            ev = json.loads(line)
            if ev.get("done"):
                final = ev
                break
            deltas.append(ev.get("delta", ""))
    assert final is not None and final["status"] == "success"
    assert "".join(deltas) == ref["response"] == final["response"]
    assert (
        final.get("kv_fabric_blocks", 0)
        + final.get("kv_promoted_blocks", 0)
    ) >= 5
    assert _handoffs(router, "stream") >= 1


@pytest.mark.chaos
def test_prefill_replica_killed_mid_handoff(disagg_fleet, ref_engine):
    """kill -9 the prefill replica BETWEEN phase 1 and phase 2: the
    decode replica's fabric fetch hits a corpse, re-prefills locally,
    and the output is bit-identical. Then the router path: with the
    prefill tier dead, fresh long-prompt work degrades to a normal
    single-replica dispatch — same bytes out, never an error. LAST test
    in the module: it leaves the prefill replica dead."""
    router, _, base, pre, dec = disagg_fleet
    prompt = "doomed handoff workload " * 4 + "omega"
    ref = ref_engine.generate(prompt, **GEN)
    # phase 1 by hand, directly against the prefill replica
    code, p1, _ = _post(pre.url, {"prompt": prompt, **GEN},
                        headers={"X-KV-Prefill-Only": "1"})
    assert code == 200 and p1.get("prefill_only") is True
    assert p1["kv_digests"]
    pre.proc.kill()  # SIGKILL mid-handoff: no drain, no goodbye
    pre.proc.wait(timeout=15)
    # phase 2 against the decode replica, hint pointing at the corpse
    code, p2, _ = _post(
        dec.url, {"prompt": prompt, **GEN},
        headers={
            "X-KV-Transfer-Peer": pre.url,
            "X-KV-Transfer-Digest": p1["kv_digests"][-1],
        },
    )
    assert code == 200 and p2["status"] == "success", p2
    assert p2["response"] == ref["response"]  # local re-prefill, bit-exact
    assert "kv_fabric_blocks" not in p2

    # router path with a dead prefill tier: a FRESH long prompt either
    # fails phase 1 (connect error -> prefill_failed) or skips the
    # handoff entirely once the prober ejects p0 — both degrade to the
    # decode replica serving it whole, bit-identical
    prompt2 = "post mortem fresh workload " * 4
    ref2 = ref_engine.generate(prompt2, **GEN)
    code, body, _ = _post(base, {"prompt": prompt2, **GEN})
    assert code == 200 and body["status"] == "success", body
    assert body["replica"] == "d0"
    assert body["response"] == ref2["response"]
    # the corpse's residency entries are purged once the breaker trips
    t0 = time.time()
    while pre.state != EJECTED and time.time() - t0 < 10:
        time.sleep(0.05)
    assert pre.state == EJECTED
    with router._res_lock:
        assert all(
            "p0" not in v[0] for v in router._residency.values()
        )
        assert all(
            "p0" not in hs for hs in router._kv_residency.values()
        )
