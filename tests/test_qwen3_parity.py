"""Logits parity: our JAX Qwen3 vs a tiny-random HF Qwen3ForCausalLM.

Qwen3 is llama-arch (RMSNorm/RoPE/GQA/SwiGLU) plus per-head RMSNorm on q
and k BEFORE RoPE (HF Qwen3Attention.q_norm/k_norm, weight [head_dim]),
an explicit head_dim decoupled from dim/n_heads, and NO qkv biases
(dropped from Qwen2). Family flag: cfg.use_qk_norm.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")
pytest.importorskip("transformers.models.qwen3")

from distributed_llm_inference_tpu import EngineConfig, MeshConfig, get_model_config
from distributed_llm_inference_tpu.engine.engine import InferenceEngine
from distributed_llm_inference_tpu.models import llama
from distributed_llm_inference_tpu.models.convert import params_from_hf_model

# fast-tier exclusion: HF-parity family file; run the full suite (plain
# `pytest`) to include it
pytestmark = pytest.mark.slow


def _tiny_hf_qwen3(n_kv_heads=2, head_dim=24):
    cfg = transformers.Qwen3Config(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=n_kv_heads,
        head_dim=head_dim,
        max_position_embeddings=128,
        rms_norm_eps=1e-6,
        rope_theta=1000000.0,
        pad_token_id=0,
        eos_token_id=2,
        bos_token_id=1,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.Qwen3ForCausalLM(cfg)
    model.eval()
    return model


@pytest.mark.parametrize(
    "n_kv_heads,head_dim",
    [(4, 16), (2, 24)],  # MHA with dim/n_heads; GQA with decoupled head_dim
)
def test_qwen3_logits_match_hf(n_kv_heads, head_dim):
    hf = _tiny_hf_qwen3(n_kv_heads, head_dim)
    cfg, params = params_from_hf_model(hf, dtype="float32")
    assert cfg.arch == "llama"
    assert cfg.use_qk_norm
    assert cfg.head_dim == head_dim
    assert not cfg.attn_qkv_bias
    assert params["layers"]["q_norm"].shape == (3, head_dim)
    assert params["layers"]["k_norm"].shape == (3, head_dim)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(2, 19), dtype=np.int64)
    with torch.no_grad():
        hf_logits = hf(torch.from_numpy(tokens)).logits.numpy()

    cache = llama.init_kv_cache(cfg, batch=2, max_seq=32)
    logits, _ = llama.forward(
        cfg, params, jnp.asarray(tokens, jnp.int32), cache, jnp.int32(0)
    )
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=2e-4, atol=2e-4)


def test_qwen3_decode_matches_hf_generate():
    """Greedy decode token-for-token vs HF generate (the qk-norm must hold
    step-by-step through the KV cache, not just on one forward) — raw id
    comparison through the backend, no tokenizer in the loop."""
    from distributed_llm_inference_tpu.engine import generate as G

    hf = _tiny_hf_qwen3()
    cfg, params = params_from_hf_model(hf, dtype="float32")
    rng = np.random.default_rng(3)
    prompt_ids = rng.integers(3, cfg.vocab_size, size=9, dtype=np.int64)
    steps = 8
    with torch.no_grad():
        hf_out = hf.generate(
            torch.from_numpy(prompt_ids[None]), max_new_tokens=steps,
            do_sample=False, pad_token_id=0,
        )[0, len(prompt_ids):].numpy().tolist()
    if cfg.eos_token_id in hf_out:
        hf_out = hf_out[: hf_out.index(cfg.eos_token_id)]

    bucket = 16
    tokens = jnp.asarray(
        [prompt_ids.tolist() + [cfg.pad_token_id] * (bucket - len(prompt_ids))],
        jnp.int32,
    )
    plen = jnp.int32(len(prompt_ids))
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(0))
    cache = llama.init_kv_cache(cfg, 1, max_seq=64)
    first, _, cache = G.prefill(cfg, params, tokens, plen, cache, kp, sampling)
    out, n, _ = G.decode(
        cfg, params, first, cache, plen, jnp.int32(steps - 1), kd, sampling,
        max_steps=steps,
    )
    ours = [int(first[0])] + [int(t) for t in np.asarray(out[0][: int(n[0])])]
    if cfg.eos_token_id in ours:
        ours = ours[: ours.index(cfg.eos_token_id)]
    assert ours == hf_out


def test_qwen3_pipeline_matches_single_device(eight_devices):
    """q_norm/k_norm shard over pp with their layers and replicate over tp
    — the pp2xtp2 mesh decodes bit-exactly what one device decodes."""
    from distributed_llm_inference_tpu.engine import generate as G
    from distributed_llm_inference_tpu.models import api as M
    from distributed_llm_inference_tpu.parallel.mesh import build_mesh
    from distributed_llm_inference_tpu.parallel.pipeline import PipelineBackend

    cfg = get_model_config("test-qwen3-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    ids = [5, 9, 13, 21, 8]
    bucket, steps = 16, 6
    tokens = jnp.asarray([ids + [cfg.pad_token_id] * (bucket - len(ids))], jnp.int32)
    plen = jnp.int32(len(ids))
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(3))

    cache_s = M.init_kv_cache(cfg, 1, max_seq=64)
    f_s, logits_s, cache_s = G.prefill(cfg, params, tokens, plen, cache_s, kp, sampling)
    out_s, n_s, _ = G.decode(
        cfg, params, f_s, cache_s, plen, jnp.int32(steps), kd, sampling,
        max_steps=steps,
    )

    mesh = build_mesh(MeshConfig(dp=1, pp=2, tp=2), eight_devices)
    pb = PipelineBackend(cfg, params, mesh)
    cache_p = pb.init_cache(1, 64)
    f_p, logits_p, cache_p = pb.prefill(tokens, plen, cache_p, kp, sampling)
    out_p, n_p, _ = pb.decode(
        f_p, cache_p, plen, jnp.int32(steps), kd, sampling, max_steps=steps
    )
    np.testing.assert_allclose(
        np.asarray(logits_p), np.asarray(logits_s), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out_s))


def test_qwen3_presets():
    cfg = get_model_config("qwen3-8b")
    assert cfg.use_qk_norm and cfg.head_dim == 128
    assert not cfg.attn_qkv_bias
    tiny = get_model_config("test-qwen3-tiny")
    assert tiny.use_qk_norm and tiny.head_dim == 24


# -- Qwen3-MoE (qwen3 attention + Mixtral-shaped expert bank) ---------------


def _tiny_hf_qwen3_moe(norm_topk=True):
    pytest.importorskip("transformers.models.qwen3_moe")
    cfg = transformers.Qwen3MoeConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        moe_intermediate_size=48, num_hidden_layers=3,
        num_attention_heads=4, num_key_value_heads=2, head_dim=24,
        num_experts=4, num_experts_per_tok=2, norm_topk_prob=norm_topk,
        max_position_embeddings=128, rms_norm_eps=1e-6,
        rope_theta=1000000.0, pad_token_id=0, eos_token_id=2,
        bos_token_id=1, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(21)
    model = transformers.Qwen3MoeForCausalLM(cfg)
    model.eval()
    return model


@pytest.mark.parametrize("norm_topk", [True, False])
def test_qwen3_moe_logits_match_hf(norm_topk):
    """Qwen3-MoE parity incl. BOTH router normalizations (norm_topk_prob
    is the only difference from the Mixtral block)."""
    hf = _tiny_hf_qwen3_moe(norm_topk)
    cfg, params = params_from_hf_model(hf, dtype="float32")
    assert cfg.use_qk_norm and cfg.n_experts == 4
    assert cfg.moe_renormalize is norm_topk
    assert cfg.ffn_dim == 48  # experts use moe_intermediate_size
    assert params["layers"]["w_gate"].shape == (3, 4, 64, 48)

    rng = np.random.default_rng(7)
    tokens = rng.integers(0, cfg.vocab_size, size=(2, 15), dtype=np.int64)
    with torch.no_grad():
        hf_logits = hf(torch.from_numpy(tokens)).logits.numpy()
    cache = llama.init_kv_cache(cfg, batch=2, max_seq=32)
    logits, _ = llama.forward(
        cfg, params, jnp.asarray(tokens, jnp.int32), cache, jnp.int32(0)
    )
    np.testing.assert_allclose(np.asarray(logits), hf_logits,
                               rtol=3e-4, atol=3e-4)


def test_qwen3_moe_rejects_partial_dense():
    pytest.importorskip("transformers.models.qwen3_moe")
    cfg = transformers.Qwen3MoeConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        moe_intermediate_size=48, num_hidden_layers=4,
        num_attention_heads=4, num_key_value_heads=2,
        num_experts=4, num_experts_per_tok=2,
        mlp_only_layers=[0],  # mixed dense/sparse stack
    )
    from distributed_llm_inference_tpu.models.convert import config_from_hf

    with pytest.raises(ValueError, match="mlp_only_layers"):
        config_from_hf(cfg)
