"""Failure surface: request deadlines + device-probing /workers.

Reference behavior being matched: 30s per worker hop / 5s health probes,
with online/offline/error worker states and clean error envelopes
(/root/reference/orchestration.py:118,131,306-329).
"""

import json
import time
import urllib.error
import urllib.request

import pytest
import jax

from distributed_llm_inference_tpu import EngineConfig, create_engine
from distributed_llm_inference_tpu.engine.engine import (
    InferenceEngine, SingleDeviceBackend,
)
from distributed_llm_inference_tpu.models import api as M
from distributed_llm_inference_tpu.models.registry import get_model_config
from distributed_llm_inference_tpu.utils.probe import probe_device


class SlowBackend(SingleDeviceBackend):
    """Backend whose prefill paths all stall, simulating a wedged device
    call. Covers extend/prefill_at too — a chat-templated prompt longer
    than the bucket takes the chunked route and must stall identically."""

    def __init__(self, cfg, params, delay_s):
        super().__init__(cfg, params)
        self.delay_s = delay_s

    def prefill(self, *a, **kw):
        time.sleep(self.delay_s)
        return super().prefill(*a, **kw)

    def extend(self, *a, **kw):
        time.sleep(self.delay_s)
        return super().extend(*a, **kw)

    def prefill_at(self, *a, **kw):
        time.sleep(self.delay_s)
        return super().prefill_at(*a, **kw)


def _slow_engine(delay_s, deadline_s):
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    return InferenceEngine(
        cfg,
        backend=SlowBackend(cfg, params, delay_s),
        engine_cfg=EngineConfig(
            prefill_buckets=(32,), request_deadline_s=deadline_s
        ),
    )


def test_deadline_times_out_and_engine_recovers():
    engine = _slow_engine(delay_s=2.0, deadline_s=0.3)
    t0 = time.time()
    r = engine.generate("hi", max_tokens=3, greedy=True, chat=False)
    elapsed = time.time() - t0
    assert r["status"] == "failed" and r["error_type"] == "timeout", r
    assert elapsed < 1.5  # envelope within the deadline, not after delay_s

    # once the wedged call drains, the engine serves again
    engine.backend.delay_s = 0.0
    deadline = time.time() + 10
    while time.time() < deadline:
        r2 = engine.generate("hi again", max_tokens=3, greedy=True, chat=False)
        if r2["status"] == "success":
            break
        assert r2["error_type"] == "timeout"
        time.sleep(0.2)
    assert r2["status"] == "success", r2


def test_no_deadline_means_no_timeout():
    engine = _slow_engine(delay_s=0.5, deadline_s=None)
    r = engine.generate("hi", max_tokens=3, greedy=True, chat=False)
    assert r["status"] == "success", r


def test_deadline_timeout_maps_to_503():
    from distributed_llm_inference_tpu.serving.server import InferenceServer

    engine = _slow_engine(delay_s=3.0, deadline_s=0.3)
    server = InferenceServer(engine, host="127.0.0.1", port=0)
    server.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/generate",
            data=json.dumps({"prompt": "x", "max_tokens": 2}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(req, timeout=30)
        assert exc_info.value.code == 503
        body = json.loads(exc_info.value.read())
        assert body["error_type"] == "timeout"
    finally:
        server.shutdown()


def test_workers_probe_reports_timing():
    engine = create_engine(
        "test-llama-tiny", engine_cfg=EngineConfig(prefill_buckets=(32,))
    )
    w = engine.workers()
    stage = w["workers"]["stage_0"]
    assert stage["status"] == "online"
    assert stage["probe_ms"] >= 0.0


def test_probe_device_error_and_timeout_paths():
    def raising():
        raise RuntimeError("device exploded")

    r = probe_device(None, _op=raising)
    assert r["status"] == "error" and "device exploded" in r["error"]

    def hanging():
        time.sleep(5)

    r = probe_device(None, timeout_s=0.2, _op=hanging)
    assert r["status"] == "offline" and "timed out" in r["error"]


def test_pipeline_workers_probe(eight_devices):
    from distributed_llm_inference_tpu import MeshConfig

    engine = create_engine(
        "test-llama-tiny",
        mesh_cfg=MeshConfig(dp=1, pp=2, tp=1),
        engine_cfg=EngineConfig(prefill_buckets=(32,)),
    )
    w = engine.workers()
    assert w["total"] == 2
    for s in w["workers"].values():
        assert s["status"] == "online"
        assert s["probe_ms"] >= 0.0


def test_health_degrades_while_wedged_and_recovers():
    """Round-2 review weak #5 / next-round #7: an abandoned deadline-
    overrun call flips /health to "degraded" with the stuck age; once the
    call drains, health returns to "healthy"."""
    engine = _slow_engine(delay_s=2.5, deadline_s=0.3)
    assert engine.health()["status"] == "healthy"
    r = engine.generate("hi", max_tokens=3, greedy=True, chat=False)
    assert r["status"] == "failed" and r["error_type"] == "timeout"
    h = engine.health()
    assert h["status"] == "degraded"
    assert h["wedged"] and h["wedged"][0]["what"] == "generate"
    assert h["wedged"][0]["age_s"] >= 0.0
    # the stuck call eventually drains on its daemon thread
    deadline = time.time() + 15
    while time.time() < deadline and engine.health()["status"] != "healthy":
        time.sleep(0.2)
    h2 = engine.health()
    assert h2["status"] == "healthy" and "wedged" not in h2


def test_max_wedged_age_tracks_oldest():
    engine = _slow_engine(delay_s=2.0, deadline_s=0.2)
    assert engine.max_wedged_age() is None
    engine.generate("hi", max_tokens=3, greedy=True, chat=False)
    age = engine.max_wedged_age()
    assert age is not None and age >= 0.0
    time.sleep(0.5)
    assert engine.max_wedged_age() > age
