"""Mixtral-style sparse MoE: HF parity + expert parallelism.

The HF MixtralForCausalLM is the behavioral spec for the router (fp32
softmax -> top-k -> renormalize) and the expert SwiGLU; the ep mesh axis
must reproduce the single-device MoE bit-for-bit (each device computes
its expert slice for all tokens, one psum combines).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llm_inference_tpu import EngineConfig, MeshConfig, create_engine
from distributed_llm_inference_tpu.engine import generate as G
from distributed_llm_inference_tpu.models import api as M
from distributed_llm_inference_tpu.models.registry import get_model_config

# fast-tier exclusion: MoE forward + ep-mesh compiles; run the full suite (plain
# `pytest`) to include it
pytestmark = pytest.mark.slow


def test_moe_forward_shapes_and_sparsity():
    cfg = get_model_config("test-moe-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    assert params["layers"]["w_gate"].shape == (4, 4, 64, 96)
    assert params["layers"]["w_router"].shape == (4, 64, 4)
    cache = M.init_kv_cache(cfg, 1, max_seq=32)
    tokens = jnp.asarray([[5, 9, 13]], jnp.int32)
    logits, _ = M.forward(cfg, params, tokens, cache, jnp.int32(0))
    assert logits.shape == (1, 3, cfg.vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_moe_logits_match_hf():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    from distributed_llm_inference_tpu.models import llama
    from distributed_llm_inference_tpu.models.convert import params_from_hf_model

    cfg_hf = transformers.MixtralConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=96,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_local_experts=4,
        num_experts_per_tok=2,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
        sliding_window=None,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf = transformers.MixtralForCausalLM(cfg_hf)
    hf.eval()
    cfg, params = params_from_hf_model(hf, dtype="float32")
    assert cfg.n_experts == 4 and cfg.n_experts_per_tok == 2

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(2, 11), dtype=np.int64)
    with torch.no_grad():
        hf_logits = hf(torch.from_numpy(tokens)).logits.numpy()
    cache = llama.init_kv_cache(cfg, batch=2, max_seq=32)
    logits, _ = llama.forward(
        cfg, params, jnp.asarray(tokens, jnp.int32), cache, jnp.int32(0)
    )
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize(
    "mesh_cfg",
    [
        MeshConfig(ep=4),
        MeshConfig(ep=2),
        MeshConfig(pp=2, ep=2),
    ],
    ids=["ep4", "ep2", "pp2ep2"],
)
def test_expert_parallel_matches_single_device(mesh_cfg, eight_devices):
    """ep-sharded expert banks (optionally under pp) decode exactly what
    the single-device MoE decodes."""
    from distributed_llm_inference_tpu.parallel.mesh import build_mesh
    from distributed_llm_inference_tpu.parallel.pipeline import PipelineBackend

    cfg = get_model_config("test-moe-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))

    rng = np.random.default_rng(2)
    ids = rng.integers(3, cfg.vocab_size, size=9, dtype=np.int64).tolist()
    bucket, steps = 16, 6
    tokens = jnp.asarray([ids + [cfg.pad_token_id] * (bucket - len(ids))], jnp.int32)
    plen = jnp.int32(len(ids))
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(3))

    cache_s = M.init_kv_cache(cfg, 1, max_seq=64)
    f_s, logits_s, cache_s = G.prefill(cfg, params, tokens, plen, cache_s, kp, sampling)
    out_s, n_s, _ = G.decode(
        cfg, params, f_s, cache_s, plen, jnp.int32(steps), kd, sampling, max_steps=steps
    )

    mesh = build_mesh(mesh_cfg, eight_devices)
    pb = PipelineBackend(cfg, params, mesh)
    # expert bank actually sharded over ep
    wg = pb.layers["w_gate"]
    assert wg.sharding.shard_shape(wg.shape)[1] == cfg.n_experts // mesh_cfg.ep
    cache_p = pb.init_cache(1, 64)
    f_p, logits_p, cache_p = pb.prefill(tokens, plen, cache_p, kp, sampling)
    out_p, n_p, _ = pb.decode(
        f_p, cache_p, plen, jnp.int32(steps), kd, sampling, max_steps=steps
    )

    np.testing.assert_allclose(
        np.asarray(logits_p), np.asarray(logits_s), rtol=1e-4, atol=1e-5
    )
    assert int(f_p[0]) == int(f_s[0])
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out_s))
    assert int(n_p[0]) == int(n_s[0])


def test_moe_engine_end_to_end(eight_devices):
    engine = create_engine(
        "test-moe-tiny",
        mesh_cfg=MeshConfig(ep=2),
        engine_cfg=EngineConfig(prefill_buckets=(32,)),
    )
    r = engine.generate("mixture of experts", max_tokens=5, greedy=True, chat=False)
    assert r["status"] == "success", r
    assert r["tokens_generated"] >= 1


def test_mesh_validation_for_experts(eight_devices):
    from distributed_llm_inference_tpu.parallel.partition import validate_mesh

    dense = get_model_config("test-llama-tiny")
    with pytest.raises(ValueError, match="needs an MoE model"):
        validate_mesh(dense, pp=1, tp=1, ep=2)
    moe = get_model_config("test-moe-tiny")  # 4 experts
    with pytest.raises(ValueError, match="not divisible by ep"):
        validate_mesh(moe, pp=1, tp=1, ep=3)
    with pytest.raises(NotImplementedError, match="tensor parallelism"):
        validate_mesh(moe, pp=1, tp=2, ep=1)


def test_moe_uneven_pp_no_op_padding(eight_devices):
    """Zero-padded no-op layers stay exact no-ops with an MoE FFN (zero
    router -> uniform top-k of zero experts -> zero output)."""
    from distributed_llm_inference_tpu.parallel.mesh import build_mesh
    from distributed_llm_inference_tpu.parallel.pipeline import PipelineBackend

    cfg = get_model_config("test-moe-tiny", n_layers=3)
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    ids = [5, 9, 13, 21]
    tokens = jnp.asarray([ids + [cfg.pad_token_id] * 12], jnp.int32)
    plen = jnp.int32(len(ids))
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(5))

    cache_s = M.init_kv_cache(cfg, 1, max_seq=64)
    f_s, logits_s, _ = G.prefill(cfg, params, tokens, plen, cache_s, kp, sampling)

    mesh = build_mesh(MeshConfig(pp=2, ep=2), eight_devices)
    pb = PipelineBackend(cfg, params, mesh)  # 3 layers over pp=2: 2,1+pad
    cache_p = pb.init_cache(1, 64)
    f_p, logits_p, _ = pb.prefill(tokens, plen, cache_p, kp, sampling)
    np.testing.assert_allclose(
        np.asarray(logits_p), np.asarray(logits_s), rtol=1e-4, atol=1e-5
    )
    assert int(f_p[0]) == int(f_s[0])
