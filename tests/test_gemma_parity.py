"""Logits parity: our JAX Gemma / Gemma-2 vs tiny-random HF models.

Gemma is llama-arch plus: unit-offset RMSNorm ((1+w)·x̂), GeGLU
(gelu_pytorch_tanh), sqrt(dim)-scaled embeddings, explicit head_dim, tied
embeddings. Gemma-2 adds sandwich norms (post-attention + post-FFN),
attention/final logit softcapping, query_pre_attn_scalar score scaling, and
sliding window on even-indexed layers only. The HF torch models are the
behavioral spec (SURVEY.md §4 testing model); all models are built from
configs offline.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from distributed_llm_inference_tpu import EngineConfig, get_model_config
from distributed_llm_inference_tpu.engine.engine import InferenceEngine
from distributed_llm_inference_tpu.models import llama
from distributed_llm_inference_tpu.models.convert import params_from_hf_model

# fast-tier exclusion: HF-parity family file; run the full suite (plain
# `pytest`) to include it
pytestmark = pytest.mark.slow


def _tiny_hf_gemma():
    cfg = transformers.GemmaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=4,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=24,  # deliberately != hidden/heads (gemma-7b trait)
        max_position_embeddings=128,
        rms_norm_eps=1e-6,
        rope_theta=10000.0,
        hidden_activation="gelu_pytorch_tanh",
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.GemmaForCausalLM(cfg)
    model.eval()
    return model


def _tiny_hf_gemma2(sliding_window=32):
    cfg = transformers.Gemma2Config(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=4,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=24,
        max_position_embeddings=128,
        rms_norm_eps=1e-6,
        rope_theta=10000.0,
        hidden_activation="gelu_pytorch_tanh",
        query_pre_attn_scalar=24,
        attn_logit_softcapping=50.0,
        final_logit_softcapping=30.0,
        sliding_window=sliding_window,
        attn_implementation="eager",
    )
    torch.manual_seed(1)
    model = transformers.Gemma2ForCausalLM(cfg)
    model.eval()
    return model


def test_gemma_logits_match_hf():
    hf = _tiny_hf_gemma()
    cfg, params = params_from_hf_model(hf, dtype="float32")
    assert cfg.norm_unit_offset and cfg.act == "gelu_tanh" and cfg.embed_scale
    assert cfg.head_dim == 24
    assert cfg.tie_embeddings  # HF omits lm_head when tied

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(2, 19), dtype=np.int64)
    with torch.no_grad():
        hf_logits = hf(torch.from_numpy(tokens)).logits.numpy()

    cache = llama.init_kv_cache(cfg, batch=2, max_seq=32)
    logits, _ = llama.forward(
        cfg, params, jnp.asarray(tokens, jnp.int32), cache, jnp.int32(0)
    )
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=2e-4, atol=2e-4)


def test_gemma2_logits_match_hf():
    """Softcaps + sandwich norms + query scale + ALTERNATING sliding window
    (sequence longer than the window so the masks actually differ)."""
    hf = _tiny_hf_gemma2(sliding_window=16)
    cfg, params = params_from_hf_model(hf, dtype="float32")
    assert cfg.post_norms and cfg.attn_softcap == 50.0
    assert cfg.final_softcap == 30.0 and cfg.query_scale_override == 24
    assert cfg.attn_window == 16 and cfg.attn_window_pattern == "even"
    assert "window_flag" in params["layers"]

    rng = np.random.default_rng(1)
    tokens = rng.integers(0, cfg.vocab_size, size=(2, 41), dtype=np.int64)
    with torch.no_grad():
        hf_logits = hf(torch.from_numpy(tokens)).logits.numpy()

    cache = llama.init_kv_cache(cfg, batch=2, max_seq=64)
    logits, _ = llama.forward(
        cfg, params, jnp.asarray(tokens, jnp.int32), cache, jnp.int32(0)
    )
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=3e-4, atol=3e-4)


def test_gemma2_decode_matches_prefill_logits():
    """Tokenwise decode (T=1 steps through the cache) reproduces the full
    prefill logits — the alternating window masks must hold per step."""
    hf = _tiny_hf_gemma2(sliding_window=8)
    cfg, params = params_from_hf_model(hf, dtype="float32")
    rng = np.random.default_rng(2)
    tokens = rng.integers(0, cfg.vocab_size, size=(1, 24), dtype=np.int64)
    jt = jnp.asarray(tokens, jnp.int32)

    cache = llama.init_kv_cache(cfg, batch=1, max_seq=32)
    full_logits, _ = llama.forward(cfg, params, jt, cache, jnp.int32(0))

    cache = llama.init_kv_cache(cfg, batch=1, max_seq=32)
    step_logits = []
    for t in range(tokens.shape[1]):
        lt, cache = llama.forward(cfg, params, jt[:, t : t + 1], cache, jnp.int32(t))
        step_logits.append(np.asarray(lt[:, 0]))
    np.testing.assert_allclose(
        np.stack(step_logits, axis=1), np.asarray(full_logits),
        rtol=2e-5, atol=2e-5,
    )


def test_gemma_chat_template_and_engine_smoke():
    cfg = get_model_config("test-gemma2-tiny")
    eng = InferenceEngine(cfg, engine_cfg=EngineConfig(prefill_buckets=(32, 64)))
    r = eng.generate("hello gemma", max_tokens=6, greedy=True)
    assert r["status"] == "success"
    assert 0 <= r["tokens_generated"] <= 6
    from distributed_llm_inference_tpu.engine.chat import format_chat_prompt

    t = format_chat_prompt("hi", arch="llama", template="gemma")
    assert t.startswith("<start_of_turn>user\n")
    assert t.endswith("<start_of_turn>model\n")


def test_gemma2_pipeline_matches_single_device(eight_devices):
    """pp=2 pipeline == single device for the gemma2 test config: proves
    the stacked window_flag / sandwich-norm leaves shard over pp (uneven
    4-layer split is even here; the flag rides the layer axis)."""
    from distributed_llm_inference_tpu import MeshConfig
    from distributed_llm_inference_tpu.engine import generate as G
    from distributed_llm_inference_tpu.models import api as M
    from distributed_llm_inference_tpu.parallel.mesh import build_mesh
    from distributed_llm_inference_tpu.parallel.pipeline import PipelineBackend

    cfg = get_model_config("test-gemma2-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(3))
    mesh = build_mesh(MeshConfig(dp=1, pp=2, tp=1), jax.devices())
    pb = PipelineBackend(cfg, params, mesh)

    rng = np.random.default_rng(4)
    ids = rng.integers(3, cfg.vocab_size, size=13, dtype=np.int64).tolist()
    bucket = 16
    tokens = jnp.asarray([ids + [cfg.pad_token_id] * (bucket - len(ids))], jnp.int32)
    plen = jnp.int32(len(ids))
    sampling = G.default_sampling(greedy=True)
    key = jax.random.PRNGKey(5)

    cache_s = M.init_kv_cache(cfg, 1, max_seq=64)
    f_s, logits_s, cache_s = G.prefill(cfg, params, tokens, plen, cache_s, key, sampling)
    out_s, n_s, _ = G.decode(
        cfg, params, f_s, cache_s, plen, jnp.int32(8), key, sampling, max_steps=8
    )

    cache_p = pb.init_cache(1, 64)
    f_p, logits_p, cache_p = pb.prefill(tokens, plen, cache_p, key, sampling)
    out_p, n_p, _ = pb.decode(
        f_p, cache_p, plen, jnp.int32(8), key, sampling, max_steps=8
    )

    np.testing.assert_allclose(
        np.asarray(logits_p), np.asarray(logits_s), rtol=1e-4, atol=1e-5
    )
    assert int(f_p[0]) == int(f_s[0])
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out_s))


def test_gemma2_presets_resolve():
    for name in ("gemma-2b", "gemma-7b", "gemma2-2b", "gemma2-9b"):
        cfg = get_model_config(name)
        assert cfg.head_dim == 256
        assert cfg.norm_unit_offset and cfg.embed_scale
        assert 107 in cfg.stop_token_ids  # <end_of_turn> stops gemma-it


def test_extra_stop_token_ends_generation():
    """A token in stop_token_ids terminates decode exactly like eos
    (gemma-it ends turns with <end_of_turn>, not <eos>): zero params make
    argmax always 0, so with stop_token_ids=(0,) and eos elsewhere the
    loop must emit nothing."""
    from distributed_llm_inference_tpu.models import llama as L

    cfg = get_model_config("test-llama-tiny").replace(
        eos_token_id=5, pad_token_id=3, stop_token_ids=(0,)
    )
    p = jax.tree.map(jnp.zeros_like, L.init_params(cfg, jax.random.PRNGKey(0)))
    from distributed_llm_inference_tpu.engine.engine import SingleDeviceBackend

    eng = InferenceEngine(
        cfg, backend=SingleDeviceBackend(cfg, p),
        engine_cfg=EngineConfig(prefill_buckets=(32,)),
    )
    r = eng.generate("hi", max_tokens=8, greedy=True, chat=False)
    assert r["status"] == "success"
    assert r["tokens_generated"] == 0 and r["response"] == ""


def test_converter_list_eos_to_stop_tokens():
    """HF checkpoints (Llama-3.1, gemma-it) ship eos_token_id as a LIST:
    first id stays the primary eos, the rest become stop_token_ids."""
    from distributed_llm_inference_tpu.models.convert import config_from_hf

    hf_cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, eos_token_id=[7, 9, 11],
    )
    cfg = config_from_hf(hf_cfg)
    assert cfg.eos_token_id == 7
    assert cfg.stop_token_ids == (9, 11)
    assert cfg.all_stop_ids == (7, 9, 11)
