"""Router-tier chaos suite (serving/router.py).

Two layers, both marked `chaos` (the router-chaos CI leg runs this file):

  * STUB-REPLICA tests: the router's routing/ejection/failover logic
    against in-process fake engine servers whose behavior is scripted
    (draining, overloaded, 500, mid-stream death) — deterministic,
    sub-second, probe sweeps driven by hand (probe_once).
  * REAL-SUBPROCESS tests: two actual engine servers (test-llama-tiny,
    --continuous) behind an in-process Router; the acceptance leg
    `kill -9`s one mid-batch and proves: the survivor keeps serving,
    every in-flight non-streamed request completes via failover with
    greedy output BIT-IDENTICAL to a fault-free single-replica run, the
    dead replica is ejected within the probe window and readmitted
    after restart, and the `dli_router_*` metrics reflect the episode.
    The victim is held in flight deterministically with a utils/faults.py
    wedge armed via DLI_FAULTS in the victim's environment.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from distributed_llm_inference_tpu.client import DistributedLLMClient
from distributed_llm_inference_tpu.engine.block_prefix import chunk_digests
from distributed_llm_inference_tpu.serving.router import (
    EJECTED, HALF_OPEN, READY, Replica, Router, RouterServer, _free_port,
    spawn_replicas,
)

pytestmark = pytest.mark.chaos


# -- stub replica infrastructure ----------------------------------------------

class _Stub:
    """A scripted fake engine server speaking the routed surface:
    /ready, /health, /generate (+ NDJSON streaming). `mode` is mutable
    mid-test: ok | draining | overloaded | error500 | stream_die."""

    def __init__(self, name: str, mode: str = "ok"):
        self.name = name
        self.mode = mode
        self.ready = True
        self.retry_after = "1"
        self.seen = []  # (path, request_id, prompt) per POST
        self.lock = threading.Lock()
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _json(self, code, payload, headers=None):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?")[0].rstrip("/") or "/"
                if path == "/ready":
                    if stub.ready:
                        self._json(200, {"ready": True})
                    else:
                        self._json(503, {"ready": False},
                                   headers={"Retry-After": stub.retry_after})
                elif path == "/health":
                    self._json(200, {"status": "healthy", "ready": stub.ready,
                                     "stub": stub.name})
                else:
                    self._json(404, {"error": "no route"})

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                data = json.loads(self.rfile.read(length) or b"{}")
                with stub.lock:
                    stub.seen.append((
                        self.path, self.headers.get("X-Request-Id"),
                        data.get("prompt", ""),
                    ))
                mode = stub.mode
                if mode == "draining":
                    self._json(
                        503,
                        {"error": "Error: server draining",
                         "status": "failed", "error_type": "draining"},
                        headers={"Retry-After": stub.retry_after},
                    )
                    return
                if mode == "overloaded":
                    self._json(
                        429,
                        {"error": "Error: request queue full (4)",
                         "status": "failed", "error_type": "overloaded",
                         "retry_after_s": 1},
                        headers={"Retry-After": stub.retry_after},
                    )
                    return
                if mode == "error500":
                    self._json(
                        500,
                        {"error": "Error: poison", "status": "failed",
                         "error_type": "poison"},
                    )
                    return
                if data.get("stream"):
                    self.send_response(200)
                    self.send_header("Content-Type", "application/x-ndjson")
                    self.end_headers()
                    self.wfile.write(
                        b'{"delta": "partial ", "tokens_so_far": 1}\n'
                    )
                    self.wfile.flush()
                    if mode == "stream_die":
                        self.connection.close()  # mid-stream death
                        return
                    self.wfile.write(json.dumps({
                        "status": "success", "done": True,
                        "response": "partial end", "served_by": stub.name,
                    }).encode() + b"\n")
                    return
                self._json(200, {
                    "status": "success",
                    "response": f"ok from {stub.name}",
                    "served_by": stub.name,
                    "request_id": self.headers.get("X-Request-Id"),
                    "timings": {"prefill_s": 0.001, "total_s": 0.002},
                })

        self._handler = Handler
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self._serve()

    def _serve(self):
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()

    def restart(self):
        """Rebind the SAME port (a replica coming back after a crash)."""
        self.httpd = ThreadingHTTPServer(("127.0.0.1", self.port),
                                         self._handler)
        self._serve()

    def served(self):
        with self.lock:
            return list(self.seen)


def _mk_router(stubs, **kw):
    kw.setdefault("probe_interval_s", 3600.0)  # probes driven by hand
    kw.setdefault("probe_timeout_s", 2.0)
    kw.setdefault("eject_threshold", 3)
    kw.setdefault("request_timeout_s", 30.0)
    reps = [Replica(s.name, s.url) for s in stubs]
    return Router(reps, **kw)


def _serve(router):
    server = RouterServer(router, host="127.0.0.1", port=0)
    server.start()
    return server, f"http://127.0.0.1:{server.port}"


def _post(base, payload, path="/generate", timeout=30, headers=None):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _get(base, path, timeout=15):
    try:
        with urllib.request.urlopen(base + path, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _counter(router, name, **labels):
    return router.metrics.get(name).labels(**labels).value


def _pin(router, prompt, rid):
    """Seed the residency map so `prompt` deterministically routes to
    replica `rid` (what a prior serve by that replica would have done)."""
    router.record_residency(
        chunk_digests(prompt, router.affinity_chunk, 32), rid
    )


LONG_PREFIX = "shared system preamble " * 8  # >> affinity_chunk bytes


# -- routing + envelope ------------------------------------------------------

def test_proxies_and_annotates_envelope():
    a, b = _Stub("a"), _Stub("b")
    router = _mk_router([a, b])
    server, base = _serve(router)
    try:
        code, body, hdrs = _post(
            base, {"prompt": "hello world", "max_tokens": 4},
            headers={"X-Request-Id": "rid-e2e-1"},
        )
        assert code == 200 and body["status"] == "success"
        # the router names the serving replica and folds its hop into the
        # contiguous timings model
        assert body["replica"] in ("a", "b")
        assert body["timings"]["router_s"] >= 0.0
        # total_s now covers the whole hop (router wall time), and the
        # upstream's own spans survived the rewrite
        assert body["timings"]["total_s"] > 0
        assert body["timings"]["prefill_s"] == 0.001
        # X-Request-Id crossed the hop (upstream saw it) and came back
        assert hdrs.get("X-Request-Id") == "rid-e2e-1"
        served = (a.served() or b.served())
        assert served[0][1] == "rid-e2e-1"
        assert _counter(router, "dli_router_requests_total",
                        replica=body["replica"], code="200") == 1
    finally:
        server.shutdown()
        a.stop()
        b.stop()


def test_prefix_affinity_pins_chain_to_one_replica():
    a, b = _Stub("a"), _Stub("b")
    router = _mk_router([a, b])
    server, base = _serve(router)
    try:
        first = _post(base, {"prompt": LONG_PREFIX + "question 0"})[1]
        owner = first["replica"]
        for i in range(1, 5):
            body = _post(base, {"prompt": LONG_PREFIX + f"question {i}"})[1]
            assert body["replica"] == owner, (
                "shared-prefix traffic must land where its KV blocks live"
            )
        assert _counter(router, "dli_router_affinity_total",
                        result="hit") >= 4
        assert router.residency_entries() > 0
    finally:
        server.shutdown()
        a.stop()
        b.stop()


def test_least_outstanding_fallback_on_miss():
    a, b = _Stub("a"), _Stub("b")
    router = _mk_router([a, b])
    try:
        ra, rb = router.replicas
        ra.outstanding = 5
        rep, digests = router.pick("short")  # < chunk: no digests
        assert digests == []
        assert rep.rid == "b"
        rb.outstanding = 9
        rep, _ = router.pick("short")
        assert rep.rid == "a"
    finally:
        a.stop()
        b.stop()


# -- failover + circuit breaking ---------------------------------------------

def test_dead_replica_failover_ejection_readmission():
    """The full episode against stubs: affinity pins the request to a
    replica that is down -> transparent failover serves it; probes eject
    the dead replica (breaker threshold), then readmit it through
    half-open once it returns."""
    a, b = _Stub("a"), _Stub("b")
    router = _mk_router([a, b])
    server, base = _serve(router)
    a.stop()  # replica a is dead before the router learns it
    try:
        _pin(router, LONG_PREFIX, "a")
        code, body, _ = _post(base, {"prompt": LONG_PREFIX + "q"})
        assert code == 200 and body["served_by"] == "b"
        assert body.get("router_attempts") == 2
        assert _counter(router, "dli_router_failovers_total", replica="a") == 1
        # the failed-over chain's residency moved with the traffic
        rep, _ = router.pick(LONG_PREFIX + "q")
        assert rep.rid == "b"
        # active probes finish the ejection (1 passive strike so far)
        ra = router.replicas[0]
        for _ in range(router.eject_threshold):
            router.probe_once()
        assert ra.state == EJECTED
        assert _counter(router, "dli_router_ejections_total", replica="a") == 1
        assert _counter(router, "dli_router_replica_ready", replica="a") == 0.0
        assert _counter(router, "dli_router_replica_ready", replica="b") == 1.0
        # replica returns: EJECTED -> HALF_OPEN -> READY over two probes
        a.restart()
        router.probe_once()
        assert ra.state == HALF_OPEN
        router.probe_once()
        assert ra.state == READY
        assert _counter(router, "dli_router_readmissions_total",
                        replica="a") == 1
        assert _counter(router, "dli_router_replica_ready", replica="a") == 1.0
    finally:
        server.shutdown()
        a.stop()
        b.stop()


def test_draining_replica_fails_over_with_cooldown():
    a, b = _Stub("a", mode="draining"), _Stub("b")
    a.retry_after = "5"
    router = _mk_router([a, b])
    server, base = _serve(router)
    try:
        _pin(router, LONG_PREFIX, "a")
        code, body, _ = _post(base, {"prompt": LONG_PREFIX + "q"})
        assert code == 200 and body["served_by"] == "b"
        assert _counter(router, "dli_router_failovers_total", replica="a") == 1
        # the upstream Retry-After became a cool-down: a is not even tried
        ra = router.replicas[0]
        assert ra.cooldown_until > time.monotonic()
        n_seen = len(a.served())
        _post(base, {"prompt": LONG_PREFIX + "q2"})
        assert len(a.served()) == n_seen
    finally:
        server.shutdown()
        a.stop()
        b.stop()


def test_overloaded_replica_spills_to_peer():
    a, b = _Stub("a", mode="overloaded"), _Stub("b")
    router = _mk_router([a, b])
    server, base = _serve(router)
    try:
        _pin(router, LONG_PREFIX, "a")
        code, body, _ = _post(base, {"prompt": LONG_PREFIX + "q"})
        assert code == 200 and body["served_by"] == "b"
        # a 429 is load, not death: no breaker strike, no ejection
        assert router.replicas[0].consecutive_failures == 0
    finally:
        server.shutdown()
        a.stop()
        b.stop()


def test_500_is_never_failed_over():
    """A 500 (incl. poison) is a request-shaped fault: re-dispatching it
    would just take down a second fleet."""
    a, b = _Stub("a", mode="error500"), _Stub("b")
    router = _mk_router([a, b])
    server, base = _serve(router)
    try:
        _pin(router, LONG_PREFIX, "a")
        code, body, _ = _post(base, {"prompt": LONG_PREFIX + "q"})
        assert code == 500 and body["error_type"] == "poison"
        assert len(b.served()) == 0
        assert _counter(router, "dli_router_failovers_total", replica="a") == 0
    finally:
        server.shutdown()
        a.stop()
        b.stop()


def test_all_replicas_rejecting_propagates_retry_after():
    a, b = _Stub("a", mode="draining"), _Stub("b", mode="draining")
    a.retry_after = b.retry_after = "3"
    router = _mk_router([a, b])
    server, base = _serve(router)
    try:
        code, body, hdrs = _post(base, {"prompt": "anything"})
        assert code == 503
        assert body["status"] == "failed"
        # the upstream's own Retry-After reached the client end-to-end
        assert hdrs.get("Retry-After") == "3"
    finally:
        server.shutdown()
        a.stop()
        b.stop()


def test_router_ready_and_aggregated_health():
    a, b = _Stub("a"), _Stub("b")
    router = _mk_router([a, b])
    server, base = _serve(router)
    try:
        code, body, _ = _get(base, "/ready")
        assert code == 200 and body["ready"] is True
        code, body, _ = _get(base, "/health")
        assert code == 200 and body["status"] == "healthy"
        assert body["replicas_ready"] == 2
        # upstream /health bodies are aggregated per replica
        assert body["replicas"]["a"]["health"]["stub"] == "a"
        assert body["replicas"]["b"]["reachable"] is True
        for rep in router.replicas:
            rep.state = EJECTED
        code, body, hdrs = _get(base, "/ready")
        assert code == 503 and hdrs.get("Retry-After")
        code, body, _ = _get(base, "/health")
        assert body["status"] == "unhealthy" and body["replicas_ready"] == 0
    finally:
        server.shutdown()
        a.stop()
        b.stop()


def test_rolling_restart_rejected_for_url_replicas():
    a = _Stub("a")
    router = _mk_router([a])
    server, base = _serve(router)
    try:
        code, body, _ = _post(base, {}, path="/admin/rolling-restart")
        assert code == 400
        assert "router-spawned" in body["error"]
    finally:
        server.shutdown()
        a.stop()


# -- streaming discipline ----------------------------------------------------

def test_stream_never_fails_over_after_partial_output():
    a, b = _Stub("a", mode="stream_die"), _Stub("b")
    router = _mk_router([a, b])
    server, base = _serve(router)
    try:
        _pin(router, LONG_PREFIX, "a")
        c = DistributedLLMClient(base, max_retries=3, retry_backoff_s=0.01)
        r = c.generate_stream(LONG_PREFIX + "q", max_tokens=4)
        assert r.get("status") != "success"  # truncated stream surfaced
        # partial output reached the client: the router must NOT have
        # re-dispatched, and the client must not have retried
        assert len(a.served()) == 1
        assert len(b.served()) == 0
        assert _counter(router, "dli_router_failovers_total", replica="a") == 0
    finally:
        server.shutdown()
        a.stop()
        b.stop()


def test_stream_pre_stream_rejection_fails_over():
    a, b = _Stub("a", mode="draining"), _Stub("b")
    a.retry_after = "0"
    router = _mk_router([a, b])
    server, base = _serve(router)
    try:
        _pin(router, LONG_PREFIX, "a")
        c = DistributedLLMClient(base, max_retries=0)
        r = c.generate_stream(LONG_PREFIX + "q", max_tokens=4)
        # zero bytes had been streamed when a rejected: b served it whole
        assert r.get("status") == "success" and r["served_by"] == "b"
        assert len(b.served()) == 1
    finally:
        server.shutdown()
        a.stop()
        b.stop()


# -- client retry discipline THROUGH the router hop (satellite) ---------------

def test_client_retry_through_router_honors_retry_after_end_to_end():
    """Every replica rejects with Retry-After; the router propagates it;
    the client sleeps the server-directed delay and its retry succeeds
    once a replica recovers — the whole chain is server-paced."""
    a, b = _Stub("a", mode="draining"), _Stub("b", mode="draining")
    a.retry_after = b.retry_after = "0.4"
    router = _mk_router([a, b])
    server, base = _serve(router)
    try:
        def recover():
            time.sleep(0.15)
            a.mode = b.mode = "ok"
            for rep in router.replicas:
                rep.cooldown_until = 0.0  # cool-down elapsed in test time

        threading.Thread(target=recover, daemon=True).start()
        c = DistributedLLMClient(base, max_retries=3, retry_backoff_s=0.001)
        t0 = time.time()
        r = c.generate("retry me", verbose=False)
        elapsed = time.time() - t0
        assert r["status"] == "success"
        # waited the server-directed 0.4s, not the 1ms local backoff
        assert elapsed >= 0.4
    finally:
        server.shutdown()
        a.stop()
        b.stop()


def test_client_retry_through_router_is_bounded():
    a = _Stub("a", mode="draining")
    a.retry_after = "0"
    router = _mk_router([a])
    server, base = _serve(router)
    try:
        c = DistributedLLMClient(base, max_retries=2, retry_backoff_s=0.01)
        r = c.generate("never succeeds", verbose=False)
        assert r["status"] == "failed"
        # initial + 2 retries at the router -> one upstream try each
        assert len(a.served()) == 3
    finally:
        server.shutdown()
        a.stop()


# -- real-subprocess acceptance leg ------------------------------------------

SPAWN_ARGS = [
    "--model", "test-llama-tiny", "--continuous", "2",
    "--continuous-chunk", "4", "--max-tokens-cap", "64",
]
SLOW_PROMPT = "SLOWPOKE " + "the quick brown fox " * 4  # > affinity_chunk
COMPANION = "jumps over the lazy dog"
# hold SLOW_PROMPT's prefill open for 6s on the armed replica, then crash
# it transiently (the PR-5 supervisor would recover it bit-exact — unless
# we kill -9 the whole process first, which is the point)
VICTIM_FAULTS = "prefill:transient:match=SLOWPOKE,wedge=6,times=1"


def _spawn_env(faults=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("DLI_FAULTS", None)
    if faults:
        env["DLI_FAULTS"] = faults
    return env


@pytest.fixture(scope="module")
def fleet():
    """Two REAL engine servers behind an in-process router: r0 armed with
    the SLOWPOKE wedge (the designated kill -9 victim), r1 clean."""
    victim = spawn_replicas(1, SPAWN_ARGS, env=_spawn_env(VICTIM_FAULTS))[0]
    clean = spawn_replicas(1, SPAWN_ARGS, env=_spawn_env())[0]
    clean.rid = "r1"
    router = Router(
        [victim, clean], eject_threshold=3, probe_interval_s=0.25,
        probe_timeout_s=2.0, request_timeout_s=120.0, drain_deadline_s=60.0,
    )
    server = RouterServer(router, host="127.0.0.1", port=0)
    server.start()  # starts the live prober too
    try:
        yield router, server, f"http://127.0.0.1:{server.port}"
    finally:
        server.shutdown()  # SIGTERMs the spawned replicas
        for rep in router.replicas:
            if rep.proc is not None:
                try:
                    rep.proc.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    rep.proc.kill()


@pytest.fixture(scope="module")
def ref_engine():
    """Fault-free single-replica references: the same weights every
    spawned replica initializes (same model name, same default seed)."""
    from distributed_llm_inference_tpu import create_engine

    return create_engine("test-llama-tiny")


def _wait_state(router, rid, state, deadline_s):
    rep = next(r for r in router.replicas if r.rid == rid)
    t0 = time.time()
    while time.time() - t0 < deadline_s:
        if rep.state == state:
            return True
        time.sleep(0.05)
    return False


def test_kill9_midbatch_failover_bit_exact(fleet, ref_engine):
    """THE acceptance leg: kill -9 one replica mid-batch. The survivor
    keeps decoding, every in-flight non-streamed request completes via
    failover with output bit-identical to a fault-free single-replica
    run, the dead replica is ejected within the probe window and
    readmitted after restart, and the metrics reflect the episode."""
    router, server, base = fleet
    refs = {
        p: ref_engine.generate(p, max_tokens=10, greedy=True, chat=False)
        for p in (SLOW_PROMPT, COMPANION)
    }
    victim = router.replicas[0]
    assert victim.rid == "r0"
    # pin the wedge prompt to the armed replica (what a prior serve of
    # this prefix by r0 would have left in the residency map)
    _pin(router, SLOW_PROMPT, "r0")
    out = {}

    def fire(name, prompt):
        out[name] = _post(
            base,
            {"prompt": prompt, "max_tokens": 10, "greedy": True,
             "chat": False},
            timeout=120,
        )

    t_slow = threading.Thread(target=fire, args=("slow", SLOW_PROMPT))
    t_slow.start()
    # wait until the wedge request is IN FLIGHT on the victim
    t0 = time.time()
    while victim.outstanding == 0:
        assert time.time() - t0 < 30, "wedge request never dispatched"
        time.sleep(0.02)
    # mid-batch: a companion request decoding on the survivor
    t_comp = threading.Thread(target=fire, args=("comp", COMPANION))
    t_comp.start()
    time.sleep(0.5)  # inside the 6s wedge window
    victim.proc.kill()  # SIGKILL: no drain, no goodbye
    t_slow.join(timeout=120)
    t_comp.join(timeout=120)

    code, slow, _ = out["slow"]
    assert code == 200 and slow["status"] == "success", slow
    # bit-identical to the fault-free single-replica run, served elsewhere
    assert slow["response"] == refs[SLOW_PROMPT]["response"]
    assert slow["tokens_generated"] == refs[SLOW_PROMPT]["tokens_generated"]
    assert slow["replica"] == "r1"
    assert slow.get("router_attempts", 1) > 1
    code, comp, _ = out["comp"]
    assert code == 200 and comp["status"] == "success", comp
    assert comp["response"] == refs[COMPANION]["response"]
    assert _counter(router, "dli_router_failovers_total", replica="r0") >= 1

    # ejected within the probe window (threshold strikes at 0.25s period,
    # minus the passive strike the failed proxy already recorded)
    assert _wait_state(router, "r0", EJECTED, deadline_s=10), (
        "dead replica was never ejected"
    )
    assert _counter(router, "dli_router_replica_ready", replica="r0") == 0.0
    assert _counter(router, "dli_router_replica_ready", replica="r1") == 1.0
    assert _counter(router, "dli_router_ejections_total", replica="r0") >= 1

    # the survivor keeps serving while r0 is down
    code, body, _ = _post(
        base, {"prompt": "still serving", "max_tokens": 4, "greedy": True,
               "chat": False}, timeout=120,
    )
    assert code == 200 and body["replica"] == "r1"

    # restart the victim (same argv/env) -> probes readmit it
    victim.proc = subprocess.Popen(
        victim.spawn_argv, env=victim.spawn_env,
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
    )
    assert _wait_state(router, "r0", READY, deadline_s=120), (
        "restarted replica was never readmitted"
    )
    assert _counter(router, "dli_router_readmissions_total", replica="r0") >= 1
    assert _counter(router, "dli_router_replica_ready", replica="r0") == 1.0


def test_router_cli_spawn_mode_end_to_end():
    """The actual CLI: `python -m ...serving.router --spawn 1` brings up
    a replica subprocess, serves /generate through it, and SIGTERM tears
    both down."""
    port = _free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "distributed_llm_inference_tpu.serving.router",
         "--host", "127.0.0.1", "--port", str(port), "--spawn", "1",
         "--spawn-args", " ".join(SPAWN_ARGS), "--probe-interval", "0.5"],
        env=_spawn_env(), stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    base = f"http://127.0.0.1:{port}"
    try:
        t0 = time.time()
        while True:
            assert proc.poll() is None, (
                f"router exited rc={proc.returncode}:\n"
                + proc.stdout.read().decode(errors="replace")
            )
            try:
                if _get(base, "/ready")[0] == 200:
                    break
            except (urllib.error.URLError, OSError):
                pass
            assert time.time() - t0 < 300, "router never became ready"
            time.sleep(0.3)
        code, body, _ = _post(
            base, {"prompt": "cli smoke", "max_tokens": 4, "greedy": True,
                   "chat": False}, timeout=120,
        )
        assert code == 200 and body["status"] == "success"
        assert body["replica"] == "r0"
        with urllib.request.urlopen(base + "/metrics", timeout=15) as r:
            exposition = r.read().decode()
        assert "dli_router_requests_total" in exposition
        assert "dli_router_replica_ready" in exposition
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_rolling_restart_never_drops_a_request(fleet, ref_engine):
    """POST /admin/rolling-restart cycles the replicas one at a time via
    SIGTERM drain + respawn + /ready, under continuous client load: every
    request during the rollout succeeds (correctly), and both replicas
    end on fresh processes."""
    router, server, base = fleet
    for rid in ("r0", "r1"):
        assert _wait_state(router, rid, READY, deadline_s=120)
    ref = ref_engine.generate(
        "rolling load", max_tokens=4, greedy=True, chat=False
    )
    old_pids = {r.rid: r.proc.pid for r in router.replicas}
    stop = threading.Event()
    results = []

    def pump():
        c = DistributedLLMClient(base, timeout=120, max_retries=2,
                                 retry_backoff_s=0.1)
        while not stop.is_set():
            results.append(c.generate(
                "rolling load", max_tokens=4, greedy=True, chat=False,
                verbose=False,
            ))

    t = threading.Thread(target=pump)
    t.start()
    try:
        code, body, _ = _post(base, {}, path="/admin/rolling-restart")
        assert code == 202, body
        t0 = time.time()
        while time.time() - t0 < 300:
            if not _get(base, "/health")[1]["rolling_restart"]["active"]:
                break
            time.sleep(0.25)
        status = _get(base, "/health")[1]["rolling_restart"]
        assert status["active"] is False
        assert status["error"] is None, status
        assert status["done"] == ["r0", "r1"]
    finally:
        stop.set()
        t.join(timeout=120)
    assert results, "load pump never completed a request"
    failed = [r for r in results if r.get("status") != "success"]
    assert not failed, f"rolling restart dropped {len(failed)}: {failed[:3]}"
    # drained replicas really were replaced, and greedy output stayed exact
    for rep in router.replicas:
        assert rep.proc.pid != old_pids[rep.rid]
    assert all(r["response"] == ref["response"] for r in results)
    # a second rolling restart while one is active would 409/400 — but
    # after completion the endpoint accepts again (state machine reset)
    code, body, _ = _post(base, {}, path="/admin/rolling-restart")
    assert code == 202
    t0 = time.time()
    while time.time() - t0 < 300:
        if not _get(base, "/health")[1]["rolling_restart"]["active"]:
            break
        time.sleep(0.25)
    assert _get(base, "/health")[1]["rolling_restart"]["error"] is None


# -- wedge-driven ejection (warm-recovery PR satellite) ----------------------

WEDGE_ARGS = [
    "--model", "test-llama-tiny", "--deadline", "1",
    "--wedge-unready", "0.3", "--max-tokens-cap", "64", "--warmup",
]
# the solo point sleeps PAST the 1s deadline, so the engine abandons the
# call (engine._wedged fills) and only 7s later does the sleep drain
WEDGE_FAULTS = "solo:transient:match=WEDGEME,wedge=7,times=1"


def test_wedge_ejection_and_readmission_after_drain():
    """DLI_FAULTS wedge -> the replica's /ready flips 503 (reason
    'wedged', off engine.max_wedged_age past --wedge-unready) -> the
    router's probes eject it -> once the abandoned call drains, probes
    readmit it and it serves again. The liveness surface (/health) stays
    200 throughout: nothing reaps a process that can still recover."""
    rep = spawn_replicas(1, WEDGE_ARGS, env=_spawn_env(WEDGE_FAULTS))[0]
    router = Router(
        [rep], eject_threshold=2, probe_interval_s=0.2,
        probe_timeout_s=2.0, request_timeout_s=60.0, drain_deadline_s=30.0,
    )
    server = RouterServer(router, host="127.0.0.1", port=0)
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        assert _wait_state(router, "r0", READY, deadline_s=300)
        # sanity: a clean request serves (and warms the solo programs so
        # the wedge request's 1s deadline is all wedge, not compile)
        code, body, _ = _post(
            base, {"prompt": "clean", "max_tokens": 2, "greedy": True,
                   "chat": False}, timeout=120,
        )
        assert code == 200 and body["status"] == "success", body

        # fire the wedge prompt; the replica answers 503 timeout after
        # its 1s deadline while the device call stays stuck for 7s
        out = {}

        def fire():
            out["r"] = _post(
                base, {"prompt": "WEDGEME now", "max_tokens": 4,
                       "greedy": True, "chat": False}, timeout=60,
            )

        t = threading.Thread(target=fire)
        t.start()
        # ejection: probes see /ready 503 (reason wedged) and strike it
        # out within the probe window
        assert _wait_state(router, "r0", EJECTED, deadline_s=15), (
            "wedged replica was never ejected"
        )
        code, body, _ = _get(base, "/ready")  # router itself: no replica
        assert code == 503
        # the replica's own readiness says WHY, and its liveness is 200
        rcode, rbody, _ = _get(rep.url, "/ready")
        assert rcode == 503 and rbody["reason"] == "wedged", rbody
        hcode, hbody, _ = _get(rep.url, "/health")
        assert hcode == 200 and hbody["ready_reason"] == "wedged"
        t.join(timeout=60)
        code, body, _ = out["r"]
        assert body.get("error_type") == "timeout", body

        # the abandoned call drains (the 7s sleep ends) -> /ready 200 ->
        # probes readmit without any restart
        assert _wait_state(router, "r0", READY, deadline_s=30), (
            "replica was never readmitted after the wedge drained"
        )
        assert _counter(
            router, "dli_router_readmissions_total", replica="r0"
        ) >= 1
        code, body, _ = _post(
            base, {"prompt": "after the wedge", "max_tokens": 2,
                   "greedy": True, "chat": False}, timeout=120,
        )
        assert code == 200 and body["status"] == "success", body
    finally:
        server.shutdown()
        if rep.proc is not None:
            try:
                rep.proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                rep.proc.kill()
