"""LoRA adapter merge-at-load (models/lora.py).

The fixture is a REAL PEFT adapter (peft.get_peft_model ->
save_pretrained), so the tensor naming and adapter_config.json are the
actual on-disk format; parity target is HF's own merge_and_unload().
Beyond-reference feature: the reference serves full checkpoints only
(/root/reference/Worker1.py:60).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")
peft = pytest.importorskip("peft")

from distributed_llm_inference_tpu import EngineConfig, create_engine
from distributed_llm_inference_tpu.models import llama
from distributed_llm_inference_tpu.models.convert import params_from_hf_model
from distributed_llm_inference_tpu.models.lora import merge_lora


def _tiny_hf():
    cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=10000.0,
        pad_token_id=0, eos_token_id=2, bos_token_id=1,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    return model


@pytest.fixture(scope="module")
def adapter(tmp_path_factory):
    """(base hf model, merged hf model, adapter dir) — adapter weights are
    randomized so the delta is nonzero."""
    base = _tiny_hf()
    lcfg = peft.LoraConfig(
        r=4, lora_alpha=16,
        target_modules=["q_proj", "v_proj", "gate_proj", "down_proj"],
        lora_dropout=0.0, task_type="CAUSAL_LM",
    )
    pm = peft.get_peft_model(_tiny_hf(), lcfg)
    torch.manual_seed(7)
    with torch.no_grad():
        for name, p in pm.named_parameters():
            if "lora_" in name:
                p.copy_(torch.randn_like(p) * 0.1)
    d = str(tmp_path_factory.mktemp("adapter"))
    pm.save_pretrained(d)
    import os

    sub = [x for x in os.listdir(d) if
           os.path.exists(os.path.join(d, x, "adapter_config.json"))]
    adir = os.path.join(d, sub[0]) if sub else d
    merged = pm.merge_and_unload()
    merged.eval()
    return base, merged, adir


@pytest.mark.slow
def test_merge_matches_hf_merge_and_unload(adapter):
    base, merged_hf, adir = adapter
    cfg, params = params_from_hf_model(base, dtype="float32")
    merged = merge_lora(cfg, params, adir)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(2, 13), dtype=np.int64)
    with torch.no_grad():
        hf_logits = merged_hf(torch.from_numpy(tokens)).logits.numpy()
    cache = llama.init_kv_cache(cfg, batch=2, max_seq=32)
    logits, _ = llama.forward(
        cfg, merged, jnp.asarray(tokens, jnp.int32), cache, jnp.int32(0)
    )
    np.testing.assert_allclose(np.asarray(logits), hf_logits,
                               rtol=3e-4, atol=3e-4)
    # untargeted leaves unchanged; targeted ones actually moved
    np.testing.assert_array_equal(
        np.asarray(merged["layers"]["wk"]), np.asarray(params["layers"]["wk"])
    )
    assert not np.allclose(
        np.asarray(merged["layers"]["wq"]), np.asarray(params["layers"]["wq"])
    )


@pytest.mark.slow
def test_create_engine_with_lora_and_quant(adapter):
    """--lora composes with --quant: merge first, then quantize the merged
    dense weights."""
    base, merged_hf, adir = adapter
    cfg, params = params_from_hf_model(base, dtype="float32")
    eng = create_engine(
        cfg.replace(quant="int8"), params=params, lora=adir,
        engine_cfg=EngineConfig(prefill_buckets=(32,)),
    )
    r = eng.generate("lora quant", max_tokens=4, greedy=True, chat=False)
    assert r["status"] == "success", r


@pytest.mark.slow
def test_merge_rejects_quantized_params(adapter):
    from distributed_llm_inference_tpu.ops.quant import quantize_params

    base, _, adir = adapter
    cfg, params = params_from_hf_model(base, dtype="float32")
    qp = quantize_params(cfg, params, mode="int8")
    with pytest.raises(ValueError, match="quantized"):
        merge_lora(cfg, qp, adir)


@pytest.mark.slow
def test_rslora_scale_matches_hf(tmp_path):
    """use_rslora adapters scale by alpha/sqrt(r); the merge must match
    HF's own rsLoRA merge, not be off by sqrt(r)."""
    base = _tiny_hf()
    lcfg = peft.LoraConfig(
        r=4, lora_alpha=8, use_rslora=True, target_modules=["q_proj"],
        lora_dropout=0.0, task_type="CAUSAL_LM",
    )
    pm = peft.get_peft_model(_tiny_hf(), lcfg)
    torch.manual_seed(9)
    with torch.no_grad():
        for name, p in pm.named_parameters():
            if "lora_" in name:
                p.copy_(torch.randn_like(p) * 0.1)
    d = str(tmp_path / "rslora")
    pm.save_pretrained(d)
    merged_hf = pm.merge_and_unload()
    merged_hf.eval()

    cfg, params = params_from_hf_model(base, dtype="float32")
    merged = merge_lora(cfg, params, d)
    rng = np.random.default_rng(2)
    tokens = rng.integers(0, cfg.vocab_size, size=(1, 11), dtype=np.int64)
    with torch.no_grad():
        hf_logits = merged_hf(torch.from_numpy(tokens)).logits.numpy()
    cache = llama.init_kv_cache(cfg, batch=1, max_seq=32)
    logits, _ = llama.forward(
        cfg, merged, jnp.asarray(tokens, jnp.int32), cache, jnp.int32(0)
    )
    np.testing.assert_allclose(np.asarray(logits), hf_logits,
                               rtol=3e-4, atol=3e-4)


@pytest.mark.slow
def test_merge_rejects_math_changing_variants(adapter, tmp_path):
    """DoRA / modules_to_save / partial-layer configs must be rejected
    loudly — a silently-wrong merged model is the worst failure mode."""
    import json as _json
    import shutil

    base, _, adir = adapter
    cfg, params = params_from_hf_model(base, dtype="float32")
    for patch, msg in [
        ({"use_dora": True}, "DoRA"),
        ({"modules_to_save": ["lm_head"]}, "modules_to_save"),
        ({"layers_to_transform": [1]}, "layers_to_transform"),
        ({"bias": "lora_only"}, "bias"),
        ({"alpha_pattern": {"q_proj": 32}}, "alpha_pattern"),
    ]:
        d = str(tmp_path / f"patched_{msg}")
        shutil.copytree(adir, d)
        with open(f"{d}/adapter_config.json") as f:
            acfg = _json.load(f)
        acfg.update(patch)
        with open(f"{d}/adapter_config.json", "w") as f:
            _json.dump(acfg, f)
        with pytest.raises(ValueError, match=msg):
            merge_lora(cfg, params, d)


def test_merge_rejects_missing_adapter(tmp_path):
    cfg_dir = str(tmp_path / "nope")
    from distributed_llm_inference_tpu.models.registry import get_model_config
    from distributed_llm_inference_tpu.models import api as M

    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    with pytest.raises(FileNotFoundError):
        merge_lora(cfg, params, cfg_dir)
