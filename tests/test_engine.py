"""Decode-engine tests: schema, EOS semantics, determinism, bounds.

The response schema is the reference's API contract
(/root/reference/orchestration.py:211-218); EOS break-before-append is
orchestration.py:181-186.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llm_inference_tpu import EngineConfig, get_model_config
from distributed_llm_inference_tpu.engine.engine import InferenceEngine, SingleDeviceBackend
from distributed_llm_inference_tpu.models import llama


@pytest.fixture(scope="module")
def tiny_engine():
    cfg = get_model_config("test-llama-tiny")
    return InferenceEngine(cfg, engine_cfg=EngineConfig(prefill_buckets=(64, 128)))


def _zero_params(cfg):
    """All-zero params -> logits identically zero -> greedy argmax is
    always token 0. Lets us pin EOS semantics deterministically."""
    p = llama.init_params(cfg, jax.random.PRNGKey(0))
    return jax.tree.map(jnp.zeros_like, p)


def test_response_schema(tiny_engine):
    r = tiny_engine.generate("hello world", max_tokens=8, seed=0)
    assert r["status"] == "success"
    for k in ("prompt", "response", "time_taken", "tokens_generated", "tokens_per_sec"):
        assert k in r, k
    assert r["prompt"] == "hello world"
    assert isinstance(r["tokens_generated"], int)
    assert r["time_taken"].endswith("s")
    assert r["ttft_s"] > 0
    assert 0 < r["tokens_generated"] <= 8


def test_eos_immediate_stop():
    """argmax token == EOS from the very first sample -> zero tokens,
    empty response (reference breaks before appending EOS)."""
    cfg = get_model_config("test-llama-tiny").replace(eos_token_id=0, pad_token_id=3)
    eng = InferenceEngine(
        cfg,
        backend=SingleDeviceBackend(cfg, _zero_params(cfg)),
        engine_cfg=EngineConfig(prefill_buckets=(32,)),
    )
    r = eng.generate("hi", max_tokens=8, greedy=True, chat=False)
    assert r["status"] == "success"
    assert r["tokens_generated"] == 0
    assert r["response"] == ""


@pytest.mark.slow
def test_no_eos_runs_to_max_tokens():
    """With EOS unreachable (argmax is always 0, eos=5), the loop must emit
    exactly max_tokens tokens."""
    cfg = get_model_config("test-llama-tiny").replace(eos_token_id=5, pad_token_id=3)
    eng = InferenceEngine(
        cfg,
        backend=SingleDeviceBackend(cfg, _zero_params(cfg)),
        engine_cfg=EngineConfig(prefill_buckets=(32,)),
    )
    r = eng.generate("hi", max_tokens=6, greedy=True, chat=False)
    assert r["tokens_generated"] == 6


def test_debug_top_predictions(tiny_engine):
    """debug=True returns the top-5 first-token candidates with probs
    (the reference's debug prints, orchestration.py:172-178)."""
    r = tiny_engine.generate("debug me", max_tokens=3, greedy=True, debug=True)
    assert r["status"] == "success"
    preds = r["top_predictions"]
    assert len(preds) == 5
    probs = [p["prob"] for p in preds]
    assert probs == sorted(probs, reverse=True)
    assert all(0.0 <= p <= 1.0 for p in probs)
    assert all(isinstance(p["id"], int) for p in preds)
    # off by default
    r2 = tiny_engine.generate("debug me", max_tokens=3, greedy=True)
    assert "top_predictions" not in r2


def test_seeded_determinism(tiny_engine):
    r1 = tiny_engine.generate("same seed", max_tokens=10, seed=42)
    r2 = tiny_engine.generate("same seed", max_tokens=10, seed=42)
    assert r1["response"] == r2["response"]


def test_greedy_matches_manual_decode():
    """Engine greedy output == a hand-rolled argmax loop over the raw model."""
    cfg = get_model_config("test-llama-tiny")
    eng = InferenceEngine(cfg, engine_cfg=EngineConfig(prefill_buckets=(32,)))
    params = eng.backend.params

    r = eng.generate("abc", max_tokens=5, greedy=True, chat=False)

    ids = eng.tokenizer.encode("abc")
    cache = llama.init_kv_cache(cfg, 1, max_seq=cfg.max_seq_len)
    logits, cache = llama.forward(
        cfg, params, jnp.asarray([ids], jnp.int32), cache, jnp.int32(0)
    )
    tok = int(jnp.argmax(logits[0, -1]))
    manual = []
    pos = len(ids)
    while len(manual) < 5 and tok != cfg.eos_token_id:
        manual.append(tok)
        lg, cache = llama.forward(
            cfg, params, jnp.asarray([[tok]], jnp.int32), cache, jnp.int32(pos)
        )
        tok = int(jnp.argmax(lg[0, -1]))
        pos += 1
    assert r["response"] == eng.tokenizer.decode(manual)


def test_prompt_too_long_fails_cleanly(tiny_engine):
    r = tiny_engine.generate("x" * 500, max_tokens=4)
    assert r["status"] == "failed"
    assert "error" in r


def test_max_tokens_clamped_by_cache_capacity():
    cfg = get_model_config("test-llama-tiny").replace(max_seq_len=48, eos_token_id=5)
    eng = InferenceEngine(
        cfg,
        backend=SingleDeviceBackend(cfg, _zero_params(cfg)),
        engine_cfg=EngineConfig(prefill_buckets=(32,)),
    )
    # prompt ~4 tokens; request far more than fits -> clamped, still succeeds
    r = eng.generate("hi", max_tokens=1000, greedy=True, chat=False)
    assert r["status"] == "success"
    assert r["tokens_generated"] <= 48


def test_health_and_workers(tiny_engine):
    h = tiny_engine.health()
    assert h["status"] == "healthy" and h["n_stages"] == 1
    w = tiny_engine.workers()
    assert w["total"] == 1 and w["workers"]["stage_0"]["status"] == "online"


@pytest.mark.slow
def test_warmup_compiles_and_requests_stay_fast():
    """warmup() precompiles all bucket programs; a following request works
    and reuses the warmed cache buffer."""
    import time as _time

    from distributed_llm_inference_tpu import EngineConfig, create_engine

    engine = create_engine(
        "test-llama-tiny",
        engine_cfg=EngineConfig(prefill_buckets=(32, 64)),
    )
    stats = engine.warmup(decode_buckets=(16,), batch_buckets=())
    # 2 prefill buckets + 1 chunked-prefill extend + 1 decode bucket
    # + presence (repetition-penalty) variants: 2 prefill + 1 decode
    # + 1 logprobs decode variant + 1 speculative decode bucket
    assert stats["programs"] == 9
    t0 = _time.time()
    r = engine.generate("hi", max_tokens=3, greedy=True, chat=False)
    assert r["status"] == "success"
    # warm path: no multi-second jit compile inside the request
    assert _time.time() - t0 < 5.0


@pytest.mark.slow  # re-tiered round 5: warmup compiles every batched
# bucket — by far the heaviest engine test, covered daily by serving tests
def test_warmup_covers_batched_programs():
    """Round-1 gap: the first batched request on a warmed server must not
    pay a compile — warmup pre-compiles the ragged (batch bucket x prefill
    bucket x decode bucket) programs and leaves warm per-bucket caches."""
    import time as _time

    from distributed_llm_inference_tpu import EngineConfig, create_engine

    engine = create_engine(
        "test-llama-tiny",
        engine_cfg=EngineConfig(prefill_buckets=(32,)),
    )
    stats = engine.warmup(decode_buckets=(16,), batch_buckets=(2,))
    # singles: 1 prefill + 1 extend + 1 decode + presence variants
    # (1 prefill + 1 decode) + 1 logprobs decode + 1 speculative decode;
    # batch-2: 1 prefill + 1 decode
    assert stats["programs"] == 9
    assert 2 in engine._batch_caches  # warm reusable cache left behind

    # the warmed engine's batched request must not trace/compile anything
    # new (the jit trace caches are the compile-count ground truth; wall
    # clock can't distinguish — jit caching is process-global)
    from distributed_llm_inference_tpu.engine import generate as G

    n0 = G.prefill._cache_size() + G.decode._cache_size()
    r = engine.generate_batch(["a", "bb"], max_tokens=3, greedy=True, chat=False)
    assert r["status"] == "success", r
    n1 = G.prefill._cache_size() + G.decode._cache_size()
    assert n1 == n0, f"batched request compiled {n1 - n0} new program(s)"
