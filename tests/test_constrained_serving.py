"""Structured output over HTTP: OpenAI `response_format` round-trips on
/v1/chat/completions (valid JSON parsed from the response for every schema
in the corpus), the `"constraint"` field on /generate, and the 400 surface
for malformed specs and unsupported combos — all over real HTTP against a
served tiny model (same harness as test_openai_api)."""

import json
import re
import urllib.error
import urllib.request

import pytest

from distributed_llm_inference_tpu import (
    EngineConfig, create_engine, get_model_config,
)
from distributed_llm_inference_tpu.serving.server import InferenceServer


@pytest.fixture(scope="module")
def served():
    # a longer window than the stock tiny config: the chat template eats a
    # 64-token prefill bucket and a schema-shaped JSON object needs up to
    # ~150 decode tokens — the decode budget is max_seq - bucket - 1
    engine = create_engine(
        get_model_config("test-llama-tiny", max_seq_len=512),
        engine_cfg=EngineConfig(prefill_buckets=(64, 128)),
    )
    server = InferenceServer(engine, host="127.0.0.1", port=0,
                             max_tokens_cap=256)
    server.start()
    yield server
    server.shutdown()


def _post(server, path, body, timeout=300):
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _post_err(server, path, body):
    try:
        _post(server, path, body)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())
    raise AssertionError("expected an HTTP error")


SCHEMAS = [
    {"type": "object",
     "properties": {"name": {"type": "string"}, "age": {"type": "integer"}},
     "required": ["name", "age"]},
    {"type": "object",
     "properties": {"color": {"enum": ["red", "green", "blue"]},
                    "ok": {"type": "boolean"}},
     "required": ["color", "ok"]},
    {"type": "object",
     "properties": {"items": {"type": "array",
                              "items": {"type": "integer"}}},
     "required": ["items"]},
]


@pytest.mark.parametrize("schema", SCHEMAS)
def test_response_format_json_schema_round_trip(served, schema):
    """Acceptance: valid JSON parsed from the response for every schema in
    the corpus, over the real OpenAI route."""
    out = _post(served, "/v1/chat/completions", {
        "model": "test-llama-tiny",
        "messages": [{"role": "user", "content": "emit the object"}],
        "max_tokens": 200,
        "temperature": 0,
        "response_format": {"type": "json_schema",
                            "json_schema": {"name": "obj", "schema": schema}},
    })
    text = out["choices"][0]["message"]["content"]
    obj = json.loads(text)  # MUST parse — that's the whole feature
    for k in schema.get("required", []):
        assert k in obj, (schema, text)


def test_response_format_json_object(served):
    out = _post(served, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "give me json"}],
        "max_tokens": 200,
        "temperature": 0,
        "response_format": {"type": "json_object"},
    })
    obj = json.loads(out["choices"][0]["message"]["content"])
    assert isinstance(obj, dict)


def test_response_format_sampled_round_trip(served):
    out = _post(served, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "emit"}],
        "max_tokens": 200,
        "temperature": 1.4,
        "seed": 5,
        "response_format": {"type": "json_schema",
                            "json_schema": {"schema": SCHEMAS[0]}},
    })
    obj = json.loads(out["choices"][0]["message"]["content"])
    assert isinstance(obj["age"], int)


def test_response_format_text_is_noop(served):
    out = _post(served, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "hi"}],
        "max_tokens": 5, "temperature": 0,
        "response_format": {"type": "text"},
    })
    assert out["choices"][0]["finish_reason"] in ("stop", "length")


def test_response_format_malformed_400(served):
    for rf in ("json", {"type": "yaml"}, {"type": "json_schema"},
               {"type": "json_schema", "json_schema": {"schema": "x"}}):
        code, body = _post_err(served, "/v1/chat/completions", {
            "messages": [{"role": "user", "content": "x"}],
            "response_format": rf,
        })
        assert code == 400, rf
        assert body["error"]["param"] == "response_format"


def test_response_format_rejected_on_completions(served):
    code, body = _post_err(served, "/v1/completions", {
        "prompt": "x", "response_format": {"type": "json_object"},
    })
    assert code == 400
    assert body["error"]["param"] == "response_format"


def test_unsupported_schema_is_400_not_500(served):
    code, body = _post_err(served, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "x"}],
        "response_format": {"type": "json_schema",
                            "json_schema": {"schema": {"type": "tuple"}}},
    })
    assert code == 400
    assert "invalid_request" in body["error"]["type"]


# -- native /generate "constraint" field -------------------------------------

def test_generate_constraint_regex(served):
    out = _post(served, "/generate", {
        "prompt": "pick a color:", "chat": False, "greedy": True,
        "max_tokens": 20, "constraint": {"regex": "(red|green|blue)"},
    })
    assert out["status"] == "success"
    assert re.fullmatch("red|green|blue", out["response"])
    assert out.get("constrained") is True


def test_generate_constraint_choices_and_schema(served):
    out = _post(served, "/generate", {
        "prompt": "pick:", "chat": False, "greedy": True, "max_tokens": 20,
        "constraint": {"choices": ["on", "off"]},
    })
    assert out["response"] in ("on", "off")
    out = _post(served, "/generate", {
        "prompt": "emit:", "chat": False, "greedy": True, "max_tokens": 200,
        "constraint": {"json_schema": SCHEMAS[0]},
    })
    assert isinstance(json.loads(out["response"])["age"], int)


def test_generate_constraint_batched_prompts(served):
    out = _post(served, "/generate", {
        "prompts": ["a:", "b:"], "chat": False, "greedy": True,
        "max_tokens": 20, "constraint": {"regex": "[0-9]{2,3}"},
    })
    assert out["status"] == "success"
    for e in out["results"]:
        assert re.fullmatch(r"[0-9]{2,3}", e["response"]), e


def test_generate_constraint_400s(served):
    # malformed spec shapes -> 400, never 500
    for con in ("regex", {"regex": ""}, {"bogus": 1},
                {"regex": "a", "choices": ["b"]}, {"regex": "(unclosed"}):
        code, body = _post_err(served, "/generate", {
            "prompt": "x", "constraint": con,
        })
        assert code == 400, con
    # unsupported combos: constraint x speculative / x beam
    code, body = _post_err(served, "/generate", {
        "prompt": "x", "greedy": True, "speculative": True,
        "constraint": {"regex": "a+"},
    })
    assert code == 400 and "speculative" in body["error"]
    code, body = _post_err(served, "/generate", {
        "prompt": "x", "num_beams": 4, "constraint": {"regex": "a+"},
    })
    assert code == 400 and "num_beams" in body["error"]
