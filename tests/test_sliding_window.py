"""Sliding-window attention (Mistral-style) vs HF reference numerics."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llm_inference_tpu.models import api as M
from distributed_llm_inference_tpu.models.registry import get_model_config
from distributed_llm_inference_tpu.ops.attention import causal_mask
from distributed_llm_inference_tpu.ops.flash_attention import flash_attend


def test_window_mask_shape():
    m = np.asarray(causal_mask(jnp.int32(0), 8, 8, window=3))
    # query t attends kv in (t-3, t]
    for t in range(8):
        for s in range(8):
            assert m[t, s] == (s <= t and s > t - 3), (t, s)


def test_flash_window_matches_masked_attend():
    from distributed_llm_inference_tpu.ops.attention import attend

    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    B, T, H, KV, Dh, S, pos, W = 1, 12, 4, 2, 32, 64, 7, 5
    q = jax.random.normal(ks[0], (B, T, H, Dh), jnp.float32)
    ck = jax.random.normal(ks[1], (B, KV, S, Dh), jnp.float32)
    cv = jax.random.normal(ks[2], (B, KV, S, Dh), jnp.float32)
    p = jnp.int32(pos)
    ref = attend(q, ck, cv, causal_mask(p, T, S, window=W))
    got = flash_attend(q, ck, cv, p, window=W)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=2e-5)


def test_windowed_forward_matches_hf_mistral_layer():
    """Full tiny model logits vs a transformers Mistral with the same
    weights (converter round-trip), prefill + one decode step."""
    transformers = pytest.importorskip("transformers")
    torch = pytest.importorskip("torch")

    from distributed_llm_inference_tpu.models.convert import params_from_hf_model

    hf_cfg = transformers.MistralConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, sliding_window=4, rms_norm_eps=1e-5,
    )
    torch.manual_seed(0)
    hf_model = transformers.MistralForCausalLM(hf_cfg).eval()
    cfg, params = params_from_hf_model(hf_model)
    assert cfg.attn_window == 4

    ids = np.array([[1, 5, 9, 13, 17, 21, 25, 29, 33, 37]])  # len 10 > window
    with torch.no_grad():
        ref = hf_model(torch.from_numpy(ids)).logits.numpy()

    cache = M.init_kv_cache(cfg, 1, max_seq=32)
    logits, cache = M.forward(
        cfg, params, jnp.asarray(ids, jnp.int32), cache, jnp.int32(0)
    )
    np.testing.assert_allclose(
        np.asarray(logits), ref, rtol=2e-4, atol=2e-4
    )

    # decode step: HF full-sequence forward vs our cached step
    ids2 = np.concatenate([ids, [[41]]], axis=1)
    with torch.no_grad():
        ref2 = hf_model(torch.from_numpy(ids2)).logits.numpy()[:, -1:, :]
    logits2, _ = M.forward(
        cfg, params, jnp.asarray([[41]], jnp.int32), cache, jnp.int32(10)
    )
    np.testing.assert_allclose(
        np.asarray(logits2), ref2, rtol=2e-4, atol=2e-4
    )
