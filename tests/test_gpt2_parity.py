"""Logits parity: our JAX GPT-2 vs a tiny-random HF GPT2LMHeadModel
(BASELINE configs 1-2 use GPT-2-small/medium). Offline: built from config."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from distributed_llm_inference_tpu.models import gpt2
from distributed_llm_inference_tpu.models.convert import params_from_hf_model

# fast-tier exclusion: HF-parity family file; run the full suite (plain
# `pytest`) to include it
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def hf_and_ours():
    cfg = transformers.GPT2Config(
        vocab_size=256,
        n_positions=128,
        n_embd=64,
        n_layer=4,
        n_head=4,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.GPT2LMHeadModel(cfg)
    model.eval()
    ours_cfg, ours_params = params_from_hf_model(model, dtype="float32")
    return model, ours_cfg, ours_params


def test_logits_match_hf(hf_and_ours):
    hf, cfg, params = hf_and_ours
    assert cfg.arch == "gpt2" and cfg.tie_embeddings
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(2, 13), dtype=np.int64)
    with torch.no_grad():
        hf_logits = hf(torch.from_numpy(tokens)).logits.numpy()
    cache = gpt2.init_kv_cache(cfg, batch=2, max_seq=32)
    logits, _ = gpt2.forward(
        cfg, params, jnp.asarray(tokens, jnp.int32), cache, jnp.int32(0)
    )
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=2e-4, atol=2e-4)


def test_incremental_decode_matches_full_forward(hf_and_ours):
    _, cfg, params = hf_and_ours
    rng = np.random.default_rng(1)
    T = 10
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(1, T)), jnp.int32)
    cache = gpt2.init_kv_cache(cfg, batch=1, max_seq=32)
    full_logits, _ = gpt2.forward(cfg, params, tokens, cache, jnp.int32(0))

    cache = gpt2.init_kv_cache(cfg, batch=1, max_seq=32)
    _, cache = gpt2.forward(cfg, params, tokens[:, :4], cache, jnp.int32(0))
    for t in range(4, T):
        step_logits, cache = gpt2.forward(
            cfg, params, tokens[:, t : t + 1], cache, jnp.int32(t)
        )
        np.testing.assert_allclose(
            np.asarray(step_logits[:, 0]),
            np.asarray(full_logits[:, t]),
            rtol=1e-4,
            atol=1e-5,
        )


def test_engine_serves_gpt2():
    """The decode engine must serve the GPT-2 family through the same path
    (config 1 of BASELINE.json is GPT-2-small single-worker)."""
    from distributed_llm_inference_tpu import EngineConfig, get_model_config
    from distributed_llm_inference_tpu.engine.engine import InferenceEngine

    cfg = get_model_config("test-gpt2-tiny")
    eng = InferenceEngine(cfg, engine_cfg=EngineConfig(prefill_buckets=(32,)))
    r = eng.generate("hello", max_tokens=6, greedy=True, chat=False, seed=0)
    assert r["status"] == "success"
    assert 0 <= r["tokens_generated"] <= 6
