"""Microbatched-pipeline (1F1B-style) equivalence tests: the zero-bubble
round-robin schedule must produce exactly the tokens the single-device
model produces, row for row, on the 8-virtual-CPU-device mesh (SURVEY.md §4
item 3; BASELINE.json config 5)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llm_inference_tpu import MeshConfig, get_model_config
from distributed_llm_inference_tpu.engine import generate as G
from distributed_llm_inference_tpu.models import api as M
from distributed_llm_inference_tpu.parallel.mesh import build_mesh
from distributed_llm_inference_tpu.parallel.schedule import MicrobatchPipelineBackend


def _prompt_batch(cfg, batch, plen, bucket, seed=0):
    rng = np.random.default_rng(seed)
    rows = rng.integers(3, min(cfg.vocab_size, 250), size=(batch, plen), dtype=np.int64)
    padded = np.pad(rows, ((0, 0), (0, bucket - plen)), constant_values=cfg.pad_token_id)
    return jnp.asarray(padded, jnp.int32)


def _single_device_reference(cfg, params, tokens, plen, steps, kp, kd, sampling):
    cache = M.init_kv_cache(cfg, tokens.shape[0], max_seq=64)
    first, logits, cache = G.prefill(cfg, params, tokens, plen, cache, kp, sampling)
    out, n_gen, _ = G.decode(
        cfg, params, first, cache, plen, jnp.int32(steps), kd, sampling, max_steps=steps
    )
    return first, logits, out, n_gen


@pytest.mark.parametrize("pp,mb", [(2, 2), (4, 4), (2, 4)])
@pytest.mark.slow
def test_microbatch_prefill_matches_single_device(pp, mb, eight_devices):
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(MeshConfig(dp=1, pp=pp, tp=1), eight_devices)
    # opt into full prefill logits (the serving default returns a
    # zero-width logits array and psums only the sampled token)
    be = MicrobatchPipelineBackend(
        cfg, params, mesh, n_microbatches=mb, return_prefill_logits=True
    )

    batch, plen, bucket = mb * 2, 9, 16
    tokens = _prompt_batch(cfg, batch, plen, bucket)
    sampling = G.default_sampling(greedy=True)
    key = jax.random.PRNGKey(1)

    cache_s = M.init_kv_cache(cfg, batch, max_seq=64)
    f_s, logits_s, _ = G.prefill(cfg, params, tokens, jnp.int32(plen), cache_s, key, sampling)

    f_p, logits_p, _ = be.prefill(tokens, jnp.int32(plen), be.init_cache(batch, 64), key, sampling)

    np.testing.assert_allclose(
        np.asarray(logits_p), np.asarray(logits_s), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_array_equal(np.asarray(f_p), np.asarray(f_s))


@pytest.mark.parametrize("cfg_name", ["test-llama-tiny", "test-gpt2-tiny"])
@pytest.mark.slow
def test_microbatch_decode_matches_single_device(cfg_name, eight_devices):
    """Greedy prefill+decode, 2 stages x 2 microbatches, both families."""
    cfg = get_model_config(cfg_name)
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(MeshConfig(dp=1, pp=2, tp=1), eight_devices)
    be = MicrobatchPipelineBackend(cfg, params, mesh)

    batch, plen, bucket, steps = 4, 7, 16, 8
    tokens = _prompt_batch(cfg, batch, plen, bucket, seed=2)
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(3))

    f_s, _, out_s, n_s = _single_device_reference(
        cfg, params, tokens, jnp.int32(plen), steps, kp, kd, sampling
    )
    cache = be.init_cache(batch, 64)
    f_p, _, cache = be.prefill(tokens, jnp.int32(plen), cache, kp, sampling)
    out_p, n_p, _ = be.decode(
        f_p, cache, jnp.int32(plen), jnp.int32(steps), kd, sampling, max_steps=steps
    )

    np.testing.assert_array_equal(np.asarray(f_p), np.asarray(f_s))
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out_s))
    np.testing.assert_array_equal(np.asarray(n_p), np.asarray(n_s))


@pytest.mark.slow
def test_microbatch_full_mesh_dp_pp_tp(eight_devices):
    """All three mesh axes + microbatching: dp=2 x pp=2 x tp=2, batch=8."""
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(MeshConfig(dp=2, pp=2, tp=2), eight_devices)
    be = MicrobatchPipelineBackend(cfg, params, mesh)

    batch, plen, bucket, steps = 8, 5, 16, 6
    tokens = _prompt_batch(cfg, batch, plen, bucket, seed=4)
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(5))

    f_s, _, out_s, n_s = _single_device_reference(
        cfg, params, tokens, jnp.int32(plen), steps, kp, kd, sampling
    )
    cache = be.init_cache(batch, 64)
    f_p, _, cache = be.prefill(tokens, jnp.int32(plen), cache, kp, sampling)
    out_p, n_p, _ = be.decode(
        f_p, cache, jnp.int32(plen), jnp.int32(steps), kd, sampling, max_steps=steps
    )

    np.testing.assert_array_equal(np.asarray(f_p), np.asarray(f_s))
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out_s))
    np.testing.assert_array_equal(np.asarray(n_p), np.asarray(n_s))


@pytest.mark.slow
def test_microbatch_eos_early_exit(eight_devices):
    """Per-row EOS finishing + per-microbatch done gating: pick the token
    greedy decode emits mid-stream as the EOS id and check both backends
    truncate identically."""
    base = get_model_config("test-llama-tiny", eos_token_id=-1)
    params = M.init_params(base, jax.random.PRNGKey(0))
    batch, plen, bucket, steps = 4, 6, 16, 8
    tokens = _prompt_batch(base, batch, plen, bucket, seed=6)
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(7))

    _, _, out_free, _ = _single_device_reference(
        base, params, tokens, jnp.int32(plen), steps, kp, kd, sampling
    )
    eos = int(np.asarray(out_free)[0, 3])  # token row 0 emits at step 3

    cfg = base.replace(eos_token_id=eos)
    f_s, _, out_s, n_s = _single_device_reference(
        cfg, params, tokens, jnp.int32(plen), steps, kp, kd, sampling
    )
    mesh = build_mesh(MeshConfig(dp=1, pp=2, tp=1), eight_devices)
    be = MicrobatchPipelineBackend(cfg, params, mesh)
    cache = be.init_cache(batch, 64)
    f_p, _, cache = be.prefill(tokens, jnp.int32(plen), cache, kp, sampling)
    out_p, n_p, _ = be.decode(
        f_p, cache, jnp.int32(plen), jnp.int32(steps), kd, sampling, max_steps=steps
    )

    assert int(np.asarray(n_s)[0]) < steps  # EOS actually truncated row 0
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out_s))
    np.testing.assert_array_equal(np.asarray(n_p), np.asarray(n_s))


def test_create_backend_selects_schedule(eight_devices):
    """runtime.create_backend: microbatches>1 -> the 1F1B schedule backend,
    plain meshes -> pipeline, trivial mesh -> single device."""
    from distributed_llm_inference_tpu import create_backend

    cfg, be = create_backend(
        "test-llama-tiny", mesh_cfg=MeshConfig(dp=1, pp=2, tp=1), microbatches=2
    )
    assert be.name == "pipeline-1f1b"
    assert be.n_microbatches == 2
    _, be2 = create_backend("test-llama-tiny", mesh_cfg=MeshConfig(dp=1, pp=2, tp=1))
    assert be2.name == "pipeline"
    _, be3 = create_backend("test-llama-tiny")
    assert be3.name == "single-device"


@pytest.mark.slow
def test_microbatch_batch_contract(eight_devices):
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(MeshConfig(dp=1, pp=2, tp=1), eight_devices)
    with pytest.raises(ValueError, match="n_microbatches"):
        MicrobatchPipelineBackend(cfg, params, mesh, n_microbatches=1)
    be = MicrobatchPipelineBackend(cfg, params, mesh)
    assert be.health()[0]["microbatches"] == 2


@pytest.mark.slow
def test_non_fleet_batch_serves_via_plain_ring(eight_devices):
    """A row count that is NOT a multiple of dp*M (here 3 on M=2) no
    longer rejects: it dispatches to the inherited plain-ring programs
    and matches the single-device reference bit for bit (round-3 review
    #3: the full surface on every topology — odd shapes included)."""
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(MeshConfig(dp=1, pp=2, tp=1), eight_devices)
    be = MicrobatchPipelineBackend(cfg, params, mesh)

    batch, plen, bucket, steps = 3, 7, 16, 6
    tokens = _prompt_batch(cfg, batch, plen, bucket, seed=8)
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(9))

    f_s, _, out_s, n_s = _single_device_reference(
        cfg, params, tokens, jnp.int32(plen), steps, kp, kd, sampling
    )
    cache = be.init_cache(batch, 64)
    f_p, _, cache = be.prefill(tokens, jnp.int32(plen), cache, kp, sampling)
    out_p, n_p, _ = be.decode(
        f_p, cache, jnp.int32(plen), jnp.int32(steps), kd, sampling,
        max_steps=steps,
    )
    np.testing.assert_array_equal(np.asarray(f_p), np.asarray(f_s))
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out_s))
    np.testing.assert_array_equal(np.asarray(n_p), np.asarray(n_s))


@pytest.mark.slow
def test_microbatch_prefill_default_skips_logits(eight_devices):
    """Serving default: no [Mb, b_m, vocab] accumulator — prefill returns a
    zero-width logits array but bit-identical first tokens."""
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(MeshConfig(dp=1, pp=2, tp=1), eight_devices)
    be = MicrobatchPipelineBackend(cfg, params, mesh)

    batch, plen, bucket = 4, 9, 16
    tokens = _prompt_batch(cfg, batch, plen, bucket)
    sampling = G.default_sampling(greedy=True)
    key = jax.random.PRNGKey(1)

    cache_s = M.init_kv_cache(cfg, batch, max_seq=64)
    f_s, _, _ = G.prefill(cfg, params, tokens, jnp.int32(plen), cache_s, key, sampling)
    f_p, logits_p, _ = be.prefill(
        tokens, jnp.int32(plen), be.init_cache(batch, 64), key, sampling
    )
    assert logits_p.shape == (batch, 0)
    np.testing.assert_array_equal(np.asarray(f_p), np.asarray(f_s))
