"""chat_template='hf': render chat through the serving tokenizer's own
jinja template (the one real checkpoints ship in tokenizer_config.json),
instead of the built-in format table. Real-weights serving parity: HF
`apply_chat_template` is the behavioral spec."""

import json
import urllib.request

import pytest

transformers = pytest.importorskip("transformers")
tokenizers = pytest.importorskip("tokenizers")

from distributed_llm_inference_tpu import EngineConfig, get_model_config
from distributed_llm_inference_tpu.engine.engine import InferenceEngine
from distributed_llm_inference_tpu.serving.server import InferenceServer

TEMPLATE = (
    "{% for m in messages %}<<{{ m.role }}>>{{ m.content }}<END>"
    "{% endfor %}{% if add_generation_prompt %}<<assistant>>{% endif %}"
)


def _fast_tokenizer_with_template():
    """A from-scratch byte-level BPE fast tokenizer (no hub access) with a
    custom jinja chat template attached."""
    from tokenizers import Tokenizer, models, pre_tokenizers, decoders

    tok = Tokenizer(models.BPE(
        vocab={chr(33 + i): i for i in range(90)} | {"<pad>": 90,
                                                     "<s>": 91, "</s>": 92},
        merges=[],
    ))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    fast = transformers.PreTrainedTokenizerFast(
        tokenizer_object=tok, pad_token="<pad>", bos_token="<s>",
        eos_token="</s>",
    )
    fast.chat_template = TEMPLATE
    return fast


class _WrappedHF:
    """Duck-typed tokenizer wrapper (same surface as utils.tokenizer's
    HFTokenizer, without a filesystem round-trip)."""

    def __init__(self, fast):
        self._tok = fast
        self.pad_token_id = fast.pad_token_id
        self.bos_token_id = fast.bos_token_id
        self.eos_token_id = fast.eos_token_id

    @property
    def has_chat_template(self):
        return bool(self._tok.chat_template)

    def apply_chat_template(self, messages):
        return self._tok.apply_chat_template(
            messages, tokenize=False, add_generation_prompt=True
        )

    def encode(self, text, add_bos=True):
        return self._tok.encode(text)

    def decode(self, ids, skip_special_tokens=True):
        return self._tok.decode(list(ids),
                                skip_special_tokens=skip_special_tokens)


@pytest.fixture(scope="module")
def engine():
    cfg = get_model_config(
        "test-llama-tiny", chat_template="hf", vocab_size=256,
        pad_token_id=90, bos_token_id=91, eos_token_id=92,
    )
    return InferenceEngine(
        cfg, tokenizer=_WrappedHF(_fast_tokenizer_with_template()),
        engine_cfg=EngineConfig(prefill_buckets=(64,)),
    )


def test_render_chat_uses_tokenizer_template(engine):
    out = engine.render_chat("hello")
    assert out == "<<user>>hello<END><<assistant>>"
    out = engine.render_chat([
        {"role": "system", "content": "sys"},
        {"role": "user", "content": "q"},
    ])
    assert out == "<<system>>sys<END><<user>>q<END><<assistant>>"


def test_generate_chat_through_hf_template(engine):
    r = engine.generate("hi there", max_tokens=4, greedy=True, chat=True)
    assert r["status"] == "success", r
    # the encoded prompt is the templated text, not the raw prompt
    templated = engine.render_chat("hi there")
    assert r["prompt_tokens"] == len(engine.tokenizer.encode(templated))


def test_openai_chat_route_uses_hf_template(engine):
    server = InferenceServer(engine, host="127.0.0.1", port=0)
    server.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/chat/completions",
            data=json.dumps({
                "messages": [{"role": "user", "content": "ping"}],
                "max_tokens": 3, "temperature": 0,
            }).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=120) as r:
            out = json.loads(r.read())
        templated = engine.render_chat("ping")
        assert out["usage"]["prompt_tokens"] == len(
            engine.tokenizer.encode(templated)
        )
    finally:
        server.shutdown()


def test_hf_template_missing_is_loud():
    cfg = get_model_config("test-llama-tiny", chat_template="hf")
    eng = InferenceEngine(cfg, engine_cfg=EngineConfig(prefill_buckets=(32,)))
    r = eng.generate("x", max_tokens=3, chat=True)
    assert r["status"] == "failed"
    assert r["error_type"] == "invalid_request"
