"""Fleet-wide distributed tracing suite (ISSUE 17: utils/tracing.py,
serving/trace_store.py, and the traceparent propagation seams in
serving/{server,router,kv_fabric}.py + engine/continuous.py).

Three layers:

  * UNIT: W3C traceparent round trip, sampling determinism, TraceStore
    bounds/LRU/idempotent end, tree assembly (orphans degrade to a
    forest), Chrome trace-event (Perfetto) export schema, histogram
    exemplars, flight-recorder ring bounds.
  * IN-PROCESS ENGINE (chaos): the sampled launch-attribution path at
    rate 1.0 (launch spans parented under the request's inbound span,
    exemplar links to a stored trace), the ZERO-overhead contract at the
    default rate 0 (no span allocation on the hot path — asserted by
    making allocation impossible), and the crash leg: a fault-injected
    scheduler crash persists the flight ring next to --restore-dir.
  * REAL SUBPROCESS FLEET (chaos): 1 prefill + 1 decode replica behind
    an in-process router — one client-rooted request yields a SINGLE
    assembled trace tree spanning router dispatch, the prefill handoff,
    the decode replica's fabric pull, the serving peer's /kv span, and
    per-launch device-time attribution; span total ≈ end-to-end wall
    time; the JSON and Perfetto exports agree. The final leg kill -9s
    the decode replica so the failover hop appears as a router.retry
    span (it runs LAST: the fleet is spent afterwards).
"""

import json
import math
import os
import subprocess
import time
import urllib.error
import urllib.request

import pytest

from distributed_llm_inference_tpu.serving.trace_store import (
    TraceStore, assemble_tree, span_tree_total, to_chrome_trace,
)
from distributed_llm_inference_tpu.utils.tracing import (
    FlightRecorder, SpanContext, parse_traceparent, sample_decision,
)


# -- traceparent + sampling units ---------------------------------------------

def test_traceparent_round_trip():
    ctx = SpanContext.new_root()
    assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    back = parse_traceparent(ctx.header())
    assert back is not None
    assert back.trace_id == ctx.trace_id
    assert back.span_id == ctx.span_id
    assert back.sampled == ctx.sampled
    child = ctx.child()
    assert child.trace_id == ctx.trace_id
    assert child.span_id != ctx.span_id


@pytest.mark.parametrize("bad", [
    None, "", "garbage", "00-short-beef-01",
    "00-" + "g" * 32 + "-" + "b" * 16 + "-01",   # non-hex trace id
    "99-" + "a" * 32 + "-" + "b" * 16 + "-01",   # unknown version
    "00-" + "0" * 32 + "-" + "b" * 16 + "-01",   # all-zero trace id
    "00-" + "a" * 32 + "-" + "0" * 16 + "-01",   # all-zero span id
])
def test_traceparent_malformed_degrades_to_none(bad):
    assert parse_traceparent(bad) is None


def test_sample_decision_deterministic_and_bounded():
    ids = [SpanContext.new_root().trace_id for _ in range(64)]
    for tid in ids:
        assert sample_decision(tid, 0.0) is False
        assert sample_decision(tid, 1.0) is True
        # deterministic: same id, same verdict
        assert sample_decision(tid, 0.5) == sample_decision(tid, 0.5)
    frac = sum(sample_decision(t, 0.5) for t in ids) / len(ids)
    assert 0.05 < frac < 0.95  # keyed off the id, not constant


# -- span store ---------------------------------------------------------------

def test_span_store_pairing_tree_and_totals():
    store = TraceStore(service="unit")
    root = SpanContext.new_root()
    with store.span("parent", root) as sp:
        sub = root.child(sp["span_id"])
        with store.span("child", sub, attrs={"k": 1}):
            time.sleep(0.01)
    spans = store.get(root.trace_id)
    assert [s["name"] for s in spans] == ["child", "parent"]  # close order
    assert all(s["service"] == "unit" for s in spans)
    roots = assemble_tree(spans)
    assert len(roots) == 1 and roots[0]["name"] == "parent"
    assert roots[0]["children"][0]["name"] == "child"
    assert roots[0]["children"][0]["attrs"] == {"k": 1}
    total = span_tree_total(roots)
    assert total >= 0.01
    assert math.isclose(
        total, spans[1]["t1"] - spans[1]["t0"], rel_tol=1e-9
    )


def test_span_store_end_is_commit_once():
    store = TraceStore(service="unit")
    ctx = SpanContext.new_root()
    sp = store.start_span("once", ctx)
    store.end_span(sp, attrs={"a": 1})
    store.end_span(sp, attrs={"b": 2})  # defensive double-end: attrs only
    spans = store.get(ctx.trace_id)
    assert len(spans) == 1
    assert spans[0]["attrs"] == {"a": 1, "b": 2}


def test_span_store_exception_path_marks_error():
    store = TraceStore(service="unit")
    ctx = SpanContext.new_root()
    with pytest.raises(RuntimeError):
        with store.span("boom", ctx):
            raise RuntimeError("x")
    spans = store.get(ctx.trace_id)
    assert len(spans) == 1 and spans[0]["attrs"]["error"] is True
    assert spans[0]["t1"] is not None  # ended despite the raise


def test_span_store_lru_and_per_trace_bounds():
    store = TraceStore(service="unit", max_traces=4, max_spans_per_trace=8)
    ids = []
    for _ in range(6):
        ctx = SpanContext.new_root()
        ids.append(ctx.trace_id)
        store.add_span(ctx.trace_id, "s", 0.0, 1.0)
    kept = store.trace_ids()
    assert len(kept) == 4 and kept == ids[2:]  # LRU evicted the oldest
    # per-trace cap: extra spans drop (counted), trace survives
    busy = ids[-1]
    for i in range(20):
        store.add_span(busy, f"s{i}", 0.0, 1.0)
    assert len(store.get(busy)) == 8
    assert store.stats()["spans_dropped"] > 0
    # reading refreshes recency
    store.get(ids[2])
    store.add_span(SpanContext.new_root().trace_id, "s", 0.0, 1.0)
    assert ids[2] in store.trace_ids()


def test_assemble_tree_orphans_surface_as_forest():
    # parent span lives in a process that was never queried: the child
    # must surface as a root, not vanish
    tid = SpanContext.new_root().trace_id
    spans = [
        {"name": "a", "trace_id": tid, "span_id": "a" * 16,
         "parent_id": None, "t0": 1.0, "t1": 3.0, "attrs": {},
         "service": "s1"},
        {"name": "orphan", "trace_id": tid, "span_id": "b" * 16,
         "parent_id": "f" * 16, "t0": 1.5, "t1": 2.0, "attrs": {},
         "service": "s2"},
    ]
    roots = assemble_tree(spans)
    assert sorted(r["name"] for r in roots) == ["a", "orphan"]
    assert span_tree_total(roots) == 2.0  # max t1 - min t0 over roots


# -- Perfetto (Chrome trace-event) export -------------------------------------

def _validate_chrome(doc):
    """Minimal trace-event schema check: what Perfetto's JSON importer
    requires of every event we emit."""
    assert isinstance(doc["traceEvents"], list)
    names_by_pid = {}
    for ev in doc["traceEvents"]:
        assert isinstance(ev["name"], str)
        assert ev["ph"] in ("X", "M")
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        if ev["ph"] == "X":
            assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
        elif ev["name"] == "process_name":
            names_by_pid[ev["pid"]] = ev["args"]["name"]
    # every complete event's pid has a declared process-name lane
    for ev in doc["traceEvents"]:
        if ev["ph"] == "X":
            assert ev["pid"] in names_by_pid
    return names_by_pid


def test_chrome_trace_schema_and_lanes():
    store = TraceStore(service="svc-a")
    ctx = SpanContext.new_root()
    with store.span("a", ctx):
        pass
    spans = store.get(ctx.trace_id)
    # a second service's span in the same trace -> its own pid lane
    spans.append({
        "name": "b", "trace_id": ctx.trace_id, "span_id": "c" * 16,
        "parent_id": spans[0]["span_id"], "t0": spans[0]["t0"],
        "t1": None, "attrs": {}, "service": "svc-b",  # unfinished
    })
    doc = to_chrome_trace(spans)
    json.dumps(doc)  # JSON-serializable end to end
    lanes = _validate_chrome(doc)
    assert sorted(lanes.values()) == ["svc-a", "svc-b"]
    unfinished = [
        e for e in doc["traceEvents"]
        if e["ph"] == "X" and e["args"].get("unfinished")
    ]
    assert len(unfinished) == 1 and unfinished[0]["dur"] == 0


# -- exemplars ----------------------------------------------------------------

def test_histogram_exemplars_keep_latest_traced_sample():
    from distributed_llm_inference_tpu.utils.metrics import MetricsRegistry

    reg = MetricsRegistry()
    h = reg.histogram("t_seconds", "t", buckets=(0.1, 1.0)).labels()
    h.observe(0.05)                       # untraced: no exemplar
    h.observe(0.06, trace_id="aaaa")
    h.observe(0.07, trace_id="bbbb")      # same bucket: latest wins
    h.observe(5.0, trace_id="cccc")       # +Inf bucket
    ex = h.exemplars()
    assert ex["0.1"]["trace_id"] == "bbbb"
    assert ex["+Inf"]["trace_id"] == "cccc"
    assert ex["0.1"]["value"] == 0.06 or ex["0.1"]["value"] == 0.07
    # surfaced in the JSON snapshot for /stats + bench captures
    snap = reg.snapshot()["t_seconds"]["series"][0]
    assert snap["exemplars"]["+Inf"]["trace_id"] == "cccc"


# -- flight recorder ----------------------------------------------------------

def test_flight_recorder_ring_bounds_and_dump():
    fl = FlightRecorder(capacity=16)
    for i in range(100):
        fl.record("tick", i=i)
    dump = fl.dump()
    assert dump["capacity"] == 16
    assert dump["recorded_total"] == 100
    assert len(dump["events"]) == 16
    # the ring keeps the TAIL, in order, with monotone seq
    assert [e["i"] for e in dump["events"]] == list(range(84, 100))
    seqs = [e["seq"] for e in dump["events"]]
    assert seqs == sorted(seqs)
    json.dumps(dump)  # crash-report-safe verbatim
    assert fl.events(limit=3) == dump["events"][-3:]


# -- in-process engine legs ---------------------------------------------------

BS = 8
POOL = 48
PROMPT = "the quick brown fox jumps over the"


@pytest.fixture(scope="module")
def engine():
    from distributed_llm_inference_tpu import get_model_config
    from distributed_llm_inference_tpu.config import EngineConfig
    from distributed_llm_inference_tpu.engine.engine import InferenceEngine

    cfg = get_model_config("test-llama-tiny")
    return InferenceEngine(
        cfg,
        engine_cfg=EngineConfig(
            prefill_buckets=(32, 64), prefix_cache_entries=8
        ),
    )


def _cont(engine, **kw):
    from distributed_llm_inference_tpu.engine.continuous import (
        ContinuousEngine,
    )

    kw.setdefault("n_slots", 2)
    kw.setdefault("chunk_steps", 4)
    kw.setdefault("restart_backoff_s", 0.01)
    kw.setdefault("kv_pool_blocks", POOL)
    kw.setdefault("kv_block_size", BS)
    return ContinuousEngine(engine, **kw)


def test_trace_sample_rate_validated():
    from distributed_llm_inference_tpu.config import EngineConfig

    with pytest.raises(ValueError):
        EngineConfig(trace_sample_rate=1.5)
    with pytest.raises(ValueError):
        EngineConfig(trace_sample_rate=-0.1)


@pytest.mark.chaos
def test_zero_overhead_at_rate_zero(engine, monkeypatch):
    """The sampling contract: at the default rate 0 the hot path must
    not allocate a single span — enforced by making span creation blow
    up for the duration, then serving a full request."""
    import distributed_llm_inference_tpu.engine.continuous as C

    def _bomb(*a, **k):
        raise AssertionError("span allocated on the rate-0 hot path")

    cont = _cont(engine)
    assert cont._trace_rate == 0.0
    try:
        monkeypatch.setattr(TraceStore, "start_span", _bomb)
        monkeypatch.setattr(TraceStore, "add_span", _bomb)
        monkeypatch.setattr(C.ContinuousEngine, "_prof_note_launch", _bomb)
        ctx = SpanContext.new_root()  # sampled inbound context, rate 0
        r = cont.submit(PROMPT, max_tokens=8, greedy=True, chat=False,
                        trace_ctx=ctx)
        assert r["status"] == "success", r
        assert not cont._launch_log
        assert engine.trace_store.get(ctx.trace_id) == []
    finally:
        cont.close()


@pytest.mark.chaos
def test_launch_attribution_and_exemplar_link_at_rate_one(engine):
    """rate 1.0: every launch a profiled request rode emits one
    launch.<kind> span parented under the request's inbound span, and
    the latency histograms' exemplars link to the SAME stored trace."""
    import dataclasses

    old = engine.engine_cfg
    engine.engine_cfg = dataclasses.replace(old, trace_sample_rate=1.0)
    try:
        cont = _cont(engine)
        assert cont._trace_rate == 1.0
        ctx = SpanContext.new_root()
        try:
            r = cont.submit(PROMPT, max_tokens=8, greedy=True, chat=False,
                            trace_ctx=ctx)
        finally:
            cont.close()
        assert r["status"] == "success", r
        spans = engine.trace_store.get(ctx.trace_id)
        launches = [s for s in spans if s["name"].startswith("launch.")]
        assert launches, [s["name"] for s in spans]
        for sp in launches:
            assert sp["parent_id"] == ctx.span_id  # nests under inbound
            assert sp["t1"] >= sp["t0"]
            assert sp["attrs"].get("launch_to_fetch_s") is not None
        # exemplar -> this exact trace, which IS inspectable in the store
        ex = engine._m_duration.labels(engine="continuous").exemplars()
        assert any(e["trace_id"] == ctx.trace_id for e in ex.values())
        assert ctx.trace_id in engine.trace_store.trace_ids()
    finally:
        engine.engine_cfg = old


@pytest.mark.chaos
def test_crash_dump_persists_flight_ring(engine, tmp_path):
    """A fault-injected scheduler crash writes the full flight dump next
    to --restore-dir; the ring's live view shows the episode too."""
    from distributed_llm_inference_tpu.utils import faults

    cont = _cont(engine, kv_shadow=True, restore_dir=str(tmp_path))
    try:
        faults.arm([faults.FaultRule("prefill", "transient", on_call=1)])
        try:
            r = cont.submit(PROMPT, max_tokens=8, greedy=True, chat=False)
        finally:
            faults.disarm()
        assert r["status"] == "success", r  # supervisor recovered
    finally:
        cont.close()
    path = tmp_path / "flight_crash.json"
    assert path.exists()
    dump = json.loads(path.read_text())
    assert dump["recorded_total"] >= 1
    kinds = [e["kind"] for e in dump["events"]]
    assert "crash" in kinds
    assert dump["error"]
    # the live ring saw the same episode (plus the recovery)
    live = [e["kind"] for e in engine.flight.events()]
    assert "crash" in live and "restart" in live


# -- real subprocess fleet ----------------------------------------------------

FLEET_ARGS = [
    "--model", "test-llama-tiny", "--continuous", "2",
    "--continuous-chunk", "4", "--kv-pool-blocks", "48",
    "--kv-block-size", str(BS), "--prefix-cache", "8",
    "--max-tokens-cap", "64", "--trace-sample-rate", "1.0",
]
PROMPT_FLEET = "fresh traced disaggregated workload " * 3 + "alpha"


def _spawn_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("DLI_FAULTS", None)
    return env


@pytest.fixture(scope="module")
def fleet():
    """1 prefill- + 1 decode-class REAL engine server (sampling 1.0)
    behind an in-process router. probe_interval is long so the final
    kill -9 leg races the prober deterministically (the router still
    believes the corpse READY when it dispatches)."""
    from distributed_llm_inference_tpu.serving.router import (
        Router, RouterServer, spawn_replicas,
    )

    pre = spawn_replicas(1, FLEET_ARGS, env=_spawn_env(),
                         replica_class="prefill", name_prefix="p")[0]
    dec = spawn_replicas(1, FLEET_ARGS, env=_spawn_env(),
                         replica_class="decode", name_prefix="d")[0]
    router = Router(
        [pre, dec], eject_threshold=3, probe_interval_s=3.0,
        probe_timeout_s=2.0, request_timeout_s=120.0,
        handoff_min_bytes=64,
    )
    server = RouterServer(router, host="127.0.0.1", port=0)
    server.start()
    try:
        yield router, server, f"http://127.0.0.1:{server.port}", pre, dec
    finally:
        server.shutdown()
        for rep in (pre, dec):
            if rep.proc is not None:
                try:
                    rep.proc.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    rep.proc.kill()


def _get(base, path, timeout=15):
    try:
        with urllib.request.urlopen(base + path, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _get_text(base, path, timeout=15):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return r.read().decode()


def _post(base, payload, headers=None, timeout=180):
    req = urllib.request.Request(
        base + "/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


@pytest.mark.chaos
def test_fleet_round_trip_single_tree(fleet):
    """THE acceptance leg: one client-rooted request through router ->
    prefill handoff -> fabric pull -> decode yields ONE assembled trace
    tree covering every hop, span total ≈ end-to-end wall, and both
    export formats agree."""
    router, _, base, pre, dec = fleet
    ctx = SpanContext.new_root()
    code, body, hdrs = _post(
        base,
        {"prompt": PROMPT_FLEET, "max_tokens": 8, "greedy": True,
         "chat": False},
        headers={"traceparent": ctx.header()},
    )
    assert code == 200 and body["status"] == "success", body
    assert hdrs.get("X-Trace-Id") == ctx.trace_id
    assert body["replica"] == "d0"          # token loop on the decode tier
    assert body.get("kv_fabric_blocks", 0) > 0

    code, tr, _ = _get(base, f"/debug/traces/{ctx.trace_id}")
    assert code == 200
    names = {(s["service"], s["name"]) for s in tr["spans"]}
    # every hop of the disaggregated request is present
    assert ("router", "router.request") in names
    assert ("router", "router.dispatch") in names
    assert ("router", "router.handoff_prefill") in names
    assert ("replica-prefill", "replica.request") in names
    assert ("replica-prefill", "kv.serve") in names
    assert ("replica-decode", "replica.request") in names
    assert ("replica-decode", "fabric.pull") in names
    assert any(s == "replica-decode" and n.startswith("launch.")
               for s, n in names)
    assert any(n.startswith("stage.") for _, n in names)
    # one single root: the router.request span
    assert len(tr["tree"]) == 1
    assert tr["tree"][0]["name"] == "router.request"
    # span total ≈ end-to-end wall time (the router folds its own hop
    # into timings.total_s, so the two measure the same interval)
    assert tr["total_s"] == pytest.approx(
        body["timings"]["total_s"], rel=0.25, abs=0.5
    )
    # Perfetto export: valid schema, one pid lane per fleet role
    code, chrome, _ = _get(
        base, f"/debug/traces/{ctx.trace_id}?format=chrome"
    )
    assert code == 200
    lanes = _validate_chrome(chrome)
    assert sorted(lanes.values()) == [
        "replica-decode", "replica-prefill", "router",
    ]
    # the replica-side view exists too (partial forest is fine)
    code, rep_tr, _ = _get(dec.url, f"/debug/traces/{ctx.trace_id}")
    assert code == 200 and rep_tr["spans"]
    # listing endpoints answer on both tiers
    code, listing, _ = _get(base, "/debug/traces")
    assert code == 200 and ctx.trace_id in listing["traces"]


@pytest.mark.chaos
def test_fleet_exemplar_links_to_fetchable_trace(fleet):
    """A decode-replica latency exemplar names a trace the router can
    actually assemble (metrics -> traces pivot)."""
    router, _, base, _, dec = fleet
    code, stats, _ = _get(dec.url, "/stats")
    assert code == 200
    ex = stats.get("exemplars", {}).get(
        "dli_request_duration_seconds", {}
    )
    tids = [e["trace_id"] for e in ex.values()]
    assert tids, "no exemplars on the decode replica"
    code, tr, _ = _get(base, f"/debug/traces/{tids[0]}")
    assert code == 200 and tr["spans"]


@pytest.mark.chaos
def test_fleet_flight_and_kv_headers(fleet):
    """/debug/flight aggregates the replicas' rings through the router;
    /kv answers echo X-Request-Id; /metrics serves dli_build_info on
    both tiers with the right replica_class label."""
    router, _, base, pre, dec = fleet
    code, fl, _ = _get(base, "/debug/flight")
    assert code == 200
    assert set(fl["replicas"]) == {"p0", "d0"}
    kinds = [e["kind"] for e in fl["replicas"]["d0"].get("events", [])]
    assert "admit" in kinds and "fabric_fetch" in kinds
    # fabric response header echo (miss path: echo must not depend on a hit)
    req = urllib.request.Request(
        pre.url + "/kv/" + "ab" * 8,
        headers={"X-Request-Id": "req-echo-check",
                 "traceparent": SpanContext.new_root().header()},
    )
    try:
        with urllib.request.urlopen(req, timeout=15) as r:
            got = dict(r.headers)
    except urllib.error.HTTPError as e:
        got = dict(e.headers)
    assert got.get("X-Request-Id") == "req-echo-check"
    # build-info gauge on every /metrics surface
    for url, cls in ((base, 'replica_class="router"'),
                     (pre.url, 'replica_class="prefill"'),
                     (dec.url, 'replica_class="decode"')):
        text = _get_text(url, "/metrics")
        line = next(
            ln for ln in text.splitlines()
            if ln.startswith("dli_build_info{")
        )
        assert cls in line and line.split()[-1] in ("1", "1.0")


@pytest.mark.chaos
def test_fleet_failover_hop_is_retry_span(fleet):
    """LAST leg (spends the fleet): kill -9 the decode replica, dispatch
    before the prober notices — the dead-replica attempt appears as a
    router.dispatch span with a connect_error outcome and the failover
    hop as a router.retry span, both in the same assembled tree."""
    router, _, base, pre, dec = fleet
    dec.proc.kill()
    dec.proc.wait(timeout=15)
    ctx = SpanContext.new_root()
    code, body, _ = _post(
        base,
        {"prompt": "failover traced probe", "max_tokens": 4,
         "greedy": True, "chat": False},
        headers={"traceparent": ctx.header()},
    )
    assert code == 200 and body["status"] == "success", body
    assert body["replica"] == "p0"  # availability beats specialization
    assert body.get("router_attempts", 1) > 1
    code, tr, _ = _get(base, f"/debug/traces/{ctx.trace_id}")
    assert code == 200
    by_name = {}
    for s in tr["spans"]:
        by_name.setdefault(s["name"], []).append(s)
    assert "router.retry" in by_name
    retry = by_name["router.retry"][0]
    assert retry["attrs"]["replica"] == "p0"
    assert retry["attrs"]["attempt"] >= 2
    dead = [
        s for s in by_name.get("router.dispatch", [])
        if s["attrs"].get("outcome") == "connect_error"
    ]
    assert dead and dead[0]["attrs"]["replica"] == "d0"
    # both attempts nest under the one router.request root
    assert len(tr["tree"]) == 1
    root_id = tr["tree"][0]["span_id"]
    assert retry["parent_id"] == root_id
    assert dead[0]["parent_id"] == root_id
