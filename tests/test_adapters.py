"""Multi-tenant paged LoRA adapter serving (engine/adapters.py) tests.

The bar: many adapters off ONE resident base model without merging —
page 0 (the base page) is bit-identical to a build with no adapter
leaves at all; a single runtime adapter serves the same greedy stream
merge-at-load serves; a mixed-adapter fleet emits token-identical
output to each (prompt, adapter) served solo; the adapter mix never
grows the compiled-program set (the page ids are a traced operand);
the pool is strict refcount/LRU discipline (referenced pages are
untouchable, refcount-0 residents park instead of dropping); tenancy
is first-class (weighted prefill split, queue quota 429s, router
inflight quota); and a scheduler crash with adapters resident recovers
bit-identical with a clean page ledger.
"""

import json
import threading
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from distributed_llm_inference_tpu import EngineConfig, get_model_config
from distributed_llm_inference_tpu.engine.adapters import (
    AdapterPool,
    adapter_leaf_dims,
    attach_adapter_pool,
    install_adapter_leaves,
)
from distributed_llm_inference_tpu.engine.continuous import (
    ContinuousEngine,
    _Request,
)
from distributed_llm_inference_tpu.engine.engine import InferenceEngine
from distributed_llm_inference_tpu.models import api as M
from distributed_llm_inference_tpu.utils import faults

needs_shard_map = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="this jax build has no jax.shard_map (pp backends unavailable)",
)

SERVE_CFG = dict(dtype="float32", eos_token_id=-1, max_seq_len=512)
RANK = 4
KW = dict(max_tokens=8, greedy=True, chat=False)


@pytest.fixture(scope="module")
def setup():
    cfg = get_model_config("test-llama-tiny", **SERVE_CFG)
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _adapter_host(cfg, seed, rank=RANK, leaves=None):
    """Programmatic host adapter: {leaf: (a [L,in,r], b [L,r,out])}."""
    rng = np.random.default_rng(seed)
    dims = adapter_leaf_dims(cfg)
    if leaves is not None:
        dims = {k: dims[k] for k in leaves}
    return {
        leaf: (
            (rng.standard_normal((cfg.n_layers, d_in, rank))
             * 0.05).astype(np.float32),
            (rng.standard_normal((cfg.n_layers, rank, d_out))
             * 0.05).astype(np.float32),
        )
        for leaf, (d_in, d_out) in dims.items()
    }


def _cont(cfg, params, adapters=0, **kw):
    """Fleet builder; adapters=N attaches an N-page pool BEFORE the
    continuous engine is built (the create_engine wiring order)."""
    ecfg = dict(prefix_cache_entries=0, prefill_buckets=(64, 128, 256))
    ecfg.update(kw.pop("engine_cfg", {}))
    eng = InferenceEngine(cfg, params=params,
                          engine_cfg=EngineConfig(**ecfg))
    if adapters:
        attach_adapter_pool(eng, slots=adapters, rank=RANK)
    args = dict(n_slots=4, chunk_steps=8, slot_max_seq=512,
                kv_pool_blocks=120, kv_block_size=16,
                restart_backoff_s=0.01)
    args.update(kw)
    return ContinuousEngine(eng, **args)


# -- pool units (no device, no engine) ----------------------------------------

class _FakeBackend:
    """Records page writes; the pool never reads them back."""

    def __init__(self):
        self.writes = []

    def write_adapter_page(self, page, updates):
        self.writes.append((page, tuple(sorted(updates))))


def _pool(cfg, slots=2, **kw):
    return AdapterPool(cfg, _FakeBackend(), slots, RANK, **kw)


def test_pool_refcount_and_lru_eviction(setup):
    cfg, _ = setup
    pool = _pool(cfg, slots=2)
    for name, seed in (("a", 1), ("b", 2), ("c", 3)):
        pool.register(name, _adapter_host(cfg, seed))
    pa = pool.acquire("a")
    assert pa in (1, 2)
    assert pool.acquire("a") == pa  # second holder, same page, no write
    assert len(pool.backend.writes) == 1
    pb = pool.acquire("b")
    assert pb != pa
    # every page referenced: backpressure, NOT eviction
    assert pool.acquire("c") is None
    assert pool.free == 0
    # refcount 2 on a: one release keeps it referenced
    pool.release("a")
    assert pool.acquire("c") is None
    pool.release("a")  # refcount 0: parks in the LRU, still resident
    assert pool.free == 1
    pc = pool.acquire("c")  # evicts the LRU resident (a), reuses its page
    assert pc == pa
    st = pool.stats()
    assert st["evictions"] == 1 and st["swaps"] == 1 and st["loads"] == 3
    # b and c referenced again: a cannot come back until a release
    assert pool.acquire("a") is None
    pool.release("b")
    assert pool.acquire("a") == pb  # evicts b, the only refcount-0 page
    pool.release("a")
    pool.release("c")
    assert pool.free == pool.total and pool.referenced() == 0


def test_pool_acquire_unknown_adapter_raises(setup):
    cfg, _ = setup
    pool = _pool(cfg)
    with pytest.raises(KeyError):
        pool.acquire("never-registered")


def test_pool_over_release_clamps(setup):
    cfg, _ = setup
    pool = _pool(cfg)
    pool.register("a", _adapter_host(cfg, 1))
    page = pool.acquire("a")
    pool.release("a")
    pool.release("a")  # accounting bug surfaced in the log, then clamped
    assert pool.referenced() == 0
    assert pool.acquire("a") == page  # still serviceable, no re-write
    assert len(pool.backend.writes) == 1


def test_pool_reset_refs_parks_residents(setup):
    """Crash recovery: holders die with the fleet, page CONTENT survives
    (the leaves live in params) — residents park in the LRU and the
    recovered requests reload nothing."""
    cfg, _ = setup
    pool = _pool(cfg, slots=2)
    pool.register("a", _adapter_host(cfg, 1))
    pool.register("b", _adapter_host(cfg, 2))
    pa, pb = pool.acquire("a"), pool.acquire("b")
    pool.acquire("a")
    pool.reset_refs()
    assert pool.referenced() == 0 and pool.free == 2
    writes = len(pool.backend.writes)
    assert pool.acquire("a") == pa and pool.acquire("b") == pb
    assert len(pool.backend.writes) == writes  # zero reloads


def test_register_validation(setup):
    cfg, _ = setup
    pool = _pool(cfg)
    with pytest.raises(ValueError, match="non-empty"):
        pool.register("", _adapter_host(cfg, 1))
    with pytest.raises(ValueError, match="base model name"):
        pool.register(cfg.name, _adapter_host(cfg, 1))
    pool.register("a", _adapter_host(cfg, 1))
    with pytest.raises(ValueError, match="already registered"):
        pool.register("a", _adapter_host(cfg, 1))
    bad = dict(_adapter_host(cfg, 2), nope=_adapter_host(cfg, 2)["wq"])
    with pytest.raises(ValueError, match="no adapter leaves"):
        pool.register("b", bad)
    wrong = _adapter_host(cfg, 3)
    a, b = wrong["wq"]
    wrong["wq"] = (a[:, :, :-1], b)  # rank mismatch
    with pytest.raises(ValueError, match="do not match"):
        pool.register("c", wrong)


def test_register_rejects_the_merged_adapter(setup):
    """Satellite: the --lora merge-at-load adapter may not ALSO register
    as a runtime adapter — its delta is already in the dense weights, so
    serving it through a page would apply the delta twice."""
    cfg, _ = setup
    pool = _pool(cfg, merged_source="/tmp/some/adapter")
    with pytest.raises(ValueError, match="already merged"):
        pool.register("tuned", "/tmp/some/../some/adapter")
    # a DIFFERENT path is not the merged adapter: it proceeds into the
    # on-disk loader (and fails there on the fake path, not on the
    # collision check)
    with pytest.raises(Exception) as ei:
        pool.register("other", "/tmp/not/that/adapter")
    assert "already merged" not in str(ei.value)


def test_install_leaves_shapes_and_validation(setup):
    cfg, params = setup
    out = install_adapter_leaves(cfg, params, slots=2, rank=RANK)
    L, P = cfg.n_layers, 3
    for leaf, (d_in, d_out) in adapter_leaf_dims(cfg).items():
        a = out["layers"][f"lora_{leaf}_a"]
        b = out["layers"][f"lora_{leaf}_b"]
        assert a.shape == (L, P, d_in, RANK)
        assert b.shape == (L, P, RANK, d_out)
        assert not np.asarray(a).any() and not np.asarray(b).any()
    # the original params are untouched (fresh dicts on the way out)
    assert "lora_wq_a" not in params["layers"]
    with pytest.raises(ValueError, match="llama"):
        install_adapter_leaves(
            cfg.replace(arch="gpt2", n_kv_heads=cfg.n_heads), params,
            2, RANK,
        )
    with pytest.raises(ValueError, match="adapter_slots"):
        install_adapter_leaves(cfg, params, 0, RANK)
    with pytest.raises(ValueError, match="adapter_rank"):
        install_adapter_leaves(cfg, params, 2, 0)


# -- identity gates (the acceptance bar) --------------------------------------

PROMPTS = [
    "the quick brown fox jumps over the lazy dog",
    "pack my box with five dozen liquor jugs",
    "how vexingly quick daft zebras jump",
    "short",
]


@pytest.fixture(scope="module")
def fleet(setup):
    """One adapter-carrying fleet shared by the identity tests: 2 pages,
    adapters ad-a / ad-b registered."""
    cfg, params = setup
    cont = _cont(cfg, params, adapters=2)
    pool = cont.engine.adapters
    pool.register("ad-a", _adapter_host(cfg, 1))
    pool.register("ad-b", _adapter_host(cfg, 2))
    yield cont, pool
    cont.close()


def test_base_request_bit_identical_to_no_adapter_build(setup, fleet):
    """Adapter id 0 IS the base model: a request naming no adapter on the
    adapter-carrying fleet emits byte-identical greedy output to a build
    with no adapter leaves installed at all (the where-select contract —
    the delta is skipped, not added as zero)."""
    cfg, params = setup
    cont_a, _ = fleet
    plain = _cont(cfg, params)
    try:
        for p in PROMPTS[:2]:
            ra = cont_a.submit(p, **KW)
            rp = plain.submit(p, **KW)
            assert ra["status"] == rp["status"] == "success"
            assert ra["response"] == rp["response"]
    finally:
        plain.close()


def test_single_adapter_matches_merge_at_load(setup, fleet):
    """The runtime-page path and merge-at-load serve the same adapter the
    same way: greedy output through (x@a)@b on page p equals a build
    whose dense weights carry W + a@b baked in."""
    cfg, params = setup
    cont_a, _ = fleet
    host = _adapter_host(cfg, 1)  # ad-a's exact tensors
    layers = dict(params["layers"])
    for leaf, (a, b) in host.items():
        delta = np.einsum("lir,lro->lio", a, b)
        layers[leaf] = layers[leaf] + delta.astype(np.float32)
    merged = dict(params, layers=layers)
    cont_m = _cont(cfg, merged)
    try:
        for p in PROMPTS[:2]:
            rr = cont_a.submit(p, adapter="ad-a", **KW)
            rm = cont_m.submit(p, **KW)
            assert rr["status"] == rm["status"] == "success"
            assert rr["response"] == rm["response"]
    finally:
        cont_m.close()


def test_mixed_fleet_token_identical_to_solo(fleet):
    """The headline gate: every (prompt, adapter) pair served inside a
    threaded mixed-adapter fleet emits exactly the tokens it emits served
    alone — base rows included."""
    cont, pool = fleet
    jobs = [
        (p, ad)
        for p in PROMPTS
        for ad in (None, "ad-a", "ad-b")
    ]
    solo = {}
    for p, ad in jobs:
        extra = {"adapter": ad} if ad else {}
        r = cont.submit(p, **KW, **extra)
        assert r["status"] == "success", r
        solo[(p, ad)] = r["response"]

    mixed, lock = {}, threading.Lock()
    it = iter(jobs)

    def client():
        while True:
            with lock:
                j = next(it, None)
            if j is None:
                return
            p, ad = j
            extra = {"adapter": ad} if ad else {}
            r = cont.submit(p, **KW, **extra)
            with lock:
                mixed[(p, ad)] = r.get("response")

    threads = [threading.Thread(target=client) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert mixed == solo
    # post-drain pool hygiene: nothing holds a page, residents parked
    assert pool.referenced() == 0
    assert pool.free == pool.total


def test_adapter_mix_never_recompiles(fleet):
    """One compiled program serves ANY adapter mix: the page ids are a
    traced operand, so churning through different adapter combinations
    leaves the jit caches exactly where the warmup put them."""
    from distributed_llm_inference_tpu.engine import paged as EP

    cont, _ = fleet
    # warm every program shape with one mixed pass (the earlier tests in
    # this module already churned the fleet, but stay self-sufficient)
    for ad in (None, "ad-a", "ad-b"):
        extra = {"adapter": ad} if ad else {}
        cont.submit(PROMPTS[0], **KW, **extra)
    mixed_programs = EP.mixed_step_ragged._cache_size()
    ingest_programs = cont.engine.backend.ragged_program_count()
    jobs = [(p, ad) for p in PROMPTS[:3]
            for ad in ("ad-b", None, "ad-a")]
    lock = threading.Lock()
    it = iter(jobs)

    def client():
        while True:
            with lock:
                j = next(it, None)
            if j is None:
                return
            p, ad = j
            extra = {"adapter": ad} if ad else {}
            cont.submit(p, **KW, **extra)

    threads = [threading.Thread(target=client) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert EP.mixed_step_ragged._cache_size() == mixed_programs
    assert cont.engine.backend.ragged_program_count() == ingest_programs


def test_adapter_request_rejections(setup, fleet):
    cfg, params = setup
    cont, _ = fleet
    r = cont.submit(PROMPTS[0], adapter="nope", **KW)
    assert r["status"] == "failed"
    assert r["error_type"] == "invalid_request"
    assert "unknown adapter" in r["error"]
    # solo-engine contracts cannot ride an adapter page
    r = cont.submit(PROMPTS[0], adapter="ad-a", seed=7,
                    max_tokens=4, chat=False)
    assert r["status"] == "failed" and "solo" in r["error"]
    # a fleet with NO pool attached rejects adapter requests outright
    plain = _cont(cfg, params)
    try:
        r = plain.submit(PROMPTS[0], adapter="ad-a", **KW)
        assert r["status"] == "failed"
        assert "adapter pool" in r["error"]
    finally:
        plain.close()


# -- tenancy ------------------------------------------------------------------

def test_tenant_weighted_prefill_split():
    """Within one class's tile grant, tenants split by configured weight:
    a weight-3 tenant's job out-apportions a weight-1 tenant's equal-age
    job roughly 3:1, and a single-tenant class degenerates to FIFO."""
    from distributed_llm_inference_tpu.engine.scheduler import (
        PrefillJob,
        SLOClass,
        TokenBudgetScheduler,
    )

    class _Req:
        def __init__(self, tenant):
            self.enqueued = 0.0
            self.tenant = tenant

    def job(tenant, slot):
        return PrefillJob(
            _Req(tenant), ids=list(range(400)), p0=0, prompt_len=400,
            max_tokens=4, slot=slot,
            sampling=(0.7, 50, 0.9, True, 0.0, 1.0, 0.0, 0.0),
            presence_row=None, table_row=None, cls=cls,
        )

    classes = {"standard": SLOClass("standard", 2.0, 0.5, 2.0, True)}
    cls = classes["standard"]
    s = TokenBudgetScheduler(
        classes, "standard", 256, 8, 4,
        tenant_weights=(("heavy", 3.0), ("light", 1.0)),
    )
    jh, jl = job("heavy", 0), job("light", 1)
    plan = {id(j): n for j, n in s.plan(0, [jl, jh], now=1.0)}
    assert plan[id(jh)] > 2 * plan[id(jl)] > 0
    # same class, no tenants: pure FIFO — the first-arrived job gets at
    # least as much of the grant as the second
    j0, j1 = job(None, 0), job(None, 1)
    plan = {id(j): n for j, n in s.plan(0, [j0, j1], now=1.0)}
    assert plan[id(j0)] >= plan.get(id(j1), 0)


def test_tenant_queue_quota_sheds(setup):
    """One tenant's queued share of the bounded queue is capped: the
    over-quota tenant 429s (with its name in the envelope) while other
    tenants and anonymous traffic still queue."""
    cfg, params = setup
    cont = _cont(cfg, params, max_queue=8,
                 engine_cfg={"tenant_max_queue_share": 0.5})
    try:
        with cont._cv:
            for i in range(4):  # cap = max(4, int(8 * 0.5)) = 4
                q = _Request(f"fill {i}",
                             dict(max_tokens=4, greedy=True, chat=False))
                q.slo = "standard"
                q.tenant = "flood"
                cont._queue.append(q)
            cont._note_queue_locked()
        req = _Request("over", dict(max_tokens=4, greedy=True, chat=False))
        req.slo = None
        req.tenant = "flood"
        shed = cont._enqueue(req)
        assert shed is not None and shed["error_type"] == "overloaded"
        assert shed["tenant"] == "flood"
        assert "queue quota" in shed["error"]
        assert shed["retry_after_s"] >= 0
        # another tenant (and anonymous traffic) is untouched
        ok = _Request("fine", dict(max_tokens=4, greedy=True, chat=False))
        ok.slo = None
        ok.tenant = "other"
        assert cont._enqueue(ok) is None
        anon = _Request("anon", dict(max_tokens=4, greedy=True, chat=False))
        anon.slo = None
        assert cont._enqueue(anon) is None
        # the per-tenant shed counter carries the tenant label
        snap = cont.engine.metrics.snapshot()
        series = {
            s["labels"].get("tenant"): s["value"]
            for s in snap.get("dli_tenant_shed_total", {}).get("series", [])
        }
        assert series.get("flood") == 1
        with cont._cv:
            cont._queue.clear()
            cont._note_queue_locked()
    finally:
        cont.close()


def test_queue_depth_gauge_carries_tenant_label(setup):
    cfg, params = setup
    cont = _cont(cfg, params)
    try:
        cont.submit(PROMPTS[3], tenant="acme", **KW)
        snap = cont.engine.metrics.snapshot()
    finally:
        cont.close()
    series = {
        (s["labels"]["slo_class"], s["labels"]["tenant"])
        for s in snap.get("dli_slo_queue_depth", {}).get("series", [])
    }
    # the tenant ever seen keeps its series (reads 0 after drain), and
    # the anonymous series stays schema-stable alongside it
    assert ("standard", "acme") in series
    assert ("standard", "") in series


def test_router_tenant_inflight_quota():
    from distributed_llm_inference_tpu.serving.router import (
        Replica,
        Router,
    )

    router = Router([Replica("r1", "http://127.0.0.1:9")],
                    tenant_max_inflight_share=0.5)
    # the floor: a quiet router admits a few requests from anyone
    for _ in range(4):
        assert router.tenant_begin("acme")
    # 4 inflight, cap = max(4, int(4 * 0.5)) = 4: the 5th sheds
    assert not router.tenant_begin("acme")
    # other tenants and the anonymous bucket are unaffected
    assert router.tenant_begin("globex")
    assert router.tenant_begin(None)
    # anonymous load raises the total, so the cap loosens: 6 inflight
    # -> cap 4 still binds at 4... grow the pie past 8 and acme fits
    for _ in range(4):
        assert router.tenant_begin("")
    assert router.tenant_begin("acme")  # cap = int(10 * .5) = 5 now
    router.tenant_end("acme")
    snap = router.metrics.snapshot()
    series = {
        s["labels"].get("tenant"): s["value"]
        for s in snap.get("dli_tenant_shed_total", {}).get("series", [])
    }
    assert series.get("acme") == 1


def test_router_affinity_key_is_adapter_scoped():
    """The same prompt under two adapters must never share an affinity
    chain (adapter KV is conditioned on adapter weights); the OpenAI
    `model` field scopes identically."""
    from distributed_llm_inference_tpu.serving.router import _affinity_key

    base = _affinity_key({"prompt": "shared prefix text"})
    ka = _affinity_key({"prompt": "shared prefix text", "adapter": "ad-a"})
    kb = _affinity_key({"prompt": "shared prefix text", "adapter": "ad-b"})
    km = _affinity_key({"prompt": "shared prefix text", "model": "ad-a"})
    assert len({base, ka, kb}) == 3
    assert ka == km  # /generate adapter and OpenAI model key the same
    assert ka.endswith("shared prefix text")


# -- HTTP surface -------------------------------------------------------------

def _post(port, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=120) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture(scope="module")
def served(setup):
    from distributed_llm_inference_tpu.serving.server import InferenceServer

    cfg, params = setup
    cont = _cont(cfg, params, adapters=2)
    cont.engine.adapters.register("ad-a", _adapter_host(cfg, 1))
    server = InferenceServer(cont.engine, host="127.0.0.1", port=0,
                             continuous=cont)
    server.start()
    yield server
    server.shutdown()


def test_models_route_lists_adapters(served):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{served.port}/v1/models", timeout=30
    ) as resp:
        models = json.loads(resp.read())
    ids = {m["id"]: m for m in models["data"]}
    assert "test-llama-tiny" in ids and "ad-a" in ids
    assert ids["ad-a"]["root"] == "test-llama-tiny"


def test_generate_adapter_resolution(served):
    status, body = _post(served.port, "/generate",
                         {"prompt": "hi there", "adapter": "ad-a",
                          "max_tokens": 4, "greedy": True, "chat": False})
    assert status == 200 and body["status"] == "success"
    status, body = _post(served.port, "/generate",
                         {"prompt": "hi", "adapter": "nope",
                          "max_tokens": 4})
    assert status == 400 and "unknown adapter" in body["error"]
    status, body = _post(served.port, "/generate",
                         {"prompt": "hi", "adapter": 7, "max_tokens": 4})
    assert status == 400
    # naming the base model is the base path, not an adapter lookup
    status, body = _post(served.port, "/generate",
                         {"prompt": "hi", "adapter": "test-llama-tiny",
                          "max_tokens": 4, "greedy": True, "chat": False})
    assert status == 200 and body["status"] == "success"


def test_openai_model_resolves_to_adapter(served):
    status, body = _post(
        served.port, "/v1/completions",
        {"model": "ad-a", "prompt": "hello", "max_tokens": 4},
    )
    assert status == 200 and body["model"] == "ad-a"
    status, body = _post(
        served.port, "/v1/completions",
        {"model": "not-registered", "prompt": "hello", "max_tokens": 4},
    )
    assert status == 400
    assert "neither the base model" in body["error"]["message"]
    # the base name keeps meaning the base
    status, body = _post(
        served.port, "/v1/completions",
        {"model": "test-llama-tiny", "prompt": "hello", "max_tokens": 4},
    )
    assert status == 200


def test_tenant_field_validation(served):
    status, body = _post(served.port, "/generate",
                         {"prompt": "hi", "tenant": 12, "max_tokens": 4})
    assert status == 400
    status, body = _post(
        served.port, "/v1/completions",
        {"model": "test-llama-tiny", "prompt": "hi", "tenant": 12,
         "max_tokens": 4},
    )
    assert status == 400
    status, body = _post(served.port, "/generate",
                         {"prompt": "hi", "tenant": "acme",
                          "max_tokens": 4, "greedy": True, "chat": False})
    assert status == 200 and body["status"] == "success"


def test_generate_adapter_without_pool_is_400(setup):
    from distributed_llm_inference_tpu.serving.server import InferenceServer

    cfg, params = setup
    cont = _cont(cfg, params)
    server = InferenceServer(cont.engine, host="127.0.0.1", port=0,
                             continuous=cont)
    server.start()
    try:
        status, body = _post(server.port, "/generate",
                             {"prompt": "hi", "adapter": "ad-a",
                              "max_tokens": 4})
        assert status == 400
        assert "adapter serving is not configured" in body["error"]
    finally:
        server.shutdown()


# -- chaos: crash with adapters resident --------------------------------------

@pytest.fixture(autouse=True)
def _always_disarm():
    faults.disarm()
    yield
    faults.disarm()


@pytest.mark.chaos
def test_crash_with_adapters_resident_recovers_bit_identical(setup):
    """A scheduler crash mid-decode with adapter pages referenced: the
    fleet rebuilds, page refcounts reset wholesale (reset_refs — content
    survives in params), every greedy stream re-emerges bit-identical,
    and after the drain the ledger is clean (referenced == 0,
    free == total)."""
    cfg, params = setup
    jobs = [(PROMPTS[0], None), (PROMPTS[1], "ad-a"), (PROMPTS[2], "ad-b")]

    def serve(spec):
        faults.disarm()
        cont = _cont(cfg, params, adapters=2)
        pool = cont.engine.adapters
        pool.register("ad-a", _adapter_host(cfg, 1))
        pool.register("ad-b", _adapter_host(cfg, 2))
        try:
            # warm the launch programs OUTSIDE the fault window
            cont.submit("warm", **KW)
            cont.submit("warm", adapter="ad-a", **KW)
            if spec:
                faults.arm(spec)
            out, lock = {}, threading.Lock()

            def client(j):
                p, ad = j
                extra = {"adapter": ad} if ad else {}
                r = cont.submit(p, **dict(KW, max_tokens=12), **extra)
                with lock:
                    out[j] = r

            threads = [threading.Thread(target=client, args=(j,))
                       for j in jobs]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            faults.disarm()
            return out, cont.restarts_total, pool.stats()
        finally:
            faults.disarm()
            cont.close()

    clean, restarts0, _ = serve(None)
    assert restarts0 == 0
    faulted, restarts, st = serve([
        faults.FaultRule("decode_launch", "transient", on_call=2),
    ])
    assert restarts >= 1
    for j in jobs:
        assert faulted[j]["status"] == "success", faulted[j]
        assert faulted[j]["response"] == clean[j]["response"]
    assert st["referenced"] == 0
    assert st["free"] == st["total"]


# -- pp twin ------------------------------------------------------------------

@needs_shard_map
def test_pp_fleet_serves_adapters_identically(setup):
    """The pipeline backend's shard_map twin: the same adapter request on
    a pp=2 mesh emits the single-device fleet's exact greedy stream (the
    lora leaves shard through the ordinary partition specs and the page
    write runs per-stage)."""
    from distributed_llm_inference_tpu import MeshConfig, create_engine

    cfg, params = setup
    host = _adapter_host(cfg, 1)
    eng_pp = create_engine(
        cfg, params=params, mesh_cfg=MeshConfig(pp=2),
        engine_cfg=EngineConfig(
            prefix_cache_entries=0, prefill_buckets=(64, 128, 256),
            adapter_slots=2, adapter_rank=RANK,
        ),
    )
    eng_pp.adapters.register("ad-a", host)
    cont_pp = ContinuousEngine(
        eng_pp, n_slots=4, chunk_steps=8, slot_max_seq=512,
        kv_pool_blocks=120, kv_block_size=16, restart_backoff_s=0.01,
    )
    cont_sd = _cont(cfg, params, adapters=2)
    cont_sd.engine.adapters.register("ad-a", host)
    try:
        for p in PROMPTS[:2]:
            rp = cont_pp.submit(p, adapter="ad-a", **KW)
            rs = cont_sd.submit(p, adapter="ad-a", **KW)
            assert rp["status"] == rs["status"] == "success"
            assert rp["response"] == rs["response"]
    finally:
        cont_pp.close()
        cont_sd.close()
