"""Smoke test: the 5-config BASELINE harness stays runnable in CI."""

import json
import os
import io
import contextlib
import sys

import pytest


@pytest.mark.slow  # re-tiered round 5: compiles all five config shapes
def test_harness_runs_each_config_shape(capsys):
    sys.path.insert(0, "benchmarks")
    from benchmarks.run_baseline_configs import main

    # conftest already forces the 8-device CPU mesh; run the two cheapest
    # configs end to end (single-device + 2-stage pipeline)
    main(["--scale", "tiny", "--configs", "1,2", "--steps", "4"])
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 2
    for i, line in enumerate(lines):
        rec = json.loads(line)
        assert rec["config"] == i + 1
        assert rec["tokens_per_sec"] > 0
        assert rec["ttft_s"] >= 0
        assert rec["platform"] == "cpu"


def test_bench_sidecar_roundtrip(tmp_path, monkeypatch):
    """bench.py's sidecar is the crash-recovery channel: a result written
    after each completed leg must read back exactly, atomically replacing
    the previous state, and a missing/corrupt file must read as None."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench

    side = tmp_path / "side.json"
    monkeypatch.setenv("_BENCH_SIDECAR", str(side))
    r1 = {"metric": "m", "value": 1.0}
    bench._write_sidecar(r1)
    assert bench._read_sidecar(str(side)) == r1
    r2 = dict(r1, value=2.0, extra_leg=3)
    bench._write_sidecar(r2)
    assert bench._read_sidecar(str(side)) == r2
    assert bench._read_sidecar(str(tmp_path / "absent.json")) is None
    side.write_text("{corrupt")
    assert bench._read_sidecar(str(side)) is None
    # unset env: write is a silent no-op (never fatal mid-bench)
    monkeypatch.delenv("_BENCH_SIDECAR")
    bench._write_sidecar(r2)


def test_bench_child_json_takes_last_line():
    """The consumer contract: the LAST parseable JSON line wins, so the
    early solo-greedy emit is superseded by the enriched final line when
    the child survives, and stands when it does not."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench

    out = (
        'WARNING: noise\n'
        '{"metric": "m", "value": 1.0}\n'
        'more noise {not json}\n'
        '{"metric": "m", "value": 2.0, "int8_tokens_per_sec": 5}\n'
    )
    assert bench._parse_child_json(out)["value"] == 2.0
    assert bench._parse_child_json("no json at all") is None
