"""Smoke test: the 5-config BASELINE harness stays runnable in CI."""

import json
import io
import contextlib
import sys


def test_harness_runs_each_config_shape(capsys):
    sys.path.insert(0, "benchmarks")
    from benchmarks.run_baseline_configs import main

    # conftest already forces the 8-device CPU mesh; run the two cheapest
    # configs end to end (single-device + 2-stage pipeline)
    main(["--scale", "tiny", "--configs", "1,2", "--steps", "4"])
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 2
    for i, line in enumerate(lines):
        rec = json.loads(line)
        assert rec["config"] == i + 1
        assert rec["tokens_per_sec"] > 0
        assert rec["ttft_s"] >= 0
        assert rec["platform"] == "cpu"
