"""Tensor-parallel (tp axis) and data-parallel (dp axis) equivalence tests
on the 8-virtual-CPU-device mesh: head/FFN-sharded execution and
batch-sharded execution must reproduce single-device results.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llm_inference_tpu import MeshConfig, get_model_config
from distributed_llm_inference_tpu.engine import generate as G
from distributed_llm_inference_tpu.models import api as M
from distributed_llm_inference_tpu.parallel.mesh import build_mesh
from distributed_llm_inference_tpu.parallel.partition import validate_mesh
from distributed_llm_inference_tpu.parallel.pipeline import PipelineBackend


def _single_device(cfg, params, tokens, plen, steps, key, sampling, batch=1):
    kp, kd = jax.random.split(key)
    cache = M.init_kv_cache(cfg, batch, max_seq=64)
    f, logits, cache = G.prefill(cfg, params, tokens, plen, cache, kp, sampling)
    out, n, _ = G.decode(
        cfg, params, f, cache, plen, jnp.int32(steps), kd, sampling, max_steps=steps
    )
    return f, logits, out, n


def _backend(cfg, params, mesh_cfg, devices, tokens, plen, steps, key, sampling,
             batch=1):
    kp, kd = jax.random.split(key)
    pb = PipelineBackend(cfg, params, build_mesh(mesh_cfg, devices))
    cache = pb.init_cache(batch, 64)
    f, logits, cache = pb.prefill(tokens, plen, cache, kp, sampling)
    out, n, _ = pb.decode(
        f, cache, plen, jnp.int32(steps), kd, sampling, max_steps=steps
    )
    return f, logits, out, n


@pytest.mark.parametrize(
    "cfg_name,mesh",
    [
        ("test-llama-tiny", MeshConfig(dp=1, pp=1, tp=2)),  # pure TP
        ("test-llama-tiny", MeshConfig(dp=1, pp=2, tp=2)),  # PP × TP
        ("test-gpt2-tiny", MeshConfig(dp=1, pp=1, tp=4)),   # MHA TP (biases)
        ("test-gpt2-tiny", MeshConfig(dp=1, pp=2, tp=2)),
    ],
)
@pytest.mark.slow
def test_tp_greedy_decode_matches_single_device(cfg_name, mesh, eight_devices):
    cfg = get_model_config(cfg_name)
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(4)
    ids = rng.integers(3, min(cfg.vocab_size, 250), size=6, dtype=np.int64).tolist()
    bucket, steps = 16, 8
    tokens = jnp.asarray([ids + [cfg.pad_token_id] * (bucket - len(ids))], jnp.int32)
    plen = jnp.int32(len(ids))
    sampling = G.default_sampling(greedy=True)
    key = jax.random.PRNGKey(7)

    f_s, logits_s, out_s, n_s = _single_device(
        cfg, params, tokens, plen, steps, key, sampling
    )
    f_t, logits_t, out_t, n_t = _backend(
        cfg, params, mesh, eight_devices, tokens, plen, steps, key, sampling
    )

    # psum reassociates the contraction over tp shards: tolerance, not
    # bit-equality, on logits; greedy tokens must still agree exactly
    np.testing.assert_allclose(
        np.asarray(logits_t), np.asarray(logits_s), rtol=2e-4, atol=2e-4
    )
    assert int(f_t[0]) == int(f_s[0])
    np.testing.assert_array_equal(np.asarray(out_t), np.asarray(out_s))
    assert int(n_t[0]) == int(n_s[0])


@pytest.mark.slow
def test_dp_batched_greedy_decode_matches_single_device(eight_devices):
    """dp=2 batch-sharded decode == single-device batch=2 decode (greedy:
    per-dp-group key folding cannot affect argmax)."""
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(9)
    batch, bucket, steps = 2, 16, 6
    plen_i = 5
    rows = rng.integers(3, 250, size=(batch, plen_i), dtype=np.int64)
    tokens = jnp.asarray(
        np.pad(rows, ((0, 0), (0, bucket - plen_i)), constant_values=cfg.pad_token_id),
        jnp.int32,
    )
    plen = jnp.int32(plen_i)
    sampling = G.default_sampling(greedy=True)
    key = jax.random.PRNGKey(13)

    f_s, _, out_s, n_s = _single_device(
        cfg, params, tokens, plen, steps, key, sampling, batch=batch
    )
    f_d, _, out_d, n_d = _backend(
        cfg, params, MeshConfig(dp=2, pp=2, tp=2), eight_devices,
        tokens, plen, steps, key, sampling, batch=batch,
    )
    np.testing.assert_array_equal(np.asarray(f_d), np.asarray(f_s))
    np.testing.assert_array_equal(np.asarray(out_d), np.asarray(out_s))
    np.testing.assert_array_equal(np.asarray(n_d), np.asarray(n_s))


def test_validate_mesh_rejects_indivisible():
    cfg = get_model_config("test-llama-tiny")  # 4 layers, 4 heads, 2 kv heads
    with pytest.raises(ValueError, match="n_kv_heads"):
        validate_mesh(cfg, pp=1, tp=4)  # 2 kv heads % 4 != 0
    # uneven pp (3 stages over 4 layers) is VALID since no-op padding;
    # only pp > n_layers is rejected
    validate_mesh(cfg, pp=3, tp=1)
    with pytest.raises(ValueError, match="pp=5"):
        validate_mesh(cfg, pp=5, tp=1)


@pytest.mark.slow
def test_dp_cache_requires_divisible_batch(eight_devices):
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    pb = PipelineBackend(
        cfg, params, build_mesh(MeshConfig(dp=2, pp=2, tp=1), eight_devices)
    )
    with pytest.raises(ValueError, match="batch=1 not divisible"):
        pb.init_cache(1, 64)
