"""Observability tests (ISSUE 2): the metrics registry (utils/metrics.py),
Prometheus exposition round-trip via an in-test parser, /stats ≡ registry
consistency, per-request stage tracing (utils/tracing.py) on the solo and
continuous paths, and warmup-traffic exclusion."""

import json
import logging as pylog
import re
import threading
import time
import urllib.error
import urllib.request

import pytest

from distributed_llm_inference_tpu import EngineConfig, create_engine
from distributed_llm_inference_tpu.engine.continuous import ContinuousEngine
from distributed_llm_inference_tpu.serving.queue import BatchingQueue
from distributed_llm_inference_tpu.serving.server import InferenceServer
from distributed_llm_inference_tpu.utils import logging as slog
from distributed_llm_inference_tpu.utils.metrics import (
    MetricsRegistry,
    percentile,
)
from distributed_llm_inference_tpu.utils.tracing import (
    Trace,
    sanitize_request_id,
)

# ---------------------------------------------------------------- registry


def test_counter_and_gauge_basics():
    reg = MetricsRegistry()
    c = reg.counter("t_requests_total", "reqs", ("engine",))
    c.labels(engine="solo").inc()
    c.labels(engine="solo").inc(2)
    c.labels(engine="batch").inc()
    assert c.labels(engine="solo").value == 3
    assert c.labels(engine="batch").value == 1
    with pytest.raises(ValueError):
        c.labels(engine="solo").inc(-1)
    with pytest.raises(ValueError):
        c.labels(wrong="x")
    g = reg.gauge("t_depth")
    g.labels().set(5)
    g.labels().dec()
    assert g.labels().value == 4


def test_registration_is_idempotent_but_typed():
    reg = MetricsRegistry()
    fam = reg.counter("x_total")
    assert reg.counter("x_total") is fam
    with pytest.raises(ValueError):
        reg.gauge("x_total")
    with pytest.raises(ValueError):
        reg.counter("x_total", labelnames=("a",))


def test_histogram_bucketing_and_window_percentiles():
    reg = MetricsRegistry()
    fam = reg.histogram("h_seconds", "h", buckets=(0.1, 1.0, 10.0))
    h = fam.labels()
    values = [0.05, 0.5, 5.0, 50.0]
    for v in values:
        h.observe(v)
    assert h.count == 4
    assert abs(h.sum - sum(values)) < 1e-9
    # non-cumulative internal counts: one observation per bucket (+Inf last)
    assert h._bucket_counts == [1, 1, 1, 1]
    # window percentiles match the shared nearest-rank formula exactly
    for q in (0.5, 0.9, 0.99):
        assert h.percentile(q) == percentile(values, q)


def test_thread_safety_under_contention():
    reg = MetricsRegistry()
    c = reg.counter("race_total").labels()
    h = reg.histogram("race_seconds").labels()

    def work():
        for _ in range(500):
            c.inc()
            h.observe(0.01)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 4000
    assert h.count == 4000


def test_label_cardinality_cap_collapses_to_other():
    reg = MetricsRegistry(max_series=4)
    c = reg.counter("cap_total", "capped", ("route",))
    for i in range(10):
        c.labels(route=f"r{i}").inc()
    series = reg.snapshot()["cap_total"]["series"]
    assert len(series) == 5  # 4 real + 1 overflow
    other = [s for s in series if s["labels"]["route"] == "_other_"]
    assert len(other) == 1 and other[0]["value"] == 6
    # no count lost to the cap
    assert sum(s["value"] for s in series) == 10


# ------------------------------------------- exposition format round-trip

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$"
)


def _parse_exposition(text: str) -> dict:
    """Tiny Prometheus text-format parser: family name ->
    {"type": ..., "samples": {(sample_name, labels_str): float}}."""
    families: dict = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            _, _, name, typ = line.split()
            families[name] = {"type": typ, "samples": {}}
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        fam = name
        for suffix in ("_bucket", "_sum", "_count"):
            if fam not in families and name.endswith(suffix):
                fam = name[: -len(suffix)]
        assert fam in families, f"sample {name!r} without a # TYPE line"
        v = float("inf") if value == "+Inf" else float(value)
        families[fam]["samples"][(name, labels)] = v
    return families


def test_exposition_roundtrip_unit():
    reg = MetricsRegistry()
    reg.counter("rt_total", "a counter", ("engine",)).labels(
        engine="solo"
    ).inc(7)
    h = reg.histogram("rt_seconds", "a hist", buckets=(0.1, 1.0)).labels()
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    fams = _parse_exposition(reg.render())
    assert fams["rt_total"]["type"] == "counter"
    assert fams["rt_total"]["samples"][("rt_total", 'engine="solo"')] == 7
    s = fams["rt_seconds"]["samples"]
    # cumulative buckets, +Inf == count, sum preserved
    assert s[("rt_seconds_bucket", 'le="0.1"')] == 1
    assert s[("rt_seconds_bucket", 'le="1"')] == 2
    assert s[("rt_seconds_bucket", 'le="+Inf"')] == 3
    assert s[("rt_seconds_count", "")] == 3
    assert abs(s[("rt_seconds_sum", "")] - 5.55) < 1e-9


def test_label_values_escaped():
    reg = MetricsRegistry()
    reg.counter("esc_total", "", ("route",)).labels(
        route='we"ird\npath\\x'
    ).inc()
    line = [
        ln for ln in reg.render().splitlines()
        if ln.startswith("esc_total{")
    ][0]
    assert '\\"' in line and "\\n" in line and "\\\\" in line
    assert "\n" not in line


# ------------------------------------------------------------------ trace


def test_trace_spans_ordered_and_sum_to_total():
    tr = Trace("rid-1")
    time.sleep(0.02)
    tr.checkpoint("prefill")
    time.sleep(0.01)
    tr.checkpoint("decode")
    tr.checkpoint("decode")  # repeat accumulates, no duplicate key
    t = tr.timings()
    keys = list(t)
    assert keys == ["prefill_s", "decode_s", "total_s"]
    assert all(v >= 0 for v in t.values())
    span_sum = sum(v for k, v in t.items() if k != "total_s")
    assert span_sum <= t["total_s"] + 1e-6
    assert t["total_s"] - span_sum < 0.05
    assert tr.request_id == "rid-1"


def test_request_id_sanitization():
    assert sanitize_request_id("ok-1.2:3_X") == "ok-1.2:3_X"
    assert sanitize_request_id("  padded  ") == "padded"
    assert sanitize_request_id("bad id") is None
    assert sanitize_request_id("x" * 200) is None
    assert sanitize_request_id(7) is None
    assert sanitize_request_id(None) is None


# ----------------------------------------------------- logging satellites


def test_configure_repeat_updates_level_installs_once():
    root = pylog.getLogger("distributed_llm_inference_tpu")
    old_level = root.level
    try:
        slog.configure(pylog.INFO)
        n_handlers = len(root.handlers)
        slog.configure(pylog.DEBUG)  # used to be silently ignored
        assert root.level == pylog.DEBUG
        assert len(root.handlers) == n_handlers
    finally:
        root.setLevel(old_level)


def test_request_id_attached_to_records():
    import io

    buf = io.StringIO()
    root = pylog.getLogger("distributed_llm_inference_tpu")
    handler = pylog.StreamHandler(buf)
    handler.setFormatter(slog._JsonFormatter())
    root.addHandler(handler)
    old_level = root.level
    root.setLevel(pylog.INFO)
    try:
        log = slog.get_logger("unit-rid")
        with slog.request_id_context("rid-77"):
            log.info("inside")
        log.info("outside")
        lines = [json.loads(l) for l in buf.getvalue().strip().splitlines()]
    finally:
        root.removeHandler(handler)
        root.setLevel(old_level)
    assert lines[0]["request_id"] == "rid-77"
    assert "request_id" not in lines[1]


# ------------------------------------------------- engine + serving paths


@pytest.fixture(scope="module")
def served():
    engine = create_engine(
        "test-llama-tiny",
        engine_cfg=EngineConfig(prefill_buckets=(64,)),
    )
    cont = ContinuousEngine(engine, n_slots=2, chunk_steps=4)
    server = InferenceServer(
        engine, host="127.0.0.1", port=0, continuous=cont
    )
    server.start()
    yield server
    server.shutdown()


def _post(server, path, body, headers=None, timeout=180):
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read()), dict(r.headers)


def _assert_spans(timings: dict, required: tuple):
    keys = list(timings)
    assert keys[-1] == "total_s"
    for name in required:
        assert f"{name}_s" in timings, timings
    assert all(v >= 0 for v in timings.values())
    span_sum = sum(v for k, v in timings.items() if k != "total_s")
    total = timings["total_s"]
    assert span_sum <= total + 1e-6
    # spans must cover ≈ the end-to-end latency (contiguous checkpoints;
    # the residual is envelope assembly after the last checkpoint)
    assert total - span_sum < max(0.1, 0.25 * total), timings


def test_generate_continuous_request_id_and_timings(served):
    body, headers = _post(
        served, "/generate",
        {"prompt": "trace me", "max_tokens": 6, "chat": False},
        headers={"X-Request-Id": "corr-42"},
    )
    assert body["status"] == "success"
    assert body["request_id"] == "corr-42"
    assert headers.get("X-Request-Id") == "corr-42"
    _assert_spans(body["timings"], ("queue_wait", "admission", "decode"))


def test_generate_solo_timings(served):
    # the bare engine (the continuous front end is bypassed): solo spans
    r = served.engine.generate(
        "solo trace", max_tokens=5, greedy=True, chat=False,
        request_id="solo-1",
    )
    assert r["status"] == "success" and r["request_id"] == "solo-1"
    _assert_spans(
        r["timings"], ("queue_wait", "prefill", "decode", "detokenize")
    )


def test_bad_request_id_replaced(served):
    body, headers = _post(
        served, "/generate",
        {"prompt": "x", "max_tokens": 3, "chat": False},
        headers={"X-Request-Id": "bad id with spaces!"},
    )
    assert body["request_id"] != "bad id with spaces!"
    assert body["request_id"].startswith("req-")
    assert headers.get("X-Request-Id") == body["request_id"]


def test_metrics_route_exposition(served):
    # ensure some traffic exists on both views
    _post(served, "/generate", {"prompt": "m", "max_tokens": 3, "chat": False})
    with urllib.request.urlopen(
        f"http://127.0.0.1:{served.port}/metrics", timeout=10
    ) as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        text = r.read().decode()
    fams = _parse_exposition(text)
    # the acceptance bar: >= 10 distinct families spanning server, queue,
    # engines, prefix cache, and the constrain fleet table
    required = {
        "dli_http_requests_total",          # server
        "dli_queue_depth",                  # queue/admission
        "dli_admission_wait_seconds",
        "dli_ttft_seconds",                 # solo + continuous engines
        "dli_tpot_seconds",
        "dli_request_duration_seconds",
        "dli_requests_total",
        "dli_tokens_generated_total",
        "dli_slots_occupied",               # continuous fleet
        "dli_decode_step_seconds",
        "dli_preemptions_total",
        "dli_constraint_states_resident",   # constrain fleet
    }
    assert required <= set(fams), sorted(required - set(fams))
    assert len(fams) >= 10
    # histogram invariant everywhere: +Inf bucket == count per series
    for name, fam in fams.items():
        if fam["type"] != "histogram":
            continue
        for (sample, labels), v in fam["samples"].items():
            if sample.endswith("_bucket") and 'le="+Inf"' in labels:
                rest = ",".join(
                    p for p in labels.split(",") if not p.startswith('le=')
                )
                assert v == fam["samples"][(name + "_count", rest)]


def test_http_counter_counts_routes_and_statuses(served):
    with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(
            f"http://127.0.0.1:{served.port}/nope", timeout=10
        )
    _post(served, "/generate", {"prompt": "c", "max_tokens": 3, "chat": False})
    fam = served.engine.metrics.get("dli_http_requests_total")
    assert fam.labels(route="other", method="GET", status="404").value >= 1
    assert fam.labels(route="/generate", method="POST", status="200").value >= 1


def test_chat_completions_carry_request_id_and_timings(served):
    body, headers = _post(
        served, "/v1/chat/completions",
        {"messages": [{"role": "user", "content": "hi"}], "max_tokens": 4},
        headers={"X-Request-Id": "oai-7"},
    )
    assert body["choices"][0]["message"]["content"] is not None
    assert body["request_id"] == "oai-7"
    assert headers.get("X-Request-Id") == "oai-7"
    _assert_spans(body["timings"], ("queue_wait", "decode"))


def test_stats_consistency_with_registry():
    engine = create_engine(
        "test-llama-tiny", engine_cfg=EngineConfig(prefill_buckets=(64,))
    )
    for i in range(5):
        r = engine.generate(
            f"consistency {i}", max_tokens=3, greedy=True, chat=False
        )
        assert r["status"] == "success"
    s = engine.stats()
    h = engine.metrics.get("dli_ttft_seconds").labels(engine="solo")
    assert s["window"] == 5 == h.count == s["samples_total"]
    assert s["ttft_p50_s"] == h.percentile(0.5)
    assert s["ttft_p90_s"] == h.percentile(0.9)
    assert s["ttft_p99_s"] == h.percentile(0.99)
    assert s["ttft_p99_s"] >= s["ttft_p50_s"]
    tok = engine.metrics.get("dli_tokens_generated_total")
    assert tok.labels(engine="solo").value == s["tokens_total"]
    assert (
        engine.metrics.get("dli_requests_total")
        .labels(engine="solo", model=engine.cfg.name).value == 5
    )


def test_warmup_traffic_excluded_from_both_views():
    engine = create_engine(
        "test-llama-tiny", engine_cfg=EngineConfig(prefill_buckets=(64,))
    )
    cont = ContinuousEngine(engine, n_slots=2, chunk_steps=4)
    try:
        assert cont.warmup()["ok"]
        h = engine.metrics.get("dli_ttft_seconds").labels(engine="continuous")
        assert h.count == 0  # /metrics view clean
        assert engine.stats()["window"] == 0  # /stats view clean
        assert (
            engine.metrics.get("dli_requests_total")
            .labels(engine="continuous", model=engine.cfg.name).value == 0
        )
        r = cont.submit("real", max_tokens=4, greedy=True, chat=False)
        assert r["status"] == "success"
        _assert_spans(r["timings"], ("queue_wait", "admission", "decode"))
        assert h.count == 1
        assert engine.stats()["window"] == 1
    finally:
        cont.close()


def test_bare_engine_exposes_full_catalog_schema():
    # a solo server with no queue/continuous/prefix still renders >= 10
    # families — the scrape schema is stable across server configs
    engine = create_engine(
        "test-llama-tiny", engine_cfg=EngineConfig(prefill_buckets=(64,))
    )
    fams = {f.name for f in engine.metrics.families()}
    assert len(fams) >= 10
    assert {
        "dli_ttft_seconds", "dli_queue_depth", "dli_slots_occupied",
        "dli_prefix_cache_hits_total", "dli_preemptions_total",
        # tiered-KV families pre-register on every engine, so the
        # scrape schema is stable whether or not a tier ever fills
        "dli_kv_tier_entries", "dli_kv_tier_bytes",
        "dli_kv_tier_promotions_total", "dli_kv_tier_demotions_total",
        "dli_kv_tier_disk_hits_total",
    } <= fams


def test_queue_metrics_and_member_timings():
    engine = create_engine(
        "test-llama-tiny", engine_cfg=EngineConfig(prefill_buckets=(64,))
    )
    queue = BatchingQueue(engine, max_queue=4, max_batch=2, max_wait_ms=1.0)
    try:
        r = queue.submit(
            "through the queue", max_tokens=3, greedy=True, chat=False,
            request_id="q-1",
        )
        assert r["status"] == "success"
        assert r["request_id"] == "q-1"
        _assert_spans(r["timings"], ("queue_wait", "prefill", "decode"))
        m = engine.metrics
        assert m.get("dli_queue_depth").labels(queue="batching").value == 0
        assert (
            m.get("dli_admission_wait_seconds")
            .labels(queue="batching").count >= 1
        )
    finally:
        queue.close()
