"""Sampling-stack unit tests.

The behavioral spec is the reference's inline filter logic
(/root/reference/orchestration.py:144-169) — top-k threshold semantics and
the top-p shifted-removal (always keep the single most-likely token).
"""

import numpy as np
import jax
import jax.numpy as jnp

from distributed_llm_inference_tpu.ops import sampling


def test_top_k_keeps_k_highest():
    logits = jnp.asarray([[1.0, 5.0, 3.0, 2.0, 4.0]])
    out = np.asarray(sampling.top_k_filter(logits, jnp.int32(2)))
    assert np.isfinite(out[0, 1]) and np.isfinite(out[0, 4])
    assert (out[0, [0, 2, 3]] < -1e30).all()


def test_top_k_disabled_and_full():
    logits = jnp.asarray([[1.0, 2.0, 3.0]])
    np.testing.assert_array_equal(
        np.asarray(sampling.top_k_filter(logits, jnp.int32(0))), np.asarray(logits)
    )
    np.testing.assert_array_equal(
        np.asarray(sampling.top_k_filter(logits, jnp.int32(50))), np.asarray(logits)
    )


def test_top_p_keeps_first_over_threshold():
    # probs ~ [0.643, 0.237, 0.087, 0.032] for logits [4,3,2,1]
    logits = jnp.asarray([[4.0, 3.0, 2.0, 1.0]])
    out = np.asarray(sampling.top_p_filter(logits, jnp.float32(0.5)))
    # cum = [0.643, ...] > 0.5 already at the first token, but shifted
    # removal keeps it; everything after is removed.
    assert np.isfinite(out[0, 0])
    assert (out[0, 1:] < -1e30).all()

    out2 = np.asarray(sampling.top_p_filter(logits, jnp.float32(0.7)))
    # keep tokens until cumulative prob exceeds 0.7: first two survive
    assert np.isfinite(out2[0, 0]) and np.isfinite(out2[0, 1])
    assert (out2[0, 2:] < -1e30).all()


def test_top_p_disabled():
    logits = jnp.asarray([[4.0, 3.0, 2.0, 1.0]])
    np.testing.assert_array_equal(
        np.asarray(sampling.top_p_filter(logits, jnp.float32(1.0))), np.asarray(logits)
    )


def test_top_p_matches_reference_torch_semantics():
    """Cross-check against a literal torch reimplementation of
    orchestration.py:150-165 on random logits."""
    import pytest as _pytest

    torch = _pytest.importorskip("torch")

    rng = np.random.default_rng(0)
    for _ in range(5):
        logits_np = rng.normal(size=(1, 64)).astype(np.float32) * 3
        top_p = 0.9
        lt = torch.from_numpy(logits_np.copy())[0]
        sorted_logits, sorted_indices = torch.sort(lt, descending=True)
        cumulative_probs = torch.cumsum(torch.softmax(sorted_logits, dim=-1), dim=-1)
        sorted_indices_to_remove = cumulative_probs > top_p
        sorted_indices_to_remove[1:] = sorted_indices_to_remove[:-1].clone()
        sorted_indices_to_remove[0] = False
        indices_to_remove = sorted_indices[sorted_indices_to_remove]
        lt[indices_to_remove] = float("-inf")
        ref_removed = ~torch.isfinite(lt).numpy()

        ours = np.asarray(
            sampling.top_p_filter(jnp.asarray(logits_np), jnp.float32(top_p))
        )[0]
        ours_removed = ours < -1e30
        np.testing.assert_array_equal(ours_removed, ref_removed)


def test_greedy_and_temperature():
    logits = jnp.asarray([[0.1, 0.2, 5.0, 0.3]])
    key = jax.random.PRNGKey(0)
    tok = sampling.sample_token(
        key, logits, jnp.float32(0.7), jnp.int32(50), jnp.float32(0.9),
        jnp.bool_(True),
    )
    assert int(tok[0]) == 2

    # temperature -> near-deterministic at tiny temperature
    toks = set()
    for i in range(10):
        t = sampling.sample_token(
            jax.random.PRNGKey(i), logits, jnp.float32(1e-3), jnp.int32(0),
            jnp.float32(1.0), jnp.bool_(False),
        )
        toks.add(int(t[0]))
    assert toks == {2}


def test_sample_distribution_sane():
    """With uniform logits, sampling should cover many tokens."""
    logits = jnp.zeros((1, 16))
    toks = {
        int(
            sampling.sample_token(
                jax.random.PRNGKey(i), logits, jnp.float32(1.0), jnp.int32(0),
                jnp.float32(1.0), jnp.bool_(False),
            )[0]
        )
        for i in range(60)
    }
    assert len(toks) > 8


def test_fused_sampler_matches_unfused_filters():
    """sample_token's single-sort fused path must draw from exactly the
    distribution of top_p_filter(top_k_filter(logits/T)) (the spec path)."""
    rng = np.random.default_rng(3)
    logits = jnp.asarray(rng.normal(size=(1, 64)) * 2, jnp.float32)
    temp, k, p = jnp.float32(0.8), jnp.int32(7), jnp.float32(0.85)
    spec = sampling.top_p_filter(
        sampling.top_k_filter(sampling.apply_temperature(logits, temp), k), p
    )
    allowed = set(np.flatnonzero(np.asarray(spec)[0] > -1e30))
    drawn = {
        int(
            sampling.sample_token(
                jax.random.PRNGKey(i), logits, temp, k, p, jnp.bool_(False)
            )[0]
        )
        for i in range(200)
    }
    assert drawn <= allowed
    # with 200 draws over <=7 tokens we should see most of the support
    assert len(drawn) >= min(len(allowed), 3)


def test_top_n_probs():
    logits = jnp.asarray([[1.0, 4.0, 2.0, 3.0]])
    probs, ids = sampling.top_n_probs(logits, n=2)
    assert list(np.asarray(ids)[0]) == [1, 3]
    assert np.all(np.diff(np.asarray(probs)[0]) <= 0)
