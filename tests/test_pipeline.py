"""Pipeline-parallel equivalence tests (SURVEY.md §4 item 3): N-stage
pipelined logits/decodes must match the single-device model exactly, on the
8-virtual-CPU-device mesh — the CI stand-in for a TPU pod."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llm_inference_tpu import EngineConfig, MeshConfig, get_model_config
from distributed_llm_inference_tpu.engine import generate as G
from distributed_llm_inference_tpu.engine.engine import InferenceEngine, SingleDeviceBackend
from distributed_llm_inference_tpu.models import api as M
from distributed_llm_inference_tpu.parallel.mesh import build_mesh
from distributed_llm_inference_tpu.parallel.pipeline import PipelineBackend


def _mk(cfg_name, pp, eight_devices):
    cfg = get_model_config(cfg_name)
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(MeshConfig(dp=1, pp=pp, tp=1), eight_devices)
    return cfg, params, PipelineBackend(cfg, params, mesh)


@pytest.mark.parametrize("pp", [2, 4])
@pytest.mark.slow
def test_pipeline_prefill_logits_match_single_device(pp, eight_devices):
    cfg, params, pb = _mk("test-llama-tiny", pp, eight_devices)
    rng = np.random.default_rng(0)
    ids = rng.integers(3, cfg.vocab_size, size=11, dtype=np.int64).tolist()
    bucket = 16
    tokens = jnp.asarray([ids + [cfg.pad_token_id] * (bucket - len(ids))], jnp.int32)
    plen = jnp.int32(len(ids))
    sampling = G.default_sampling(greedy=True)
    key = jax.random.PRNGKey(1)

    cache_s = M.init_kv_cache(cfg, 1, max_seq=64)
    f_s, logits_s, _ = G.prefill(cfg, params, tokens, plen, cache_s, key, sampling)

    cache_p = pb.init_cache(1, 64)
    f_p, logits_p, _ = pb.prefill(tokens, plen, cache_p, key, sampling)

    np.testing.assert_allclose(
        np.asarray(logits_p), np.asarray(logits_s), rtol=1e-4, atol=1e-5
    )
    assert int(f_p[0]) == int(f_s[0])


@pytest.mark.parametrize(
    "cfg_name",
    [
        "test-llama-tiny",
        # gpt2 variant re-tiered round 5 (fast-tier budget): the family x
        # pp matrix is pinned by the slow tier + test_schedule
        pytest.param("test-gpt2-tiny", marks=pytest.mark.slow),
    ],
)
def test_pipeline_greedy_decode_matches_single_device(cfg_name, eight_devices):
    """Full prefill+decode: 4-stage pipeline == single device, both families."""
    cfg = get_model_config(cfg_name)
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(MeshConfig(dp=1, pp=4, tp=1), eight_devices)
    pb = PipelineBackend(cfg, params, mesh)

    rng = np.random.default_rng(2)
    ids = rng.integers(3, min(cfg.vocab_size, 250), size=7, dtype=np.int64).tolist()
    bucket, steps = 16, 8
    tokens = jnp.asarray([ids + [cfg.pad_token_id] * (bucket - len(ids))], jnp.int32)
    plen = jnp.int32(len(ids))
    sampling = G.default_sampling(greedy=True)
    key = jax.random.PRNGKey(3)
    kp, kd = jax.random.split(key)

    cache_s = M.init_kv_cache(cfg, 1, max_seq=64)
    f_s, _, cache_s = G.prefill(cfg, params, tokens, plen, cache_s, kp, sampling)
    out_s, n_s, _ = G.decode(
        cfg, params, f_s, cache_s, plen, jnp.int32(steps), kd, sampling, max_steps=steps
    )

    cache_p = pb.init_cache(1, 64)
    f_p, _, cache_p = pb.prefill(tokens, plen, cache_p, kp, sampling)
    out_p, n_p, _ = pb.decode(
        f_p, cache_p, plen, jnp.int32(steps), kd, sampling, max_steps=steps
    )

    assert int(f_p[0]) == int(f_s[0])
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out_s))
    assert int(n_p[0]) == int(n_s[0])


@pytest.mark.parametrize("n_layers,pp", [(6, 4), (5, 2), (7, 4)])
@pytest.mark.slow
def test_pipeline_uneven_split_matches_single_device(n_layers, pp, eight_devices):
    """pp that does not divide n_layers (round-1 verdict item 5): balanced
    remainder-spread ranges with zero no-op padding must stay bit-exact with
    the single-device model — the reference's own 22-layer model split
    generalized (/root/reference/Worker1.py:27-28)."""
    cfg = get_model_config("test-llama-tiny", n_layers=n_layers)
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(MeshConfig(dp=1, pp=pp, tp=1), eight_devices)
    pb = PipelineBackend(cfg, params, mesh)

    rng = np.random.default_rng(4)
    ids = rng.integers(3, cfg.vocab_size, size=9, dtype=np.int64).tolist()
    bucket, steps = 16, 6
    tokens = jnp.asarray([ids + [cfg.pad_token_id] * (bucket - len(ids))], jnp.int32)
    plen = jnp.int32(len(ids))
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(7))

    cache_s = M.init_kv_cache(cfg, 1, max_seq=64)
    f_s, logits_s, cache_s = G.prefill(cfg, params, tokens, plen, cache_s, kp, sampling)
    out_s, n_s, _ = G.decode(
        cfg, params, f_s, cache_s, plen, jnp.int32(steps), kd, sampling, max_steps=steps
    )

    cache_p = pb.init_cache(1, 64)
    f_p, logits_p, cache_p = pb.prefill(tokens, plen, cache_p, kp, sampling)
    out_p, n_p, _ = pb.decode(
        f_p, cache_p, plen, jnp.int32(steps), kd, sampling, max_steps=steps
    )

    np.testing.assert_allclose(
        np.asarray(logits_p), np.asarray(logits_s), rtol=1e-4, atol=1e-5
    )
    assert int(f_p[0]) == int(f_s[0])
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out_s))
    assert int(n_p[0]) == int(n_s[0])

    # stage ranges: balanced remainder spread, complete and in order
    ranges = [h["layers"] for h in pb.health()]
    flat = [l for r in ranges for l in r]
    assert flat == list(range(n_layers))
    sizes = [len(r) for r in ranges]
    assert max(sizes) - min(sizes) <= 1


@pytest.mark.slow
def test_embed_and_head_vocab_sharded(eight_devices):
    """Round-1 verdict item 6: embed/lm_head must NOT be fully replicated
    on every device — each device holds a 1/pp vocab shard (padded to a
    multiple of pp), and logits stay bit-compatible (checked by every
    equivalence test above)."""
    cfg, params, pb = _mk("test-llama-tiny", 4, eight_devices)
    V, D = cfg.vocab_size, cfg.dim
    embed = pb.shared["embed"]
    V_pad = -(-V // 4) * 4
    assert embed.shape == (V_pad, D)
    assert embed.sharding.shard_shape(embed.shape) == (V_pad // 4, D)
    head = pb.shared["lm_head"]
    assert head.shape == (D, V_pad)
    assert head.sharding.shard_shape(head.shape) == (D, V_pad // 4)
    # norms stay replicated
    fn = pb.shared["final_norm"]
    assert fn.sharding.shard_shape(fn.shape) == fn.shape


@pytest.mark.slow
def test_vocab_shard_odd_vocab(eight_devices):
    """A vocab size not divisible by pp (GPT-2's 50257-style) pads and
    still decodes bit-exactly vs single device."""
    cfg = get_model_config("test-llama-tiny", vocab_size=253)
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(MeshConfig(dp=1, pp=4, tp=1), eight_devices)
    pb = PipelineBackend(cfg, params, mesh)

    ids = [5, 9, 13, 250, 252]
    bucket, steps = 16, 6
    tokens = jnp.asarray([ids + [cfg.pad_token_id] * (bucket - len(ids))], jnp.int32)
    plen = jnp.int32(len(ids))
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(17))

    cache_s = M.init_kv_cache(cfg, 1, max_seq=64)
    f_s, logits_s, cache_s = G.prefill(cfg, params, tokens, plen, cache_s, kp, sampling)
    out_s, _, _ = G.decode(
        cfg, params, f_s, cache_s, plen, jnp.int32(steps), kd, sampling, max_steps=steps
    )
    cache_p = pb.init_cache(1, 64)
    f_p, logits_p, cache_p = pb.prefill(tokens, plen, cache_p, kp, sampling)
    out_p, _, _ = pb.decode(
        f_p, cache_p, plen, jnp.int32(steps), kd, sampling, max_steps=steps
    )
    assert logits_p.shape == logits_s.shape  # pad columns sliced off
    np.testing.assert_allclose(
        np.asarray(logits_p), np.asarray(logits_s), rtol=1e-4, atol=1e-5
    )
    assert int(f_p[0]) == int(f_s[0])
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out_s))


@pytest.mark.slow
def test_engine_with_pipeline_backend(eight_devices):
    """InferenceEngine over the pipeline backend: same response as over the
    single-device backend for a seeded greedy request."""
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(MeshConfig(dp=1, pp=2, tp=1), eight_devices)

    ecfg = EngineConfig(prefill_buckets=(32,))
    eng_s = InferenceEngine(cfg, backend=SingleDeviceBackend(cfg, params), engine_cfg=ecfg)
    eng_p = InferenceEngine(cfg, backend=PipelineBackend(cfg, params, mesh), engine_cfg=ecfg)

    r_s = eng_s.generate("pipeline", max_tokens=6, greedy=True, chat=False, seed=5)
    r_p = eng_p.generate("pipeline", max_tokens=6, greedy=True, chat=False, seed=5)
    assert r_s["status"] == r_p["status"] == "success"
    assert r_s["response"] == r_p["response"]
    assert r_p["backend"] == "pipeline"

    w = eng_p.workers()
    assert w["total"] == 2
    assert w["workers"]["stage_1"]["layers"] == [2, 3]


@pytest.mark.slow
def test_pipeline_sampled_decode_matches_single_device(eight_devices):
    """Sampling path (temperature/top-k/top-p) must also agree: identical
    keys and identical logits => identical draws."""
    cfg, params, pb = _mk("test-llama-tiny", 2, eight_devices)
    ids = [5, 9, 13]
    bucket, steps = 16, 8
    tokens = jnp.asarray([ids + [cfg.pad_token_id] * (bucket - len(ids))], jnp.int32)
    plen = jnp.int32(len(ids))
    sampling = G.default_sampling(temperature=0.9, top_k=20, top_p=0.95)
    kp, kd = jax.random.split(jax.random.PRNGKey(11))

    cache_s = M.init_kv_cache(cfg, 1, max_seq=64)
    f_s, _, cache_s = G.prefill(cfg, params, tokens, plen, cache_s, kp, sampling)
    out_s, _, _ = G.decode(
        cfg, params, f_s, cache_s, plen, jnp.int32(steps), kd, sampling, max_steps=steps
    )
    cache_p = pb.init_cache(1, 64)
    f_p, _, cache_p = pb.prefill(tokens, plen, cache_p, kp, sampling)
    out_p, _, _ = pb.decode(
        f_p, cache_p, plen, jnp.int32(steps), kd, sampling, max_steps=steps
    )
    assert int(f_p[0]) == int(f_s[0])
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out_s))
