"""OpenAI-compatible API surface (serving/openai_api.py): /v1/models,
/v1/completions (single + batched prompts + SSE streaming),
/v1/chat/completions (multi-turn templating + SSE streaming), OpenAI error
objects, and usage accounting — all over real HTTP against a served tiny
model. Beyond-reference feature: the reference serves only its own ad-hoc
/generate schema (/root/reference/orchestration.py:331-356)."""

import json
import urllib.error
import urllib.request

import pytest

from distributed_llm_inference_tpu import EngineConfig, MeshConfig, create_engine
from distributed_llm_inference_tpu.engine.chat import (
    format_chat_messages,
    format_chat_prompt,
)
from distributed_llm_inference_tpu.serving.server import InferenceServer


@pytest.fixture(scope="module")
def served():
    engine = create_engine(
        "test-llama-tiny",
        mesh_cfg=MeshConfig(),
        engine_cfg=EngineConfig(prefill_buckets=(64, 128)),
    )
    server = InferenceServer(engine, host="127.0.0.1", port=0)
    server.start()
    yield server
    server.shutdown()


def _post(server, path, body, timeout=120):
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _post_raw(server, path, body, timeout=120):
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    return urllib.request.urlopen(req, timeout=timeout)


def test_models_route(served):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{served.port}/v1/models", timeout=10
    ) as r:
        out = json.loads(r.read())
    assert out["object"] == "list"
    assert out["data"][0]["id"] == "test-llama-tiny"
    assert out["data"][0]["object"] == "model"


def test_completions_basic(served):
    out = _post(served, "/v1/completions", {
        "model": "test-llama-tiny",
        "prompt": "hello world",
        "max_tokens": 6,
        "temperature": 0,
    })
    assert out["object"] == "text_completion"
    assert out["id"].startswith("cmpl-")
    assert len(out["choices"]) == 1
    c = out["choices"][0]
    assert c["index"] == 0
    assert isinstance(c["text"], str)
    assert c["finish_reason"] in ("stop", "length")
    u = out["usage"]
    assert u["prompt_tokens"] > 0
    assert u["total_tokens"] == u["prompt_tokens"] + u["completion_tokens"]
    assert u["completion_tokens"] <= 6


def test_completions_greedy_matches_engine(served):
    """temperature=0 must be the engine's greedy argmax path, raw
    continuation (no chat template)."""
    out = _post(served, "/v1/completions", {
        "prompt": "the quick brown", "max_tokens": 5, "temperature": 0,
    })
    ref = served.engine.generate(
        "the quick brown", max_tokens=5, greedy=True, chat=False,
    )
    assert out["choices"][0]["text"] == ref["response"]


def test_completions_batched_prompt_list(served):
    out = _post(served, "/v1/completions", {
        "prompt": ["alpha beta", "gamma delta epsilon"],
        "max_tokens": 4,
        "temperature": 0,
    })
    assert [c["index"] for c in out["choices"]] == [0, 1]
    assert out["usage"]["prompt_tokens"] > 0
    # batched greedy rows must equal solo greedy rows (ragged-batch parity)
    for prompt, choice in zip(["alpha beta", "gamma delta epsilon"],
                              out["choices"]):
        ref = served.engine.generate(
            prompt, max_tokens=4, greedy=True, chat=False
        )
        assert choice["text"] == ref["response"]


def test_completions_finish_reason_length(served):
    out = _post(served, "/v1/completions", {
        "prompt": "a b c", "max_tokens": 3, "temperature": 0,
    })
    c = out["choices"][0]
    if out["usage"]["completion_tokens"] == 3:
        assert c["finish_reason"] == "length"


def test_completions_stop_sequence(served):
    # stop="" is ignored; a stop that fires reports finish_reason "stop"
    base = _post(served, "/v1/completions", {
        "prompt": "x y", "max_tokens": 8, "temperature": 0,
    })["choices"][0]["text"]
    if len(base) > 2:
        needle = base[1]
        out = _post(served, "/v1/completions", {
            "prompt": "x y", "max_tokens": 8, "temperature": 0,
            "stop": needle,
        })
        c = out["choices"][0]
        assert needle not in c["text"]
        assert c["finish_reason"] == "stop"


def test_completions_logprobs(served):
    out = _post(served, "/v1/completions", {
        "prompt": "hello", "max_tokens": 4, "temperature": 0, "logprobs": 1,
    })
    lp = out["choices"][0]["logprobs"]
    assert len(lp["token_logprobs"]) == out["usage"]["completion_tokens"]
    assert all(x <= 0.0 for x in lp["token_logprobs"])


def test_completions_seeded_sampling_reproducible(served):
    body = {"prompt": "seed test", "max_tokens": 6, "temperature": 0.9,
            "seed": 123}
    a = _post(served, "/v1/completions", body)
    b = _post(served, "/v1/completions", body)
    assert a["choices"][0]["text"] == b["choices"][0]["text"]


def test_completions_errors(served):
    for body, param in [
        ({"max_tokens": 4}, "prompt"),
        ({"prompt": "x", "n": 99}, "n"),
        ({"prompt": "x", "n": "junk"}, "n"),
        ({"prompt": ["a", "b"], "n": 2}, "n"),
        ({"prompt": "x", "n": 2, "stream": True}, "n"),
        ({"prompt": "x", "best_of": 2}, "best_of"),
        ({"prompt": "x", "logit_bias": {"5": 500}}, "logit_bias"),
        ({"prompt": "x", "logit_bias": {"x": "y"}}, "logit_bias"),
        # in-range penalties are SUPPORTED now; only out-of-range /
        # non-numeric values reject (OpenAI's documented [-2, 2])
        ({"prompt": "x", "frequency_penalty": 2.5}, "frequency_penalty"),
        ({"prompt": "x", "frequency_penalty": "y"}, "frequency_penalty"),
        ({"prompt": "x", "presence_penalty": -9}, "presence_penalty"),
        ({"prompt": "x", "temperature": -1}, "temperature"),
        ({"prompt": "x", "max_tokens": 0}, "max_tokens"),
        ({"prompt": "x", "stop": 5}, "stop"),
    ]:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(served, "/v1/completions", body)
        assert ei.value.code == 400
        err = json.loads(ei.value.read())["error"]
        assert err["type"] == "invalid_request_error"
        assert err["param"] == param


def test_completions_sse_stream(served):
    with _post_raw(served, "/v1/completions", {
        "prompt": "stream me", "max_tokens": 5, "temperature": 0,
        "stream": True,
    }) as r:
        assert r.headers["Content-Type"].startswith("text/event-stream")
        raw = r.read().decode()
    events = [json.loads(line[len("data: "):])
              for line in raw.strip().split("\n\n")
              if line.startswith("data: ") and line != "data: [DONE]"]
    assert raw.strip().endswith("data: [DONE]")
    assert all(e["object"] == "text_completion" for e in events)
    # exactly one terminal chunk, carrying finish_reason + usage
    finals = [e for e in events if e["choices"][0]["finish_reason"]]
    assert len(finals) == 1
    assert finals[0]["usage"]["completion_tokens"] <= 5
    text = "".join(e["choices"][0]["text"] for e in events)
    ref = served.engine.generate(
        "stream me", max_tokens=5, greedy=True, chat=False
    )
    assert text == ref["response"]


def test_chat_completions_basic(served):
    out = _post(served, "/v1/chat/completions", {
        "messages": [
            {"role": "system", "content": "Be terse."},
            {"role": "user", "content": "hi there"},
        ],
        "max_tokens": 6,
        "temperature": 0,
    })
    assert out["object"] == "chat.completion"
    assert out["id"].startswith("chatcmpl-")
    msg = out["choices"][0]["message"]
    assert msg["role"] == "assistant"
    assert isinstance(msg["content"], str)
    assert out["usage"]["prompt_tokens"] > 0


def test_chat_completions_template_parity(served):
    """The chat route must render the model family's template: its greedy
    output == engine.generate(chat=True) on the same single user turn."""
    out = _post(served, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "what is up"}],
        "max_tokens": 5,
        "temperature": 0,
    })
    ref = served.engine.generate(
        "what is up", max_tokens=5, greedy=True, chat=True
    )
    assert out["choices"][0]["message"]["content"] == ref["response"]


def test_chat_completions_sse_stream(served):
    with _post_raw(served, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "stream chat"}],
        "max_tokens": 5, "temperature": 0, "stream": True,
    }) as r:
        raw = r.read().decode()
    events = [json.loads(line[len("data: "):])
              for line in raw.strip().split("\n\n")
              if line.startswith("data: ") and line != "data: [DONE]"]
    assert raw.strip().endswith("data: [DONE]")
    assert all(e["object"] == "chat.completion.chunk" for e in events)
    # first chunk announces the assistant role (OpenAI convention)
    assert events[0]["choices"][0]["delta"].get("role") == "assistant"
    finals = [e for e in events if e["choices"][0]["finish_reason"]]
    assert len(finals) == 1
    text = "".join(
        e["choices"][0]["delta"].get("content", "") for e in events
    )
    ref = served.engine.generate(
        "stream chat", max_tokens=5, greedy=True, chat=True
    )
    assert text == ref["response"]


def test_chat_completions_bad_messages(served):
    for msgs in [
        [],
        [{"role": "user", "content": "a"}, {"role": "system", "content": "b"}],
        [{"role": "assistant", "content": "only assistant"}],
        [{"role": "tool", "content": "x"}, {"role": "user", "content": "y"}],
    ]:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(served, "/v1/chat/completions",
                  {"messages": msgs, "max_tokens": 4})
        assert ei.value.code == 400


# -- multi-turn template rendering (pure functions) -------------------------


def test_format_chat_messages_single_turn_parity():
    """One user turn through the messages renderer == format_chat_prompt,
    byte-identical, for every template."""
    for arch, template in [("llama", None), ("llama", "tinyllama"),
                           ("gpt2", None), ("llama", "gemma"),
                           ("llama", "phi3")]:
        a = format_chat_messages(
            [{"role": "user", "content": "hello"}], arch=arch,
            template=template,
        )
        b = format_chat_prompt("hello", arch=arch, template=template)
        assert a == b, (arch, template)


def test_format_chat_messages_multi_turn():
    msgs = [
        {"role": "system", "content": "sys"},
        {"role": "user", "content": "q1"},
        {"role": "assistant", "content": "a1"},
        {"role": "user", "content": "q2"},
    ]
    z = format_chat_messages(msgs, arch="llama", template="tinyllama")
    assert z == ("<|system|>\nsys</s>\n<|user|>\nq1</s>\n"
                 "<|assistant|>\na1</s>\n<|user|>\nq2</s>\n<|assistant|>\n")
    g = format_chat_messages(msgs, arch="llama", template="gemma")
    assert g == ("<start_of_turn>user\nsys\n\nq1<end_of_turn>\n"
                 "<start_of_turn>model\na1<end_of_turn>\n"
                 "<start_of_turn>user\nq2<end_of_turn>\n"
                 "<start_of_turn>model\n")
    p = format_chat_messages(msgs, arch="llama", template="phi3")
    assert p == ("<|system|>\nsys<|end|>\n<|user|>\nq1<|end|>\n"
                 "<|assistant|>\na1<|end|>\n<|user|>\nq2<|end|>\n"
                 "<|assistant|>\n")
    n = format_chat_messages(msgs, arch="gpt2")
    assert n == "sys\nq1\na1\nq2"


def test_completions_n_choices(served):
    out = _post(served, "/v1/completions", {
        "prompt": "pick some words", "max_tokens": 4, "n": 3,
        "temperature": 0.9,
    })
    assert [c["index"] for c in out["choices"]] == [0, 1, 2]
    # prompt billed ONCE for n choices (OpenAI semantics)
    one = _post(served, "/v1/completions", {
        "prompt": "pick some words", "max_tokens": 4, "temperature": 0.9,
    })
    assert out["usage"]["prompt_tokens"] == one["usage"]["prompt_tokens"]
    assert out["usage"]["completion_tokens"] <= 12


def test_chat_completions_n_choices(served):
    out = _post(served, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": 3, "n": 2, "temperature": 0.8,
    })
    assert len(out["choices"]) == 2
    assert all(c["message"]["role"] == "assistant" for c in out["choices"])


def test_logit_bias_forces_and_bans(served):
    """+100 on one token forces it at every step under greedy; banning the
    natural first choice changes the output (OpenAI logit_bias semantics)."""
    eng = served.engine
    forced = 17
    out = _post(served, "/v1/completions", {
        "prompt": "bias me", "max_tokens": 4, "temperature": 0,
        "logit_bias": {str(forced): 100},
    })
    ids = eng.tokenizer.encode(out["choices"][0]["text"])
    # every generated token is the forced one (decoded text re-encodes to
    # it; compare via the engine to dodge tokenizer round-trip quirks)
    r = eng.generate("bias me", max_tokens=4, greedy=True, chat=False,
                     logit_bias={forced: 100.0})
    assert r["status"] == "success"
    assert out["choices"][0]["text"] == r["response"]

    base = eng.generate("ban test", max_tokens=1, greedy=True, chat=False)
    first_id = eng.tokenizer.encode(base["response"])
    if len(first_id) == 1:  # ban the natural argmax -> different token
        banned = eng.generate(
            "ban test", max_tokens=1, greedy=True, chat=False,
            logit_bias={first_id[0]: -100.0},
        )
        assert banned["response"] != base["response"]


def test_logit_bias_engine_validation(served):
    r = served.engine.generate("x", max_tokens=2, greedy=True, chat=False,
                               logit_bias={10**9: 5.0})
    assert r["status"] == "failed"
    assert r["error_type"] == "invalid_request"


def test_stream_logprobs_and_top_logprobs_rejected(served):
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(served, "/v1/completions", {
            "prompt": "x", "stream": True, "logprobs": 1,
        })
    assert ei.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(served, "/v1/chat/completions", {
            "messages": [{"role": "user", "content": "x"}],
            "logprobs": True, "top_logprobs": 5,
        })
    assert ei.value.code == 400
    assert json.loads(ei.value.read())["error"]["param"] == "top_logprobs"


def test_chat_logprobs_token_strings(served):
    out = _post(served, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "hi"}],
        "max_tokens": 4, "temperature": 0, "logprobs": True,
    })
    content = out["choices"][0]["logprobs"]["content"]
    assert len(content) == out["usage"]["completion_tokens"]
    for c in content:
        assert isinstance(c["token"], str)
        assert c["logprob"] <= 0.0


def test_engine_reports_finish_reason(served):
    eng = served.engine
    r = eng.generate("a b c d", max_tokens=3, greedy=True, chat=False)
    assert r["finish_reason"] in ("stop", "length")
    if r["tokens_generated"] == 3:
        assert r["finish_reason"] == "length"
    # a fired textual stop is always finish_reason "stop"
    base = eng.generate("a b c d", max_tokens=8, greedy=True, chat=False)
    if len(base["response"]) > 2:
        r2 = eng.generate(
            "a b c d", max_tokens=8, greedy=True, chat=False,
            stop=[base["response"][1]],
        )
        assert r2["finish_reason"] == "stop"


def test_completions_null_max_tokens_falls_through(served):
    """Clients migrating to max_completion_tokens often null the old key."""
    out = _post(served, "/v1/completions", {
        "prompt": "hello", "max_tokens": None, "max_completion_tokens": 7,
        "temperature": 0,
    })
    assert out["usage"]["completion_tokens"] <= 7
    # and logprobs: 0 is "chosen tokens' logprobs, 0 alternatives" — not off
    out = _post(served, "/v1/completions", {
        "prompt": "hello", "max_tokens": 3, "temperature": 0, "logprobs": 0,
    })
    assert "logprobs" in out["choices"][0]


def test_stream_events_flushes_solo_fallback_text():
    """A continuous-engine solo fallback (seeded/logprobs requests) yields
    only the final envelope, no deltas — the SSE adapter must still deliver
    the full completion text."""
    from distributed_llm_inference_tpu.serving.openai_api import stream_events

    events = iter([
        {"response": "full text", "status": "success", "tokens_generated": 2,
         "prompt_tokens": 3, "done": True},
    ])
    payloads = [p for p, _ in stream_events(
        events, "m", {"max_tokens": 8}, chat=False
    )]
    text = "".join(
        json.loads(p[len(b"data: "):].decode())["choices"][0]["text"]
        for p in payloads
        if p.startswith(b"data: {")
    )
    assert text == "full text"


def test_format_chat_messages_gemma_system_folds_into_user_turn():
    """An assistant-first history must not swallow the system text into a
    model turn — it folds into the first USER turn."""
    out = format_chat_messages(
        [{"role": "system", "content": "sys"},
         {"role": "assistant", "content": "greeting"},
         {"role": "user", "content": "q"}],
        arch="llama", template="gemma",
    )
    assert out == ("<start_of_turn>model\ngreeting<end_of_turn>\n"
                   "<start_of_turn>user\nsys\n\nq<end_of_turn>\n"
                   "<start_of_turn>model\n")


def test_format_chat_messages_must_end_with_user():
    with pytest.raises(ValueError):
        format_chat_messages(
            [{"role": "user", "content": "q"},
             {"role": "assistant", "content": "a"}],
            arch="llama",
        )
