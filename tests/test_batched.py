"""Ragged batched generation (left-padded, per-row validity mask).

Correctness bar: a batch of different-length prompts must produce, per
row, the SAME greedy tokens as running that prompt alone through the
unbatched path — left-padding and masking must be invisible.
"""

import json
import urllib.request

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llm_inference_tpu import EngineConfig, MeshConfig, create_engine
from distributed_llm_inference_tpu.engine import generate as G
from distributed_llm_inference_tpu.engine.engine import SingleDeviceBackend
from distributed_llm_inference_tpu.models import api as M
from distributed_llm_inference_tpu.models.registry import get_model_config


def _greedy_single(cfg, params, ids, steps, max_seq=64):
    """Unbatched right-padded reference run for one prompt."""
    bucket = 16
    plen = len(ids)
    tokens = jnp.asarray(
        [ids + [cfg.pad_token_id] * (bucket - plen)], jnp.int32
    )
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(5))
    cache = M.init_kv_cache(cfg, 1, max_seq=max_seq)
    first, _, cache = G.prefill(
        cfg, params, tokens, jnp.int32(plen), cache, kp, sampling
    )
    out, n_gen, _ = G.decode(
        cfg, params, first, cache, jnp.int32(plen), jnp.int32(steps - 1),
        kd, sampling, max_steps=steps,
    )
    row = [int(first[0])] + [int(t) for t in list(out[0][: int(n_gen[0])])]
    return row


@pytest.mark.slow  # re-tiered round 5 (fast-tier budget): the per-row
# equivalence duplicates test_engine_generate_batch's coverage at 4x cost
def test_ragged_batch_matches_individual_runs():
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(3)
    prompts = [
        [int(t) for t in rng.integers(3, cfg.vocab_size, size=n)]
        for n in (4, 9, 16)
    ]
    steps, bucket, max_seq = 6, 16, 64

    refs = [_greedy_single(cfg, params, ids, steps) for ids in prompts]

    # batched: left-pad to the shared bucket
    pad = cfg.pad_token_id
    tokens = jnp.asarray(
        [[pad] * (bucket - len(ids)) + ids for ids in prompts], jnp.int32
    )
    valid_start = jnp.asarray([bucket - len(ids) for ids in prompts], jnp.int32)
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(5))
    cache = M.init_kv_cache(cfg, 3, max_seq=max_seq)
    first, _, cache = G.prefill(
        cfg, params, tokens, jnp.int32(bucket), cache, kp, sampling, valid_start
    )
    out, n_gen, _ = G.decode(
        cfg, params, first, cache, jnp.int32(bucket), jnp.int32(steps - 1),
        kd, sampling, valid_start, max_steps=steps,
    )
    for b, ref in enumerate(refs):
        row = [int(first[b])] + [int(t) for t in list(out[b][: int(n_gen[b])])]
        # rows that hit EOS keep their shorter ref
        assert row == ref, f"row {b}: {row} != {ref}"


@pytest.mark.parametrize(
    "mesh_cfg",
    [MeshConfig(), MeshConfig(dp=1, pp=2, tp=1)],
    ids=["single-device", "pp2"],
)
@pytest.mark.slow
def test_engine_generate_batch(mesh_cfg, eight_devices):
    engine = create_engine(
        "test-llama-tiny",
        mesh_cfg=mesh_cfg,
        engine_cfg=EngineConfig(prefill_buckets=(64, 128)),
    )
    r = engine.generate_batch(
        ["short", "a much longer prompt with more words in it"],
        max_tokens=5, greedy=True, seed=0,
    )
    assert r["status"] == "success", r
    assert r["batch_size"] == 2 and len(r["results"]) == 2
    for row in r["results"]:
        assert row["status"] == "success"
        assert row["tokens_generated"] <= 5

    # single-prompt result must be unaffected by batching machinery
    single = engine.generate(
        "short", max_tokens=5, greedy=True, chat=True, seed=0
    )
    assert single["status"] == "success"


@pytest.mark.slow
def test_pipeline_ragged_batch_matches_single_device(eight_devices):
    """Backend-level bit-exactness: ragged left-padded batch on a pp=2 mesh
    == the same batch on the single-device backend (greedy)."""
    from distributed_llm_inference_tpu.parallel.mesh import build_mesh
    from distributed_llm_inference_tpu.parallel.pipeline import PipelineBackend

    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(3)
    prompts = [
        [int(t) for t in rng.integers(3, cfg.vocab_size, size=n)]
        for n in (4, 9, 16, 12)
    ]
    steps, bucket, max_seq = 6, 16, 64
    pad = cfg.pad_token_id
    tokens = jnp.asarray(
        [[pad] * (bucket - len(ids)) + ids for ids in prompts], jnp.int32
    )
    valid_start = jnp.asarray([bucket - len(ids) for ids in prompts], jnp.int32)
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(5))

    cache = M.init_kv_cache(cfg, len(prompts), max_seq=max_seq)
    f_s, _, cache = G.prefill(
        cfg, params, tokens, jnp.int32(bucket), cache, kp, sampling, valid_start
    )
    out_s, n_s, _ = G.decode(
        cfg, params, f_s, cache, jnp.int32(bucket), jnp.int32(steps - 1),
        kd, sampling, valid_start, max_steps=steps,
    )

    mesh = build_mesh(MeshConfig(dp=1, pp=2, tp=1), eight_devices)
    pb = PipelineBackend(cfg, params, mesh)
    cache_p = pb.init_cache(len(prompts), max_seq)
    f_p, _, cache_p = pb.prefill(
        tokens, jnp.int32(bucket), cache_p, kp, sampling, valid_start
    )
    out_p, n_p, _ = pb.decode(
        f_p, cache_p, jnp.int32(bucket), jnp.int32(steps - 1), kd, sampling,
        valid_start, max_steps=steps,
    )

    np.testing.assert_array_equal(np.asarray(f_p), np.asarray(f_s))
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out_s))
    np.testing.assert_array_equal(np.asarray(n_p), np.asarray(n_s))


def test_engine_generate_batch_rejects_bad_input():
    engine = create_engine(
        "test-llama-tiny", engine_cfg=EngineConfig(prefill_buckets=(64,))
    )
    r = engine.generate_batch([], max_tokens=3)
    assert r["status"] == "failed" and r["error_type"] == "invalid_request"
    r = engine.generate_batch(["ok", ""], max_tokens=3)
    assert r["status"] == "failed" and r["error_type"] == "invalid_request"

    gpt2 = create_engine(
        "test-gpt2-tiny", engine_cfg=EngineConfig(prefill_buckets=(64,))
    )
    r = gpt2.generate_batch(["a", "b"], max_tokens=3)
    assert r["status"] == "failed" and "llama-family" in r["error"]


@pytest.mark.slow
def test_batched_over_http():
    from distributed_llm_inference_tpu.serving.server import InferenceServer

    engine = create_engine(
        "test-llama-tiny", engine_cfg=EngineConfig(prefill_buckets=(64, 128))
    )
    server = InferenceServer(engine, host="127.0.0.1", port=0)
    server.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/generate",
            data=json.dumps(
                {"prompts": ["one", "two prompts"], "max_tokens": 4, "greedy": True}
            ).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            r = json.loads(resp.read())
        assert r["status"] == "success"
        assert r["batch_size"] == 2 and len(r["results"]) == 2
    finally:
        server.shutdown()
