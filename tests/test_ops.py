"""Unit tests for norms / RoPE / attention-cache ops against torch or
closed-form references."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llm_inference_tpu.ops import attention, norms, rope


def test_rms_norm_matches_torch():
    torch = pytest.importorskip("torch")
    x = np.random.default_rng(0).normal(size=(2, 5, 16)).astype(np.float32)
    w = np.random.default_rng(1).normal(size=(16,)).astype(np.float32)
    xt = torch.from_numpy(x)
    var = xt.pow(2).mean(-1, keepdim=True)
    ref = (xt * torch.rsqrt(var + 1e-5)) * torch.from_numpy(w)
    ours = norms.rms_norm(jnp.asarray(x), jnp.asarray(w), 1e-5)
    np.testing.assert_allclose(np.asarray(ours), ref.numpy(), rtol=1e-5, atol=1e-6)


def test_layer_norm_matches_torch():
    torch = pytest.importorskip("torch")
    x = np.random.default_rng(0).normal(size=(2, 5, 16)).astype(np.float32)
    w = np.random.default_rng(1).normal(size=(16,)).astype(np.float32)
    b = np.random.default_rng(2).normal(size=(16,)).astype(np.float32)
    ref = torch.nn.functional.layer_norm(
        torch.from_numpy(x), (16,), torch.from_numpy(w), torch.from_numpy(b), 1e-5
    )
    ours = norms.layer_norm(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b), 1e-5)
    np.testing.assert_allclose(np.asarray(ours), ref.numpy(), rtol=1e-5, atol=1e-5)


def test_rope_preserves_norm_and_relative_phase():
    cos, sin = rope.rope_cos_sin(jnp.arange(8), 16, 10000.0)
    q = jnp.ones((1, 8, 2, 16))
    k = jnp.ones((1, 8, 2, 16))
    qr, kr = rope.apply_rope(q, k, cos, sin)
    # rotation preserves per-head vector norm
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(qr), axis=-1),
        np.linalg.norm(np.asarray(q), axis=-1),
        rtol=1e-5,
    )
    # position 0 is identity rotation
    np.testing.assert_allclose(np.asarray(qr[0, 0]), np.asarray(q[0, 0]), rtol=1e-6)
    # q.k depends only on relative offset: <q_i, k_j> == <q_{i+d}, k_{j+d}>
    qi_kj = np.einsum("d,d->", np.asarray(qr)[0, 2, 0], np.asarray(kr)[0, 5, 0])
    qi_kj_shift = np.einsum("d,d->", np.asarray(qr)[0, 3, 0], np.asarray(kr)[0, 6, 0])
    np.testing.assert_allclose(qi_kj, qi_kj_shift, rtol=1e-4)


def test_kv_cache_update_and_mask():
    # cache [B, KV, S, Dh]; new chunk [B, T, KV, Dh]
    ck = jnp.zeros((1, 2, 8, 4))
    cv = jnp.zeros((1, 2, 8, 4))
    k_new = jnp.ones((1, 3, 2, 4))
    ck2, cv2 = attention.update_kv_cache(ck, cv, k_new, k_new * 2, jnp.int32(2))
    arr = np.asarray(ck2)
    assert (arr[:, :, 2:5] == 1).all() and (arr[:, :, :2] == 0).all() and (arr[:, :, 5:] == 0).all()
    assert (np.asarray(cv2)[:, :, 2:5] == 2).all()

    mask = np.asarray(attention.causal_mask(jnp.int32(2), 3, 8))
    # query t=0 is absolute position 2: sees slots 0..2
    assert mask[0, :3].all() and not mask[0, 3:].any()
    assert mask[2, :5].all() and not mask[2, 5:].any()


def test_attend_gqa_equals_repeated_mha():
    """GQA grouped einsum == explicitly repeating KV heads."""
    rng = np.random.default_rng(0)
    B, T, S, H, KV, Dh = 1, 4, 6, 4, 2, 8
    q = jnp.asarray(rng.normal(size=(B, T, H, Dh)), jnp.float32)
    ck = jnp.asarray(rng.normal(size=(B, KV, S, Dh)), jnp.float32)
    cv = jnp.asarray(rng.normal(size=(B, KV, S, Dh)), jnp.float32)
    mask = attention.causal_mask(jnp.int32(2), T, S)
    out = attention.attend(q, ck, cv, mask)

    ck_rep = jnp.repeat(ck.transpose(0, 2, 1, 3), H // KV, axis=2)
    cv_rep = jnp.repeat(cv.transpose(0, 2, 1, 3), H // KV, axis=2)
    scores = jnp.einsum("bthd,bshd->bhts", q, ck_rep) * (Dh ** -0.5)
    scores = jnp.where(mask[None, None], scores, jnp.finfo(jnp.float32).min)
    ref = jnp.einsum("bhts,bshd->bthd", jax.nn.softmax(scores, -1), cv_rep)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-6)
