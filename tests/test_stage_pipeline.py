"""Chaos matrix for the multi-process MPMD stage pipeline
(serving/stage_runtime.py).

Every test here runs the REAL deployment shape on CPU: each stage is a
separate OS process owning a contiguous layer slice, driven over the
HTTP stage transport. The matrix kills each stage role (first / middle
/ last) with SIGKILL at the prefill and decode launch boundaries, under
warm (shadow present) and cold (shadow wiped) restore, and requires the
greedy output to be BIT-IDENTICAL to a fault-free single-process run in
every cell — plus pool `free == total` on every stage after recovery,
heartbeat-timeout -> unready -> readmission, and a rolling stage
restart under live concurrent load with zero failures.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from distributed_llm_inference_tpu.analysis.callgraph import (
    build_index, decode_unreachable, traced_reachable,
)
from distributed_llm_inference_tpu.models import api as M
from distributed_llm_inference_tpu.models.registry import get_model_config
from distributed_llm_inference_tpu.parallel.schedule import (
    mpmd_1f1b_order, plan_stages,
)
from distributed_llm_inference_tpu.serving.stage_runtime import (
    HttpStageTransport, MPMDPipeline, StageSupervisor, free_port,
)
from distributed_llm_inference_tpu.utils import faults
from distributed_llm_inference_tpu.utils.tokenizer import ByteTokenizer

MODEL = "test-llama-tiny"
BLOCK = 8
PROMPT = "stage chaos!"  # 13 tokens with bos: boundary-misaligned on purpose
N_NEW = 16
KILL_AFTER = 6  # decode steps before the mid-decode SIGKILL

PKG_ROOT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "distributed_llm_inference_tpu",
)


def _stage_env(extra=None):
    env = dict(os.environ)
    # stage processes need no virtual mesh — one device boots faster
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("DLI_FAULTS", None)
    env.update(extra or {})
    return env


def wait_until(pred, timeout_s: float, interval_s: float = 0.1):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval_s)
    return False


@pytest.fixture(scope="module")
def reference():
    """Fault-free single-process greedy transcripts, by (prompt, n)."""
    cfg = get_model_config(MODEL)
    tok = ByteTokenizer()
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    memo = {}

    def run(prompt: str, max_new: int):
        key = (prompt, max_new)
        if key in memo:
            return memo[key]
        ids = tok.encode(prompt)
        cache = M.init_kv_cache(cfg, 1, cfg.max_seq_len, cfg.n_layers)
        logits, cache = M.forward(
            cfg, params, jnp.asarray([ids], jnp.int32), cache, 0
        )
        t = int(jnp.argmax(logits[0, -1]))
        out, pos = [t], len(ids)
        for _ in range(max_new - 1):
            if t == tok.eos_token_id:
                break
            logits, cache = M.forward(
                cfg, params, jnp.asarray([[t]], jnp.int32), cache, pos
            )
            t = int(jnp.argmax(logits[0, -1]))
            out.append(t)
            pos += 1
        if out and out[-1] == tok.eos_token_id:
            out = out[:-1]
        memo[key] = out
        return out

    return run


class Fleet:
    def __init__(self, n_stages: int, restore_dir: str, *,
                 wire_quant=None, env_extra=None, **pipe_kw):
        self.restore_dir = restore_dir
        ports = [free_port() for _ in range(n_stages)]
        self.sup = StageSupervisor(
            MODEL, n_stages, ports, seed=0, block_size=BLOCK,
            restore_dir=restore_dir, wire_quant=wire_quant,
            restart_budget=100, env=_stage_env(env_extra),
        )
        self.pipe = MPMDPipeline(
            self.sup,
            transport=HttpStageTransport(wire_quant=wire_quant),
            **pipe_kw,
        )

    def start(self):
        self.pipe.start_fleet(ready_timeout_s=120)
        return self

    def stage_slots(self, s: int) -> dict:
        return self.pipe.transport.get_json(
            self.sup.addr(s), "/health"
        )["kv_slots"]

    def shutdown(self):
        self.pipe.shutdown()


@pytest.fixture(scope="module")
def fleet3(tmp_path_factory):
    f = Fleet(3, str(tmp_path_factory.mktemp("restore3"))).start()
    yield f
    f.shutdown()


# -- the kill -9 chaos matrix -------------------------------------------------
#
# The decode x warm diagonal (the acceptance headline: kill -9 any stage
# mid-decode, warm restore recomputes < block_size) runs in the fast
# tier; the other nine cells carry the `slow` marker like every other
# subprocess-heavy leg (pytest.ini) and run in CI's dedicated
# test_stage_pipeline.py step.

def _cells():
    out = []
    for victim in (0, 1, 2):
        for boundary in ("prefill", "decode"):
            for restore in ("warm", "cold"):
                fast = boundary == "decode" and restore == "warm"
                out.append(pytest.param(
                    victim, boundary, restore,
                    marks=() if fast else (pytest.mark.slow,),
                    id=f"victim{victim}-{boundary}-{restore}",
                ))
    return out


@pytest.mark.parametrize("victim,boundary,restore", _cells())
def test_chaos_matrix_bit_identical(fleet3, reference, victim, boundary,
                                    restore):
    """SIGKILL stage `victim` at `boundary` under `restore`; greedy
    output must be bit-identical to the fault-free run, the pool must
    drain back to free == total, and a warm restore must recompute
    fewer than block_size tokens."""
    pipe, sup = fleet3.pipe, fleet3.sup
    ref = reference(PROMPT, N_NEW)
    assert len(ref) == N_NEW  # the drill needs a full-length transcript

    rid = pipe.start(PROMPT)
    got = 1  # start() accepted the first token
    if boundary == "decode":
        for _ in range(KILL_AFTER):
            assert pipe.step_once(rid) is not None
            got += 1
    sup.proc(victim).kill()  # SIGKILL: no drain, no flush, no goodbye
    sup.proc(victim).wait(timeout=10)
    if restore == "cold":
        shutil.rmtree(
            os.path.join(fleet3.restore_dir, f"stage{victim}"),
            ignore_errors=True,
        )
    while got < N_NEW:
        tok = pipe.step_once(rid)
        if tok is None:
            break
        got += 1
    out = pipe.finish(rid)
    assert out["tokens"] == ref, (victim, boundary, restore)

    salvage = pipe.last_salvage()
    assert salvage["stage"] == victim
    recomputed = salvage["tokens_recomputed"][rid]
    fed_at_kill = len(ByteTokenizer().encode(PROMPT)) + (
        KILL_AFTER if boundary == "decode" else 0
    )
    if restore == "warm":
        assert 0 < recomputed < BLOCK, recomputed
    else:
        assert recomputed == fed_at_kill, recomputed

    for s in range(3):
        slots = fleet3.stage_slots(s)
        assert slots["free"] == slots["total"], (s, slots)


def test_transport_fault_points_retry_transparently(fleet3, reference):
    """Armed stage_send drops are absorbed by the controller's retry
    loop: output stays bit-identical and the rules actually fired."""
    plan = faults.arm("stage_send:transient:on=2,every=3,times=3")
    try:
        out = fleet3.pipe.generate(PROMPT, N_NEW)
        assert out["tokens"] == reference(PROMPT, N_NEW)
        assert plan.fired("stage_send") == 3
    finally:
        faults.disarm()


def test_trace_propagation_reaches_every_stage(fleet3):
    """traceparent flows controller -> every stage: the same trace id
    shows up in each stage's span store with stage.step spans."""
    fleet3.pipe.generate(PROMPT, 4)
    ids_per_stage = []
    for s in range(3):
        traces = fleet3.pipe.transport.get_json(
            fleet3.sup.addr(s), "/debug/traces"
        )
        spans = [sp for tid in traces for sp in traces[tid]]
        assert any(sp["name"] == "stage.step" for sp in spans)
        ids_per_stage.append(set(traces))
    shared = set.intersection(*ids_per_stage)
    assert shared, ids_per_stage


# -- heartbeat: wedge -> unready -> readmission ------------------------------

@pytest.mark.slow
def test_heartbeat_timeout_unready_then_readmitted(tmp_path):
    """A wedged stage (heartbeat handler stalls past the timeout, armed
    via DLI_FAULTS in the STAGE process) flips the pipeline unready;
    when the wedge clears, heartbeats resume and it is readmitted."""
    fleet = Fleet(
        2, str(tmp_path / "restore"),
        env_extra={
            "DLI_FAULTS":
                "stage_recv:transient:match=heartbeat:stage1,"
                "on=1,every=1,times=4,wedge=1.5",
        },
        hb_interval_s=0.15, hb_timeout_s=0.5,
    ).start()
    seen = {}

    def unready(pipe=fleet.pipe):
        if pipe.ready():
            return False
        seen["liveness"] = pipe.liveness()
        return True

    try:
        assert wait_until(unready, timeout_s=15)
        assert seen["liveness"].get(1) in ("wedged", "dead")
        kinds = [e["kind"] for e in fleet.pipe.flight.events()]
        assert "heartbeat_lost" in kinds
        # the rule exhausts after 4 firings: heartbeats succeed again
        assert wait_until(fleet.pipe.ready, timeout_s=30)
    finally:
        fleet.shutdown()


# -- rolling restart under live load -----------------------------------------

@pytest.mark.slow
def test_rolling_restart_zero_drops_under_live_load(tmp_path, reference):
    """Cycle every stage through drain -> respawn -> /ready while two
    driver threads generate continuously: zero failed requests, every
    transcript bit-identical to its fault-free reference."""
    fleet = Fleet(2, str(tmp_path / "restore")).start()
    prompts = ["rolling load A", "rolling load B"]
    results = {p: [] for p in prompts}
    errors = []
    stop = threading.Event()

    def driver(prompt):
        while not stop.is_set():
            try:
                out = fleet.pipe.generate(prompt, 8)
                results[prompt].append(out["tokens"])
            except Exception as e:  # any drop is a failure
                errors.append((prompt, repr(e)))
                return

    threads = [
        threading.Thread(target=driver, args=(p,), daemon=True)
        for p in prompts
    ]
    try:
        for t in threads:
            t.start()
        assert wait_until(
            lambda: all(results[p] for p in prompts), timeout_s=60
        )
        report = fleet.pipe.rolling_restart()
        assert [r["stage"] for r in report["stages"]] == [0, 1]
        assert wait_until(
            lambda: all(len(results[p]) >= 3 for p in prompts),
            timeout_s=60,
        )
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        fleet.shutdown()
    assert not errors, errors
    for p in prompts:
        ref = reference(p, 8)
        assert results[p], p
        for transcript in results[p]:
            assert transcript == ref, p
    kinds = [e["kind"] for e in fleet.pipe.flight.events()]
    assert kinds.count("rolling_stage_done") == 2


# -- int8 cross-process wire --------------------------------------------------

@pytest.mark.slow
def test_int8_wire_quant_applies_to_cross_process_hops(tmp_path):
    """pp_wire_quant="int8" on the stage transport: bodies ship int8 +
    scales, the pipeline still generates, and the bytes land on
    dli_pp_wire_bytes_total{path="stage"} at the quantized size."""
    fleet = Fleet(2, str(tmp_path / "restore"), wire_quant="int8").start()
    try:
        out = fleet.pipe.generate(PROMPT, 6)
        assert len(out["tokens"]) == 6
        fam = fleet.pipe.transport.registry.get("dli_pp_wire_bytes_total")
        quant_bytes = fam.labels(path="stage").value
        assert quant_bytes > 0
    finally:
        fleet.shutdown()

    # the same traffic unquantized is strictly fatter on the wire
    fleet = Fleet(2, str(tmp_path / "restore_fp")).start()
    try:
        fleet.pipe.generate(PROMPT, 6)
        fam = fleet.pipe.transport.registry.get("dli_pp_wire_bytes_total")
        raw_bytes = fam.labels(path="stage").value
        assert raw_bytes > quant_bytes
    finally:
        fleet.shutdown()


# -- frontend over HTTP -------------------------------------------------------

@pytest.mark.slow
def test_frontend_http_surface(tmp_path, reference):
    """The --frontend CLI: spawns its stage fleet, serves /generate,
    /ready, /health, /debug/flight and /admin/rolling-restart, and
    reaps the stages on SIGTERM."""
    import subprocess
    import sys

    port = free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "distributed_llm_inference_tpu.serving.stage_runtime",
         "--frontend", "--stages", "2", "--model", MODEL,
         "--port", str(port), "--block-size", str(BLOCK),
         "--restore-dir", str(tmp_path / "restore")],
        env=_stage_env(),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    base = f"http://127.0.0.1:{port}"

    def ready():
        try:
            with urllib.request.urlopen(f"{base}/ready", timeout=2) as r:
                return r.status == 200
        except Exception:
            return False

    try:
        assert wait_until(ready, timeout_s=120, interval_s=0.25)
        body = json.dumps(
            {"prompt": PROMPT, "max_new_tokens": 8}
        ).encode()
        req = urllib.request.Request(
            f"{base}/generate", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as r:
            out = json.loads(r.read())
        assert out["tokens"] == reference(PROMPT, 8)
        with urllib.request.urlopen(f"{base}/health", timeout=10) as r:
            health = json.loads(r.read())
        assert health["ready"] and health["n_stages"] == 2
        rr = urllib.request.Request(
            f"{base}/admin/rolling-restart", data=b"{}",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(rr, timeout=120) as r:
            report = json.loads(r.read())
        assert [x["stage"] for x in report["stages"]] == [0, 1]
        with urllib.request.urlopen(f"{base}/debug/flight", timeout=10) as r:
            flight = json.loads(r.read())
        kinds = [e["kind"] for e in flight["events"]]
        assert "rolling_restart_done" in kinds
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)


# -- pure glue: stage planning + 1F1B order ----------------------------------

def test_plan_stages_contiguous_cover():
    assert plan_stages(4, 2) == [(0, 2), (2, 4)]
    assert plan_stages(5, 2) == [(0, 3), (3, 5)]
    assert plan_stages(7, 3) == [(0, 3), (3, 5), (5, 7)]
    ranges = plan_stages(32, 8)
    assert ranges[0][0] == 0 and ranges[-1][1] == 32
    assert all(a[1] == b[0] for a, b in zip(ranges, ranges[1:]))
    with pytest.raises(ValueError):
        plan_stages(2, 3)


def test_mpmd_1f1b_order_properties():
    S, Mb = 3, 5
    events = mpmd_1f1b_order(S, Mb)
    assert len(events) == S * Mb
    # per-stage order is FIFO in microbatch id (queue drain == schedule)
    for s in range(S):
        mbs = [m for _, ss, m in events if ss == s]
        assert mbs == sorted(mbs)
    # stage s+1 sees microbatch m strictly after stage s
    tick = {(s, m): t for t, s, m in events}
    for m in range(Mb):
        for s in range(S - 1):
            assert tick[(s + 1, m)] > tick[(s, m)]
    # fill-drain trapezoid makespan
    assert max(t for t, _, _ in events) == Mb + S - 2
    with pytest.raises(ValueError):
        mpmd_1f1b_order(0, 1)


# -- fault-point grammar ------------------------------------------------------

def test_stage_fault_points_in_grammar():
    assert "stage_send" in faults.POINTS
    assert "stage_recv" in faults.POINTS
    rules = faults.parse_spec(
        "stage_send:transient:on=3,every=2;"
        "stage_recv:fatal:match=heartbeat:stage1,wedge=0.5"
    )
    assert rules[0].point == "stage_send" and rules[0].on_call == 3
    assert rules[1].point == "stage_recv" and rules[1].wedge_s == 0.5
    with pytest.raises(ValueError):
        faults.FaultRule(point="stage_bogus")


# -- derived callgraph + comms contract --------------------------------------

@pytest.fixture(scope="module")
def pkg_index():
    return build_index(PKG_ROOT)


def test_stage_runtime_pinned_decode_unreachable(pkg_index):
    """Every host loop in serving.stage_runtime is decode-UNREACHABLE
    by the DERIVED callgraph (no manual pin list): the stage/frontend
    servers, the transport, the supervisor and the controller can never
    leak into a traced program."""
    derived = decode_unreachable(pkg_index, traced_reachable(pkg_index))
    funcs = [
        f.key
        for f in pkg_index.modules["serving.stage_runtime"].functions.values()
    ]
    assert funcs, "serving.stage_runtime not indexed"
    missing = [k for k in funcs if k not in derived]
    assert not missing, missing


def test_stage_wire_links_registered_and_accounted(pkg_index):
    from distributed_llm_inference_tpu.analysis import comms

    for name in ("stage-activation-dcn", "stage-result-dcn"):
        spec = comms.WIRE_LINKS[name]
        assert spec.axis == "dcn" and spec.path == "stage"
    report = comms.build_report(pkg_index)
    assert not report["problems"], report["problems"]
    by_name = {l["name"]: l for l in report["links"]}
    for name in ("stage-activation-dcn", "stage-result-dcn"):
        assert by_name[name]["accounted_at"], name
    # the int8 wire formula applies to the cross-process hop too
    act = by_name["stage-activation-dcn"]
    assert act["reference_bytes_quant"] < act["reference_bytes_raw"]
