"""Graceful degradation under memory pressure: SLO-aware KV preemption
with swap-to-host, end-to-end deadlines, and real cancellation.

The bar (ISSUE 10 acceptance):
  * preempt→resume greedy output is bit-identical to an uncontended run,
    on BOTH policies — "swap" (victim's filled blocks pushed to the host
    shadow, restored in one scatter on resume, tail-only re-prefill) and
    "recompute" (drop-and-recompute from the salvage record);
  * victim selection is SLO policy: lowest weight first, youngest within
    a weight tie, and a victim never outranks the beneficiary;
  * preemption STORM: a pool sized so N concurrent requests force
    repeated preemption still completes every request, bit-identically,
    with `free == total` (minus cached chains) after the fleet drains;
  * chaos: a crash landing at every fault point — the new `preempt`
    point included — during a preempt/resume cycle is contained by the
    supervisor and the output stays bit-identical;
  * cancellation frees resources promptly: a vanished streaming client
    (broken pipe) or an expired `deadline_ms` releases blocks + slot at
    the next launch boundary, long before the token budget drains;
  * HTTP surface: deadline_ms on /generate and the OpenAI routes (504
    `deadline_exceeded`, 499 `cancelled`), fail-fast for already-expired
    requests (ZERO pool allocations), X-Request-Deadline-Ms relay, and
    the router NEVER re-dispatching a 504.

Deterministic where possible (counter-triggered faults); the contention
legs poll real scheduler state with bounded timeouts (marker `chaos`).
"""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from distributed_llm_inference_tpu import EngineConfig, get_model_config
from distributed_llm_inference_tpu.engine.continuous import ContinuousEngine
from distributed_llm_inference_tpu.engine.engine import InferenceEngine
from distributed_llm_inference_tpu.engine.scheduler import (
    SLOClass, TokenBudgetScheduler,
)
from distributed_llm_inference_tpu.serving.server import InferenceServer
from distributed_llm_inference_tpu.utils import faults

pytestmark = pytest.mark.chaos

BS = 8  # kv_block_size for every fleet here
PROMPT_A = "the quick brown fox jumps over the"
PROMPT_B = "pack my box with five dozen liquor"
KW = dict(max_tokens=10, greedy=True, chat=False)
# the contention victim decodes LONG (and holds 7 of the 8 usable
# blocks), so the second admission always finds it mid-decode
KW_LONG = dict(max_tokens=24, greedy=True, chat=False)


@pytest.fixture(autouse=True)
def _always_disarm():
    faults.disarm()
    yield
    faults.disarm()


@pytest.fixture(scope="module")
def engine():
    cfg = get_model_config("test-llama-tiny")
    return InferenceEngine(
        cfg,
        engine_cfg=EngineConfig(
            prefill_buckets=(32, 64), prefix_cache_entries=8
        ),
    )


@pytest.fixture(scope="module")
def solo_a(engine):
    return engine.generate(PROMPT_A, **KW_LONG)


@pytest.fixture(scope="module")
def solo_b(engine):
    return engine.generate(PROMPT_B, **KW)


def _cont(engine, pool=16, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("chunk_steps", 2)
    kw.setdefault("restart_backoff_s", 0.01)
    kw.setdefault("slot_max_seq", 64)  # 8 blocks of BS
    return ContinuousEngine(
        engine, kv_pool_blocks=pool, kv_block_size=BS, **kw
    )


def _ctr(engine, name):
    snap = engine.metrics.snapshot()
    return sum(
        s.get("value", s.get("count", 0))
        for s in snap.get(name, {}).get("series", [])
    )


def _pool_clean(cont):
    """free + index-cached == everything (the trash block excluded)."""
    st = cont.stats()["paged"]
    return st["free_blocks"] + st["cached_blocks"] == st["pool_blocks"] - 1


def _wait(pred, timeout=20.0, what="condition"):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if pred():
            return
        time.sleep(0.005)
    raise AssertionError(f"timed out waiting for {what}")


def _contended_pair(engine, cont):
    """Serve A (long decode) and B (admitted mid-A against a pool that
    cannot hold both) concurrently; returns (result_a, result_b)."""
    out = {}

    def run(tag, prompt, kw):
        out[tag] = cont.submit(prompt, **kw)

    ta = threading.Thread(target=run, args=("a", PROMPT_A, KW_LONG))
    ta.start()
    # B joins only once A is decoding (occupying its blocks)
    _wait(lambda: cont.stats()["occupied"] >= 1, what="A admitted")
    tb = threading.Thread(target=run, args=("b", PROMPT_B, KW))
    tb.start()
    ta.join(timeout=60)
    tb.join(timeout=60)
    assert not ta.is_alive() and not tb.is_alive(), "requests hung"
    return out["a"], out["b"]


# -- preempt -> resume bit-exactness -----------------------------------------

# pool: 9 usable blocks. A (35 ids + 24 tokens) needs ceil(59 / 8) = 8;
# B (35 ids + 10) needs ceil(45 / 8) = 6 > 1 free, and A's mapped chains
# are pinned while it decodes — B can only be placed by preempting A.
TIGHT_POOL = 10


@pytest.mark.parametrize("policy", ["swap", "recompute"])
def test_preempt_resume_bit_exact(policy, solo_a, solo_b):
    cfg = get_model_config("test-llama-tiny")
    eng = InferenceEngine(
        cfg,
        engine_cfg=EngineConfig(
            prefill_buckets=(32, 64), prefix_cache_entries=8,
            preempt_policy=policy,
        ),
    )
    cont = _cont(eng, pool=TIGHT_POOL, kv_shadow=(policy == "swap"))
    try:
        restored0 = _ctr(eng, "dli_shadow_restored_blocks_total")
        ra, rb = _contended_pair(eng, cont)
        assert ra["status"] == "success", ra
        assert rb["status"] == "success", rb
        # the acceptance bar: preempted-and-resumed output is
        # bit-identical to the never-preempted (solo) run
        assert ra["response"] == solo_a["response"]
        assert rb["response"] == solo_b["response"]
        assert cont.preempted_total >= 1
        assert _ctr(eng, "dli_preempted_resume_seconds") >= 1  # _count
        if policy == "swap":
            # the victim's chain came back through the shadow scatter
            assert (
                _ctr(eng, "dli_shadow_restored_blocks_total") > restored0
            )
        assert _pool_clean(cont)
    finally:
        cont.close()


def test_preempted_request_reports_recovered(solo_a):
    """A preempted request's envelope carries recovered: true (it was
    served through the salvage-continuation machinery)."""
    cfg = get_model_config("test-llama-tiny")
    eng = InferenceEngine(
        cfg,
        engine_cfg=EngineConfig(
            prefill_buckets=(32, 64), prefix_cache_entries=8,
        ),
    )
    cont = _cont(eng, pool=TIGHT_POOL)
    try:
        ra, _ = _contended_pair(eng, cont)
        assert ra["status"] == "success"
        assert ra["response"] == solo_a["response"]
        # A was the victim (B never preempts anyone else); its envelope
        # records the eviction count
        assert ra.get("preempted", 0) >= 1
        assert cont.stats()["preemption"]["preempted_total"] >= 1
    finally:
        cont.close()


def test_preempt_policy_off_waits(solo_a, solo_b):
    """preempt_policy='off' restores the old behavior: B waits for A's
    release instead of evicting it — both still complete, zero
    preemptions."""
    cfg = get_model_config("test-llama-tiny")
    eng = InferenceEngine(
        cfg,
        engine_cfg=EngineConfig(
            prefill_buckets=(32, 64), prefix_cache_entries=8,
            preempt_policy="off",
        ),
    )
    cont = _cont(eng, pool=TIGHT_POOL)
    try:
        ra, rb = _contended_pair(eng, cont)
        assert ra["response"] == solo_a["response"]
        assert rb["response"] == solo_b["response"]
        assert cont.preempted_total == 0
        assert _pool_clean(cont)
    finally:
        cont.close()


def test_bad_preempt_policy_rejected():
    cfg = get_model_config("test-llama-tiny")
    eng = InferenceEngine(
        cfg, engine_cfg=EngineConfig(preempt_policy="sometimes"),
    )
    with pytest.raises(ValueError, match="preempt_policy"):
        _cont(eng, pool=TIGHT_POOL)


# -- victim-selection policy units -------------------------------------------

INTERACTIVE = SLOClass("interactive", 0.5, 0.1, 4.0, True)
STANDARD = SLOClass("standard", 2.0, 0.5, 2.0, True)
BATCH = SLOClass("batch", 30.0, 2.0, 1.0, False)


def _sched():
    classes = {
        c.name: c for c in (INTERACTIVE, STANDARD, BATCH)
    }
    return TokenBudgetScheduler(classes, "standard", 128, 8, 2)


def test_victim_lowest_weight_first():
    s = _sched()
    v = s.select_victim(
        [("i", INTERACTIVE, 1.0), ("b", BATCH, 2.0), ("s", STANDARD, 3.0)],
        INTERACTIVE,
    )
    assert v == "b"


def test_victim_youngest_within_weight_tie():
    s = _sched()
    v = s.select_victim(
        [("old", STANDARD, 1.0), ("young", STANDARD, 9.0)], STANDARD,
    )
    assert v == "young"


def test_victim_never_outranks_beneficiary():
    s = _sched()
    # a batch admission may not preempt interactive/standard decodes
    assert s.select_victim(
        [("i", INTERACTIVE, 1.0), ("s", STANDARD, 2.0)], BATCH,
    ) is None
    # equal weight IS eligible (FIFO fairness: youngest yields)
    assert s.select_victim([("b2", BATCH, 5.0)], BATCH) == "b2"


def test_victim_cap_respected(solo_a):
    """A request preempted max_preemptions_per_req times becomes immune:
    the pool then backpressures instead of thrashing it forever."""
    cfg = get_model_config("test-llama-tiny")
    eng = InferenceEngine(
        cfg,
        engine_cfg=EngineConfig(
            prefill_buckets=(32, 64), prefix_cache_entries=8,
            max_preemptions_per_req=0,  # everyone immune from the start
        ),
    )
    cont = _cont(eng, pool=TIGHT_POOL)
    try:
        ra, rb = _contended_pair(eng, cont)
        assert ra["status"] == "success" and rb["status"] == "success"
        assert ra["response"] == solo_a["response"]
        assert cont.preempted_total == 0  # cap 0 == policy off in effect
    finally:
        cont.close()


# -- preemption storm ---------------------------------------------------------

def test_preemption_storm_all_complete(engine):
    """N concurrent requests against a pool that can hold ~one of them:
    repeated preemption, every request completes bit-identically, and
    the pool books balance after the fleet drains."""
    solos = {}
    prompts = [
        PROMPT_A, PROMPT_B,
        "sphinx of black quartz judge my vow today",
        "how vexingly quick daft zebras jump now",
    ]
    for p in prompts:
        solos[p] = engine.generate(p, **KW)
    cont = _cont(engine, pool=TIGHT_POOL, n_slots=4)
    try:
        out = {}

        def run(p):
            out[p] = cont.submit(p, **KW)

        threads = [
            threading.Thread(target=run, args=(p,)) for p in prompts
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "storm request hung"
        for p in prompts:
            assert out[p]["status"] == "success", out[p]
            assert out[p]["response"] == solos[p]["response"], p
        assert _pool_clean(cont)
    finally:
        cont.close()


# -- chaos: crash landing during a preempt/resume cycle -----------------------

_CYCLE_RULES = {
    "preempt": dict(on_call=1),
    "admission": dict(on_call=3),
    "alloc": dict(on_call=3),
    "prefill": dict(on_call=2),
    "decode_launch": dict(on_call=6),
    "fetch": dict(on_call=4),
}


@pytest.mark.parametrize("point", sorted(_CYCLE_RULES))
def test_crash_during_preempt_cycle(point, solo_a, solo_b):
    """A transient crash landing anywhere in a contended preempt/resume
    cycle — the preempt hook itself included — is contained by the
    supervisor, and BOTH requests still finish bit-identically."""
    cfg = get_model_config("test-llama-tiny")
    eng = InferenceEngine(
        cfg,
        engine_cfg=EngineConfig(
            prefill_buckets=(32, 64), prefix_cache_entries=8,
        ),
    )
    cont = _cont(eng, pool=TIGHT_POOL)
    try:
        faults.arm([
            faults.FaultRule(point, "transient", **_CYCLE_RULES[point])
        ])
        ra, rb = _contended_pair(eng, cont)
        faults.disarm()
        assert ra["status"] == "success", (point, ra)
        assert rb["status"] == "success", (point, rb)
        assert ra["response"] == solo_a["response"], point
        assert rb["response"] == solo_b["response"], point
        assert _pool_clean(cont)
        assert cont.stats()["supervisor"]["ready"] is True
    finally:
        faults.disarm()
        cont.close()


# -- cancellation frees resources promptly ------------------------------------

def test_stream_close_cancels_and_frees(engine):
    """Abandoning a stream (the serving edge's broken-pipe path calls
    generator.close()) flips the cancel flag; the worker kills the slot
    and frees the blocks within one scheduler step — NOT after the full
    max_new_tokens budget."""
    cont = _cont(engine, pool=16)
    try:
        cancelled0 = _ctr(engine, "dli_cancelled_total")
        gen = cont.stream(PROMPT_A, max_tokens=2000, greedy=True,
                          chat=False)
        first = next(gen)  # at least one delta: the request is decoding
        assert "delta" in first
        gen.close()
        _wait(
            lambda: cont.stats()["occupied"] == 0 and _pool_clean(cont),
            what="slot+blocks freed after stream close",
        )
        # well under the 2000-token budget: the fleet is idle already
        assert cont.stats()["occupied"] == 0
        assert _ctr(engine, "dli_cancelled_total") > cancelled0
    finally:
        cont.close()


def test_http_sse_disconnect_cancels(engine):
    """A vanished SSE client (socket closed mid-stream) routes into the
    cancellation path: the engine stops decoding and frees the slot long
    before the budget drains (the PR's streaming-disconnect bugfix)."""
    cont = _cont(engine, pool=16)
    server = InferenceServer(
        engine, host="127.0.0.1", port=0, max_tokens_cap=4096,
        continuous=cont,
    )
    server.start()
    try:
        body = json.dumps({
            "model": "m",
            "messages": [{"role": "user", "content": PROMPT_A}],
            "stream": True, "max_tokens": 2000, "temperature": 0.0,
        })
        s = socket.create_connection(("127.0.0.1", server.port), timeout=10)
        s.sendall(
            (
                f"POST /v1/chat/completions HTTP/1.1\r\n"
                f"Host: 127.0.0.1\r\nContent-Type: application/json\r\n"
                f"Content-Length: {len(body)}\r\n\r\n{body}"
            ).encode()
        )
        s.recv(1024)  # headers + the first SSE bytes: decode is live
        s.close()  # vanish mid-stream
        _wait(
            lambda: cont.stats()["occupied"] == 0 and _pool_clean(cont),
            what="engine freed after SSE disconnect",
        )
    finally:
        server.shutdown()


# -- end-to-end deadlines ------------------------------------------------------

def test_expired_deadline_fails_fast_zero_allocations(engine):
    """An already-expired deadline_ms is refused BEFORE admission: no
    prefill launch, zero pool blocks touched."""
    cont = _cont(engine, pool=16)
    try:
        free0 = cont.stats()["paged"]["free_blocks"]
        exceeded0 = _ctr(engine, "dli_deadline_exceeded_total")
        r = cont.submit(PROMPT_B + " xyz", deadline_ms=0.01, **KW)
        assert r["status"] == "failed"
        assert r["error_type"] == "deadline_exceeded"
        assert cont.stats()["paged"]["free_blocks"] == free0
        assert _ctr(engine, "dli_deadline_exceeded_total") > exceeded0
    finally:
        cont.close()


def test_mid_decode_deadline_frees_blocks(engine):
    """A deadline expiring mid-decode kills the slot at the next launch
    boundary and releases blocks + slot immediately — the envelope is
    the distinct deadline_exceeded, not the legacy timeout. The deadline
    is sized off a measured warm request so it reliably lands INSIDE the
    decode window on any host speed."""
    cont = _cont(engine, pool=16, slot_max_seq=120, chunk_steps=1)
    kw = dict(max_tokens=4000, greedy=True, chat=False)
    try:
        # dry run (also pays every compile): the exact request's warm
        # TTFT and total wall clock bound the decode window
        cont.submit(PROMPT_A, **kw)
        t0 = time.time()
        dry = cont.submit(PROMPT_A, **kw)
        dry_s = time.time() - t0
        assert dry["status"] == "success"
        ttft = float(dry["ttft_s"])
        # aim the deadline inside the decode window; per-run jitter can
        # still let a fast run finish first, so try a few fractions —
        # ONE mid-decode expiry proves the property
        hit = None
        for frac in (0.5, 0.3, 0.7, 0.2, 0.85):
            deadline_s = ttft + frac * max(0.01, dry_s - ttft)
            t0 = time.time()
            r = cont.submit(PROMPT_A, deadline_ms=deadline_s * 1e3, **kw)
            elapsed = time.time() - t0
            if r["status"] == "failed":
                hit = (r, elapsed)
                break
        assert hit is not None, "deadline never landed mid-decode"
        r, elapsed = hit
        assert r["error_type"] == "deadline_exceeded"
        # the request died at its deadline, not at budget exhaustion
        assert elapsed < 30
        _wait(
            lambda: cont.stats()["occupied"] == 0 and _pool_clean(cont),
            what="blocks freed after deadline",
        )
    finally:
        cont.close()


def test_solo_engine_deadline_ms(engine):
    r = engine.generate(PROMPT_A, deadline_ms=0.01, **KW)
    assert r["status"] == "failed"
    assert r["error_type"] == "deadline_exceeded"


def test_queue_expired_deadline_fails_fast(engine):
    from distributed_llm_inference_tpu.serving.queue import BatchingQueue

    q = BatchingQueue(engine, max_queue=4)
    try:
        r = q.submit(PROMPT_A, deadline_ms=0.01, **KW)
        assert r["status"] == "failed"
        assert r["error_type"] == "deadline_exceeded"
    finally:
        q.close()


# -- HTTP surface -------------------------------------------------------------

def _post(base, path, payload, headers=None):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture(scope="module")
def http_server(engine):
    cont = _cont(engine, pool=16)
    server = InferenceServer(
        engine, host="127.0.0.1", port=0, max_tokens_cap=64,
        continuous=cont,
    )
    server.start()
    yield f"http://127.0.0.1:{server.port}"
    server.shutdown()


def test_http_generate_deadline_504(http_server):
    code, body = _post(
        http_server, "/generate",
        {"prompt": PROMPT_A, "max_tokens": 5, "deadline_ms": 0.01},
    )
    assert code == 504
    assert body["error_type"] == "deadline_exceeded"


def test_http_generate_bad_deadline_400(http_server):
    code, body = _post(
        http_server, "/generate",
        {"prompt": PROMPT_A, "deadline_ms": -5},
    )
    assert code == 400


def test_http_openai_deadline_504(http_server):
    for path, payload in (
        ("/v1/completions", {"model": "m", "prompt": PROMPT_A,
                             "deadline_ms": 0.01}),
        ("/v1/chat/completions", {
            "model": "m",
            "messages": [{"role": "user", "content": PROMPT_A}],
            "deadline_ms": 0.01,
        }),
    ):
        code, body = _post(http_server, path, payload)
        assert code == 504, (path, body)
        assert body["error"]["type"] == "timeout_error"


def test_http_deadline_header_overrides_body(http_server):
    """X-Request-Deadline-Ms (the router's remaining-budget relay) wins
    over the body field: a generous body deadline with a spent header
    budget still 504s."""
    code, body = _post(
        http_server, "/generate",
        {"prompt": PROMPT_A, "max_tokens": 5, "deadline_ms": 60000},
        headers={"X-Request-Deadline-Ms": "0.01"},
    )
    assert code == 504
    assert body["error_type"] == "deadline_exceeded"


def test_http_deadline_success_when_budget_fits(http_server):
    code, body = _post(
        http_server, "/generate",
        {"prompt": PROMPT_A, "max_tokens": 3, "greedy": True,
         "chat": False, "deadline_ms": 120000},
    )
    assert code == 200, body
    assert body["status"] == "success"


# -- router discipline ---------------------------------------------------------

class _StubReplica:
    """Minimal replica: /ready 200; POST answers a fixed (status, body);
    records hits + headers."""

    def __init__(self, status, body):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        stub = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                payload = json.dumps({"ready": True}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                stub.hits += 1
                stub.headers.append(dict(self.headers))
                payload = json.dumps(stub.body).encode()
                self.send_response(stub.status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self.hits = 0
        self.headers: list = []
        self.status = status
        self.body = body
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        ).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _router(urls):
    from distributed_llm_inference_tpu.serving.router import Replica, Router

    return Router(
        [Replica(f"r{i}", u) for i, u in enumerate(urls)],
        probe_interval_s=3600.0,
    )


def test_router_never_retries_deadline_exceeded():
    """A 504 deadline_exceeded comes straight back: ONE dispatch, no
    failover to the second replica, no breaker strike."""
    dead_env = {
        "error": "Error: request exceeded its deadline_ms budget",
        "status": "failed", "error_type": "deadline_exceeded",
    }
    a = _StubReplica(504, dead_env)
    b = _StubReplica(504, dead_env)
    router = _router([a.url, b.url])
    try:
        body = json.dumps({"prompt": "x", "deadline_ms": 5000}).encode()
        rep, status, rbody, _h, attempts = router.dispatch(
            "/generate", body, "x", "rid-1", deadline_ms=5000.0,
        )
        assert status == 504
        assert json.loads(rbody)["error_type"] == "deadline_exceeded"
        assert attempts == 1
        assert a.hits + b.hits == 1  # exactly one replica was asked
        assert rep is not None and rep.consecutive_failures == 0
    finally:
        router.close()
        a.close()
        b.close()


def test_router_relays_remaining_deadline_header():
    ok = _StubReplica(200, {"status": "success", "response": "hi"})
    router = _router([ok.url])
    try:
        body = json.dumps({"prompt": "x"}).encode()
        _rep, status, _b, _h, _n = router.dispatch(
            "/generate", body, "x", "rid-2", deadline_ms=5000.0,
        )
        assert status == 200
        hdr = ok.headers[0].get("X-Request-Deadline-Ms")
        assert hdr is not None
        assert 0 < float(hdr) <= 5000.0
    finally:
        router.close()
        ok.close()


def test_router_spent_budget_answers_504_without_dispatch():
    ok = _StubReplica(200, {"status": "success"})
    router = _router([ok.url])
    try:
        body = json.dumps({"prompt": "x"}).encode()
        _rep, status, rbody, _h, _n = router.dispatch(
            "/generate", body, "x", "rid-3", deadline_ms=0.0001,
        )
        assert status == 504
        assert json.loads(rbody)["error_type"] == "deadline_exceeded"
        assert ok.hits == 0  # the budget died at the router
    finally:
        router.close()
        ok.close()
