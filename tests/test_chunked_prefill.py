"""Chunked prefill: prompts longer than the largest compiled bucket.

Correctness bar: a prompt processed as extend-chunks + final sampling
chunk must generate exactly the same greedy tokens as the same prompt
through a single big-bucket prefill — on the single-device backend AND
on a pp=2 SPMD pipeline mesh (round-1 verdict: SPMD backends must serve
the same request surface as single-chip).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llm_inference_tpu import EngineConfig, MeshConfig, create_engine
from distributed_llm_inference_tpu.engine import generate as G
from distributed_llm_inference_tpu.models import api as M
from distributed_llm_inference_tpu.models.registry import get_model_config


def test_chunked_equals_single_prefill():
    cfg = get_model_config("test-llama-tiny", max_seq_len=128)
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(7)
    plen, steps = 40, 6
    ids = [int(t) for t in rng.integers(3, cfg.vocab_size, size=plen)]
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(9))

    # reference: one 64-bucket prefill
    tokens = jnp.asarray([ids + [cfg.pad_token_id] * (64 - plen)], jnp.int32)
    cache = M.init_kv_cache(cfg, 1, max_seq=128)
    first_r, _, cache = G.prefill(
        cfg, params, tokens, jnp.int32(plen), cache, kp, sampling
    )
    out_r, n_r, _ = G.decode(
        cfg, params, first_r, cache, jnp.int32(plen), jnp.int32(steps),
        kd, sampling, max_steps=steps,
    )

    # chunked: two 16-token extends + final 8-token chunk in a 16 bucket
    cache = M.init_kv_cache(cfg, 1, max_seq=128)
    for c in range(2):
        cache = G.extend(
            cfg, params, jnp.asarray([ids[c * 16 : (c + 1) * 16]], jnp.int32),
            jnp.int32(c * 16), cache,
        )
    tail = ids[32:]
    tokens = jnp.asarray([tail + [cfg.pad_token_id] * (16 - len(tail))], jnp.int32)
    first_c, _, cache = G.prefill(
        cfg, params, tokens, jnp.int32(len(tail)), cache, kp, sampling,
        None, jnp.int32(32),
    )
    out_c, n_c, _ = G.decode(
        cfg, params, first_c, cache, jnp.int32(plen), jnp.int32(steps),
        kd, sampling, max_steps=steps,
    )

    assert int(first_c[0]) == int(first_r[0])
    assert np.asarray(out_c).tolist() == np.asarray(out_r).tolist()
    assert np.asarray(n_c).tolist() == np.asarray(n_r).tolist()


@pytest.mark.parametrize(
    "mesh_cfg",
    [MeshConfig(), MeshConfig(dp=1, pp=2, tp=1)],
    ids=["single-device", "pp2"],
)
@pytest.mark.slow
def test_engine_chunked_prefill_end_to_end(mesh_cfg, eight_devices):
    """Engine accepts a prompt longer than every bucket and generates —
    identically on a single device and a pp=2 pipeline mesh."""
    engine = create_engine(
        get_model_config("test-llama-tiny", max_seq_len=256),
        mesh_cfg=mesh_cfg,
        engine_cfg=EngineConfig(prefill_buckets=(32, 64), max_seq_len=256),
    )
    # ~151 tokens under the byte-fallback tokenizer: past the 64 bucket,
    # inside max_seq_len-2 capacity
    long_prompt = "word " * 30
    r = engine.generate(long_prompt, max_tokens=5, greedy=True, chat=False, seed=1)
    assert r["status"] == "success", r
    assert r["tokens_generated"] >= 1

    # equivalence with a big-bucket single-device engine on the same prompt
    ref_engine = create_engine(
        get_model_config("test-llama-tiny", max_seq_len=256),
        engine_cfg=EngineConfig(prefill_buckets=(256,), max_seq_len=256),
    )
    ref = ref_engine.generate(
        long_prompt, max_tokens=5, greedy=True, chat=False, seed=1
    )
    # byte-fallback tokenizer: prompt must actually exceed the chunk bucket
    assert ref["status"] == "success", ref
    assert r["response"] == ref["response"]


@pytest.mark.slow
def test_pipeline_extend_matches_single_device(eight_devices):
    """Backend-level: pp=2 extend + prefill_at chunks == one big single-
    device prefill, bit-exact greedy tokens."""
    from distributed_llm_inference_tpu.parallel.mesh import build_mesh
    from distributed_llm_inference_tpu.parallel.pipeline import PipelineBackend

    cfg = get_model_config("test-llama-tiny", max_seq_len=128)
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(11)
    plen, steps = 40, 6
    ids = [int(t) for t in rng.integers(3, cfg.vocab_size, size=plen)]
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(13))

    # single-device reference: one 64-bucket prefill
    tokens64 = jnp.asarray([ids + [cfg.pad_token_id] * (64 - plen)], jnp.int32)
    cache = M.init_kv_cache(cfg, 1, max_seq=128)
    first_r, _, cache = G.prefill(
        cfg, params, tokens64, jnp.int32(plen), cache, kp, sampling
    )
    out_r, n_r, _ = G.decode(
        cfg, params, first_r, cache, jnp.int32(plen), jnp.int32(steps),
        kd, sampling, max_steps=steps,
    )

    # pp=2 pipeline: two 16-token extends + final 8-in-16 prefill_at chunk
    mesh = build_mesh(MeshConfig(dp=1, pp=2, tp=1), eight_devices)
    pb = PipelineBackend(cfg, params, mesh)
    cache = pb.init_cache(1, 128)
    for c in range(2):
        cache = pb.extend(
            jnp.asarray([ids[c * 16 : (c + 1) * 16]], jnp.int32),
            jnp.int32(c * 16), cache,
        )
    tail = ids[32:]
    tokens16 = jnp.asarray([tail + [cfg.pad_token_id] * (16 - len(tail))], jnp.int32)
    first_c, _, cache = pb.prefill_at(
        tokens16, jnp.int32(32), jnp.int32(len(tail)), cache, kp, sampling
    )
    out_c, n_c, _ = pb.decode(
        first_c, cache, jnp.int32(plen), jnp.int32(steps), kd, sampling,
        max_steps=steps,
    )

    assert int(first_c[0]) == int(first_r[0])
    assert np.asarray(out_c).tolist() == np.asarray(out_r).tolist()
    assert np.asarray(n_c).tolist() == np.asarray(n_r).tolist()


def test_engine_still_rejects_over_capacity():
    """Chunking extends to max_seq_len, not beyond."""
    engine = create_engine(
        get_model_config("test-llama-tiny", max_seq_len=64),
        engine_cfg=EngineConfig(prefill_buckets=(32,), max_seq_len=64),
    )
    r = engine.generate("x " * 200, max_tokens=5, greedy=True, chat=False)
    assert r["status"] == "failed" and r["error_type"] == "invalid_request"


@pytest.mark.slow
def test_chunked_final_bucket_never_overhangs_cache():
    """max_seq not a multiple of the chunk: the final padded bucket must not
    write past max_seq (update_kv_cache would silently clamp and corrupt
    prompt K/V — code-review regression). Here max_seq=96, buckets (64,):
    prompt 90 would need a 64-bucket at pos 64 -> end 128 > 96: reject."""
    engine = create_engine(
        get_model_config("test-llama-tiny", max_seq_len=96),
        engine_cfg=EngineConfig(prefill_buckets=(64,), max_seq_len=96),
    )
    ids_len_90_prompt = "w " * 45  # 90 bytes -> ~91 tokens (byte fallback)
    r = engine.generate(
        ids_len_90_prompt, max_tokens=3, greedy=True, chat=False
    )
    assert r["status"] == "failed" and r["error_type"] == "invalid_request"
    assert "cannot be chunk-prefilled" in r["error"]

    # with a 32 bucket available the same prompt fits (64+32 <= 96): succeeds
    engine2 = create_engine(
        get_model_config("test-llama-tiny", max_seq_len=96),
        engine_cfg=EngineConfig(prefill_buckets=(32, 64), max_seq_len=96),
    )
    r2 = engine2.generate(
        ids_len_90_prompt, max_tokens=3, greedy=True, chat=False
    )
    assert r2["status"] == "success", r2
