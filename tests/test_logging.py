"""Structured JSON-lines logging."""

import io
import json
import logging

from distributed_llm_inference_tpu.utils import logging as slog


def test_json_records_with_fields():
    buf = io.StringIO()
    # fresh handler onto our buffer regardless of prior configure() calls
    root = logging.getLogger("distributed_llm_inference_tpu")
    handler = logging.StreamHandler(buf)
    handler.setFormatter(slog._JsonFormatter())
    root.addHandler(handler)
    root.setLevel(logging.INFO)
    try:
        log = slog.get_logger("unit")
        log.info("request", model="m", tokens=3, tps=1.5)
        log.warning("slow", elapsed_s=9.9)
        lines = [json.loads(l) for l in buf.getvalue().strip().splitlines()]
    finally:
        root.removeHandler(handler)
    assert lines[0]["event"] == "request"
    assert lines[0]["model"] == "m" and lines[0]["tokens"] == 3
    assert lines[0]["logger"] == "distributed_llm_inference_tpu.unit"
    assert lines[1]["level"] == "warning" and lines[1]["elapsed_s"] == 9.9


def test_exception_captured():
    buf = io.StringIO()
    root = logging.getLogger("distributed_llm_inference_tpu")
    handler = logging.StreamHandler(buf)
    handler.setFormatter(slog._JsonFormatter())
    root.addHandler(handler)
    root.setLevel(logging.INFO)
    try:
        log = slog.get_logger("unit2")
        try:
            raise RuntimeError("boom")
        except RuntimeError:
            log.error("failed", exc_info=True, detail="x")
        rec = json.loads(buf.getvalue().strip())
    finally:
        root.removeHandler(handler)
    assert rec["event"] == "failed" and rec["detail"] == "x"
    assert "boom" in rec["exc"]
