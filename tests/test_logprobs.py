"""Per-token logprobs ("logprobs": true): each generated token's
log-probability under the RAW model distribution (log_softmax of the step
logits, before temperature/filters — the OpenAI convention), verified
against a manual tokenwise forward."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_llm_inference_tpu import EngineConfig, get_model_config
from distributed_llm_inference_tpu.engine.engine import InferenceEngine
from distributed_llm_inference_tpu.models import llama


class _NumTok:
    """Lossless ids<->text: '12 7 9' (the byte-fallback tokenizer can't
    round-trip arbitrary ids through replacement characters)."""

    def encode(self, text):
        return [int(t) % 250 + 3 for t in text.split()] or [3]

    def decode(self, toks, skip_special_tokens=True):
        return " ".join(str(int(t)) for t in toks)


@pytest.fixture(scope="module")
def eng():
    cfg = get_model_config("test-llama-tiny", eos_token_id=-1)  # full length
    return InferenceEngine(
        cfg, tokenizer=_NumTok(),
        engine_cfg=EngineConfig(prefill_buckets=(32, 64)),
    )


@pytest.mark.slow
def test_logprobs_match_manual_forward(eng):
    cfg = eng.cfg
    r = eng.generate("12 44 91 7", max_tokens=6, greedy=True, chat=False,
                     logprobs=True)
    assert r["status"] == "success"
    lps = r["token_logprobs"]
    assert len(lps) == r["tokens_generated"] == 6
    assert all(lp <= 0.0 for lp in lps)

    # manual tokenwise replay: prompt + generated prefix -> next-token
    # distribution; the recorded logprob must match log_softmax[token]
    ids = eng.tokenizer.encode("12 44 91 7")
    gen = [int(t) for t in r["response"].split()]
    params = eng.backend.params
    cache = llama.init_kv_cache(cfg, batch=1, max_seq=128)
    seq = ids + gen
    logits, _ = llama.forward(
        cfg, params, jnp.asarray([seq], jnp.int32), cache, jnp.int32(0)
    )
    for i, tok in enumerate(gen):
        lp = jax.nn.log_softmax(logits[0, len(ids) - 1 + i].astype(jnp.float32))
        np.testing.assert_allclose(lps[i], float(lp[tok]), rtol=2e-3, atol=2e-4)


def test_logprobs_greedy_tokens_are_argmax(eng):
    """Greedy + logprobs: every recorded logprob is the distribution's
    maximum (the argmax token's own probability)."""
    r = eng.generate("8 5 19", max_tokens=5, greedy=True, chat=False,
                     logprobs=True)
    ids = eng.tokenizer.encode("8 5 19")
    gen = [int(t) for t in r["response"].split()]
    cfg = eng.cfg
    cache = llama.init_kv_cache(cfg, batch=1, max_seq=128)
    logits, _ = llama.forward(
        cfg, eng.backend.params, jnp.asarray([ids + gen], jnp.int32), cache,
        jnp.int32(0),
    )
    for i in range(len(gen)):
        lp = jax.nn.log_softmax(logits[0, len(ids) - 1 + i].astype(jnp.float32))
        np.testing.assert_allclose(
            r["token_logprobs"][i], float(jnp.max(lp)), rtol=2e-3, atol=2e-4
        )


@pytest.mark.slow
def test_logprobs_served_on_pipeline(eng):
    """Round-2 review #3: the pp mesh serves the full request surface —
    logprobs included (bit-consistency vs single-device is covered by
    tests/test_pp_feature_parity.py)."""
    from distributed_llm_inference_tpu import MeshConfig
    from distributed_llm_inference_tpu.parallel.mesh import build_mesh
    from distributed_llm_inference_tpu.parallel.pipeline import PipelineBackend

    cfg = eng.cfg
    mesh = build_mesh(MeshConfig(pp=2), jax.devices())
    pb = PipelineBackend(cfg, eng.backend.params, mesh)
    e2 = InferenceEngine(cfg, backend=pb, tokenizer=eng.tokenizer,
                         engine_cfg=EngineConfig(prefill_buckets=(32,)))
    r = e2.generate("9 9", max_tokens=3, logprobs=True, chat=False)
    assert r["status"] == "success"
    assert len(r["token_logprobs"]) == r["tokens_generated"]


@pytest.mark.slow
def test_logprobs_continuous_falls_back_solo(eng):
    from distributed_llm_inference_tpu.engine.continuous import ContinuousEngine

    cont = ContinuousEngine(eng, n_slots=2, chunk_steps=4)
    try:
        r = cont.submit("41 7 23", max_tokens=4, greedy=True, chat=False,
                        logprobs=True)
        assert r["status"] == "success"
        assert len(r["token_logprobs"]) == r["tokens_generated"]
        assert "continuous" not in r  # served solo
    finally:
        cont.close()
