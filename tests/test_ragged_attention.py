"""Ragged paged attention (ops/paged_attention.ragged_paged_attend +
engine/paged ragged ingest) tests.

The bar: the ragged path is a LAUNCH strategy, not a semantics change —
mixed prefill+decode rows of arbitrary length in one kernel launch must
match the dense reference bit-for-fp32-tolerance (incl. int8 kv_quant and
sliding windows), the engine's ragged admission must be greedy-identical
to the bucketed fallback, and the block-prefix planner must reuse at
EXACT chunk depth where the bucketed plan degrades to a bucket boundary.
Every kernel here runs under interpret=True on CPU (tests/conftest.py
pins DLI_PALLAS_INTERPRET=1 — the tier-1 bit-exactness switch).
"""

import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_inference_tpu import EngineConfig, get_model_config
from distributed_llm_inference_tpu.engine import paged as P
from distributed_llm_inference_tpu.engine.continuous import ContinuousEngine
from distributed_llm_inference_tpu.engine.engine import InferenceEngine
from distributed_llm_inference_tpu.models import api as M
from distributed_llm_inference_tpu.ops.attention import attend
from distributed_llm_inference_tpu.ops.flash_attention import (
    resolve_interpret,
)
from distributed_llm_inference_tpu.ops.kv_quant import KVQuant, dequantize
from distributed_llm_inference_tpu.ops.paged_attention import (
    RAGGED_DECODE,
    RAGGED_PREFILL,
    ragged_paged_attend,
)


# -- kernel-level bit-exactness (ragged vs dense reference) -------------------

def _mixed_case(seed=0, quant=False):
    """A pool + tables + mixed metadata: two prefill rows of different
    lengths (one mid-sequence, one from zero) and two decode rows."""
    rng = np.random.default_rng(seed)
    N, KV, bs, Dh, H, MB = 12, 2, 8, 16, 4, 4
    shape = (N, KV, bs, Dh)
    if quant:
        pool_k = KVQuant(
            jnp.asarray(rng.integers(-127, 127, shape), jnp.int8),
            jnp.asarray(rng.uniform(0.01, 0.1, shape[:-1]), jnp.float32),
        )
        pool_v = KVQuant(
            jnp.asarray(rng.integers(-127, 127, shape), jnp.int8),
            jnp.asarray(rng.uniform(0.01, 0.1, shape[:-1]), jnp.float32),
        )
    else:
        pool_k = jnp.asarray(rng.normal(size=shape), jnp.float32)
        pool_v = jnp.asarray(rng.normal(size=shape), jnp.float32)
    table = jnp.asarray(
        [[1, 2, 3, 4], [5, 6, 7, 8], [9, 10, 11, 1], [2, 5, 9, 3]],
        jnp.int32,
    )
    entries = [
        (0, 5, 13, RAGGED_PREFILL),  # mid-sequence chunk (ctx 0..17)
        (1, 20, 1, RAGGED_DECODE),  # decode at pos 20
        (2, 0, 6, RAGGED_PREFILL),  # cold chunk from position 0
        (3, 9, 1, RAGGED_DECODE),  # decode at pos 9
    ]
    W, tile = 32, 4
    meta, tok_row, tok_pos, offs, stats = P.build_ragged_meta(
        entries, width=W, tile=tile
    )
    q = jnp.asarray(rng.normal(size=(W, H, Dh)), jnp.float32)
    return (pool_k, pool_v, table, entries, meta, tok_row, tok_pos, offs,
            stats, q, bs, MB, KV, Dh)


def _dense_ref(pool_k, pool_v, table, row, q_rows, positions, bs, MB,
               window=None):
    """Per-row reference: gather the row's logical view, run the stock
    masked attention at the given absolute positions."""
    def view(leaf):
        g = dequantize(KVQuant(leaf.q[table[row]], leaf.s[table[row]])) \
            if isinstance(leaf, KVQuant) else leaf[table[row]]
        KV, Dh = g.shape[1], g.shape[-1]
        return g.transpose(1, 0, 2, 3).reshape(1, KV, MB * bs, Dh)

    kv_pos = np.arange(MB * bs)
    mask = jnp.asarray(kv_pos[None, :] <= np.asarray(positions)[:, None])
    if window is not None:
        mask &= jnp.asarray(
            kv_pos[None, :] > np.asarray(positions)[:, None] - window
        )
    return attend(q_rows[None], view(pool_k), view(pool_v), mask[None])[0]


@pytest.mark.parametrize("quant", [False, True])
def test_ragged_kernel_matches_dense_reference(quant):
    (pool_k, pool_v, table, entries, meta, tok_row, tok_pos, offs, stats,
     q, bs, MB, KV, Dh) = _mixed_case(quant=quant)
    out = ragged_paged_attend(
        q, pool_k, pool_v, table, jnp.asarray(meta), interpret=True
    )
    for (row, start, length, _), off in zip(entries, offs):
        ref = _dense_ref(
            pool_k, pool_v, table, row, q[off : off + length],
            np.arange(start, start + length), bs, MB,
        )
        np.testing.assert_allclose(
            np.asarray(out[off : off + length]), np.asarray(ref),
            rtol=2e-5, atol=2e-5,
        )


def test_ragged_kernel_sliding_window():
    (pool_k, pool_v, table, entries, meta, tok_row, tok_pos, offs, stats,
     q, bs, MB, KV, Dh) = _mixed_case()
    win = 7
    out = ragged_paged_attend(
        q, pool_k, pool_v, table, jnp.asarray(meta), window=win,
        interpret=True,
    )
    # traced per-layer width (window_dyn) must agree with the static one
    out_dyn = ragged_paged_attend(
        q, pool_k, pool_v, table, jnp.asarray(meta), jnp.int32(win),
        interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(out_dyn), rtol=1e-6, atol=1e-6
    )
    for (row, start, length, _), off in zip(entries, offs):
        ref = _dense_ref(
            pool_k, pool_v, table, row, q[off : off + length],
            np.arange(start, start + length), bs, MB, window=win,
        )
        np.testing.assert_allclose(
            np.asarray(out[off : off + length]), np.asarray(ref),
            rtol=2e-5, atol=2e-5,
        )


def test_ragged_meta_builder():
    meta, tok_row, tok_pos, offs, stats = P.build_ragged_meta(
        [(0, 5, 13, P.RAGGED_PREFILL), (1, 20, 1, P.RAGGED_DECODE)],
        width=24, tile=4,
    )
    # entry 0: 13 tokens -> 4 tiles (3 full + 1 of length 1); entry 1
    # starts on the next tile boundary
    assert offs == [0, 16]
    assert list(meta[:, 2]) == [4, 4, 4, 1, 1, 0]
    assert stats == {
        "tiles": 6, "pad_tiles": 1, "prefill_rows": 1, "decode_rows": 1,
    }
    # pad tile inherits its predecessor's placement (DMA repetition) with
    # q_len 0; padding tokens carry row -1 (scattered to the trash block)
    assert meta[5, 0] == meta[4, 0] and meta[5, 1] == meta[4, 1]
    assert tok_row[13] == -1 and tok_row[12] == 0 and tok_row[16] == 1
    assert tok_pos[16] == 20
    with pytest.raises(ValueError):
        P.build_ragged_meta(
            [(0, 0, 25, P.RAGGED_PREFILL)], width=24, tile=4
        )
    with pytest.raises(ValueError):
        P.build_ragged_meta([(0, 0, 1, 0)], width=10, tile=4)


def test_interpret_env_switch():
    """tests/conftest.py pins DLI_PALLAS_INTERPRET=1, and the shared
    resolver honors it — the tier-1 contract that every Pallas kernel
    here actually ran its own math, not a silent XLA fallback."""
    assert os.environ.get("DLI_PALLAS_INTERPRET") == "1"
    assert resolve_interpret(None) is True
    assert resolve_interpret(False) is False
    old = os.environ["DLI_PALLAS_INTERPRET"]
    try:
        os.environ["DLI_PALLAS_INTERPRET"] = "0"
        # explicit 0: the backend default decides only via TPU presence
        assert resolve_interpret(None) is False
    finally:
        os.environ["DLI_PALLAS_INTERPRET"] = old


# -- engine-level: ragged admission vs bucketed fallback ----------------------

PREFIX_CFG = dict(dtype="float32", eos_token_id=-1, max_seq_len=256)


@pytest.fixture(scope="module", params=["test-llama-tiny", "test-gpt2-tiny"])
def family_setup(request):
    cfg = get_model_config(request.param, **PREFIX_CFG)
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _cont(cfg, params, ragged, attn_impl=None, **ecfg):
    if attn_impl is not None:
        cfg = cfg.replace(attn_impl=attn_impl)
    eng = InferenceEngine(
        cfg, params=params,
        engine_cfg=EngineConfig(
            prefix_cache_entries=4, ragged_prefill=ragged,
            prefill_buckets=(64, 128, 256), **ecfg,
        ),
    )
    return ContinuousEngine(
        eng, n_slots=4, chunk_steps=8, slot_max_seq=256,
        kv_pool_blocks=48, kv_block_size=16,
    )


def _submit_all(cont, prompts, **kw):
    out = [None] * len(prompts)

    def run(i):
        out[i] = cont.submit(prompts[i], greedy=True, chat=False, **kw)

    threads = [
        threading.Thread(target=run, args=(i,)) for i in range(len(prompts))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return out


def test_ragged_greedy_identical_to_bucketed(family_setup):
    """Mixed fleet (concurrent prompts of different lengths, warm prefix
    reuse) — the ragged path must be token-identical to the bucketed
    scratch path, both families."""
    cfg, params = family_setup
    shared = " ".join(f"ctx{j}" for j in range(16))
    prompts = [
        "the quick brown fox jumps over the lazy dog",
        shared + " question one",
        shared + " question two",
        "short",
    ]
    outs = {}
    for ragged in (False, True):
        cont = _cont(cfg, params, ragged)
        try:
            # serial first pass warms the prefix chains; the threaded wave
            # exercises a mixed fleet on the warm path
            warm = [
                cont.submit(p, max_tokens=10, greedy=True, chat=False)
                for p in prompts
            ]
            wave = _submit_all(cont, prompts, max_tokens=10)
            st = cont.stats()
        finally:
            cont.close()
        assert all(r["status"] == "success" for r in warm + wave), (
            ragged, warm, wave,
        )
        assert st["paged"]["ragged_prefill"] is ragged
        outs[ragged] = [r["response"] for r in warm] + [
            r["response"] for r in wave
        ]
    assert outs[True] == outs[False]


def test_ragged_kernel_path_greedy_identical(family_setup):
    """attn_impl='pallas' routes the ragged ingest through the Pallas
    kernel (interpret mode on CPU); greedy output must match the XLA
    gather twin — the kernel-vs-fallback bit-exactness gate at the
    serving level."""
    cfg, params = family_setup
    if cfg.arch == "gpt2":
        pytest.skip("attn_impl is a llama-family config knob")
    prompts = ["a b c d e f", "the quick brown fox jumps"]
    outs = {}
    for impl in ("xla", "pallas"):
        cont = _cont(cfg, params, True, attn_impl=impl)
        try:
            outs[impl] = [
                cont.submit(p, max_tokens=8, greedy=True, chat=False)[
                    "response"
                ]
                for p in prompts
            ]
        finally:
            cont.close()
    assert outs["pallas"] == outs["xla"]


def test_ragged_int8_pool_greedy_identical(family_setup):
    """int8 kv_quant composes with the ragged path: quantize-on-scatter
    into the pool must serve the same greedy stream as the bucketed
    scratch path (which quantizes into the scratch, then block-copies)."""
    cfg, params = family_setup
    if cfg.arch == "gpt2":
        pytest.skip("kv_quant is a llama-family config knob")
    qcfg = cfg.replace(kv_quant="int8")
    prompts = ["the quick brown fox", "hello world"]
    outs = {}
    for ragged in (False, True):
        cont = _cont(qcfg, params, ragged)
        try:
            outs[ragged] = [
                cont.submit(p, max_tokens=8, greedy=True, chat=False)[
                    "response"
                ]
                for p in prompts
            ]
        finally:
            cont.close()
    assert outs[True] == outs[False]


def test_exact_depth_reuse_no_bucket_degradation():
    """The planner regression the ragged path exists to fix: a hit whose
    tail no prefill bucket fits degrades the reuse depth on the bucketed
    path, but reuses at EXACT chunk depth on the ragged path — and
    mark() accounting matches the planned depth in both modes."""
    cfg = get_model_config(
        "test-llama-tiny", dtype="float32", eos_token_id=-1,
        max_seq_len=128,
    )
    params = M.init_params(cfg, jax.random.PRNGKey(1))

    def serve(ragged):
        eng = InferenceEngine(
            cfg, params=params,
            engine_cfg=EngineConfig(
                prefix_cache_entries=4, ragged_prefill=ragged,
                prefill_buckets=(64,),
            ),
        )
        cont = ContinuousEngine(
            eng, n_slots=2, chunk_steps=4, slot_max_seq=128,
            kv_pool_blocks=24, kv_block_size=16,
        )
        try:
            # 96-token shared head (6 full blocks), ~100-token prompts:
            # the 4-token tail needs the 64 bucket, and 96 + 64 > 128, so
            # the bucketed plan must degrade the depth to 64
            base = "x" * 96
            r1 = cont.submit(base + "abcd", max_tokens=4, greedy=True,
                             chat=False)
            r2 = cont.submit(base + "wxyz", max_tokens=4, greedy=True,
                             chat=False)
            st = cont.stats()["prefix_cache"]
        finally:
            cont.close()
        assert r1["status"] == "success" and r2["status"] == "success"
        return r2.get("prefix_cached_tokens", 0), st

    ragged_depth, ragged_st = serve(True)
    bucketed_depth, bucketed_st = serve(False)
    assert ragged_depth == 96  # exact chunk depth: 6 blocks of 16
    assert bucketed_depth == 64  # degraded to fit the 64 bucket
    # mark() accounting follows the PLANNED depth, not the chain depth
    assert ragged_st["dedup_saved_tokens"] == 96
    assert bucketed_st["dedup_saved_tokens"] == 64


def test_ragged_single_program_any_tail():
    """One compiled (extend, prefill) program pair serves every tail:
    admissions with different prompt lengths must not add backend
    launches beyond ceil(tail/width), and tails <= width are exactly ONE
    launch (the single-launch contract the analysis ragged rule pins on
    the artifact)."""
    cfg = get_model_config(
        "test-llama-tiny", dtype="float32", eos_token_id=-1,
        max_seq_len=256,
    )
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    eng = InferenceEngine(
        cfg, params=params,
        # chunked_prefill=False: this test pins the PER-ADMISSION ragged
        # ingest launches (extend/prefill pair); the chunked scheduler's
        # mixed-launch counting lives in tests/test_scheduler.py
        engine_cfg=EngineConfig(
            prefix_cache_entries=0, ragged_prefill=True,
            chunked_prefill=False,
        ),
    )
    cont = ContinuousEngine(
        eng, n_slots=2, chunk_steps=4, slot_max_seq=256,
        kv_pool_blocks=40, kv_block_size=16,
    )
    calls = {"extend": 0, "prefill": 0}
    be = cont.backend
    orig_extend, orig_prefill = be.extend_ragged_paged, be.prefill_ragged_paged

    def count_extend(*a, **k):
        calls["extend"] += 1
        return orig_extend(*a, **k)

    def count_prefill(*a, **k):
        calls["prefill"] += 1
        return orig_prefill(*a, **k)

    be.extend_ragged_paged = count_extend
    be.prefill_ragged_paged = count_prefill
    try:
        # 30-token tail (< width 64): one prefill launch, zero extends
        cont.submit("a" * 30, max_tokens=3, greedy=True, chat=False)
        assert calls == {"extend": 0, "prefill": 1}
        # 150-token tail: two whole-width extends + one prefill
        cont.submit("b" * 150, max_tokens=3, greedy=True, chat=False)
        assert calls == {"extend": 2, "prefill": 2}
        # a third, different tail length must not recompile the programs
        n_prog = be.ragged_program_count()
        cont.submit("c" * 45, max_tokens=3, greedy=True, chat=False)
        assert be.ragged_program_count() == n_prog
    finally:
        be.extend_ragged_paged = orig_extend
        be.prefill_ragged_paged = orig_prefill
        cont.close()


def test_ragged_metrics_and_pool_hygiene():
    """dli_ragged_* families populate (rows by kind, tile liveness, the
    compiled-program gauge) and the pool frees fully after the fleet
    drains — the ragged scatter leaks no blocks."""
    cfg = get_model_config(
        "test-llama-tiny", dtype="float32", eos_token_id=-1,
        max_seq_len=256,
    )
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    eng = InferenceEngine(
        cfg, params=params,
        # per-admission ingest metrics (phase=extend/prefill launches);
        # the chunked scheduler's phase=mixed accounting is covered in
        # tests/test_scheduler.py
        engine_cfg=EngineConfig(
            prefix_cache_entries=0, ragged_prefill=True,
            chunked_prefill=False,
        ),
    )
    cont = ContinuousEngine(
        eng, n_slots=2, chunk_steps=4, slot_max_seq=256,
        kv_pool_blocks=40, kv_block_size=16,
    )
    try:
        for p in ("hello world", "x" * 100):
            r = cont.submit(p, max_tokens=4, greedy=True, chat=False)
            assert r["status"] == "success"
        snap = eng.metrics.snapshot()

        def series(name):
            return {
                tuple(sorted(s["labels"].items())): s["value"]
                for s in snap.get(name, {}).get("series", [])
            }

        rows = series("dli_ragged_rows_total")
        assert rows.get((("kind", "prefill"),), 0) >= 2
        tiles = series("dli_ragged_tiles_total")
        assert tiles.get((("state", "live"),), 0) > 0
        assert tiles.get((("state", "pad"),), 0) > 0
        launches = series("dli_ragged_launches_total")
        assert launches.get((("phase", "prefill"),), 0) == 2
        progs = series("dli_ragged_compiled_programs")
        assert progs.get((), 0) >= 1
    finally:
        cont.close()
    assert cont._alloc.free_blocks == cont._alloc.n_blocks - 1
    assert cont._alloc.outstanding == 0
