"""The compiled-decode invariant checker (analysis/): rule fixtures
(positive + negative + suppressed per rule), call-graph reachability
units on synthetic packages AND the real one, the CLI exit contract, and
the compiled-artifact (HLO) assertions for solo and pp decode.

Selectable standalone: `pytest -m analysis`.
"""

import os
import subprocess
import sys
import textwrap

import jax
import pytest

from distributed_llm_inference_tpu.analysis import hlo
from distributed_llm_inference_tpu.analysis.callgraph import (
    build_index, traced_reachable,
)
from distributed_llm_inference_tpu.analysis.lint import run_lint

pytestmark = pytest.mark.analysis

PKG_ROOT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "distributed_llm_inference_tpu",
)

needs_shard_map = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="this jax build has no jax.shard_map (pp backends unavailable)",
)


def make_pkg(tmp_path, files: dict) -> str:
    """Write a throwaway package tree and return its root."""
    root = tmp_path / "fixture_pkg"
    for rel, body in files.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(body))
    return str(root)


def lint(tmp_path, files, rules=None):
    return run_lint(make_pkg(tmp_path, files), rules=rules)


def rules_hit(diagnostics):
    return sorted({d.rule for d in diagnostics})


# -- host-sync: reachability-scoped sync detection ---------------------------

HOST_SYNC_PKG = {
    "engine/generate.py": """
        import functools
        import jax
        import jax.numpy as jnp
        from ..ops.helpers import traced_helper

        @functools.partial(jax.jit, donate_argnames=("cache",))
        def decode(tokens, cache):
            return traced_helper(tokens), cache

        def host_only(x):
            return x.item()  # NOT reachable from a jit root: no finding
    """,
    "ops/helpers.py": """
        import jax.numpy as jnp

        def traced_helper(x):
            return jnp.sum(x)
    """,
}


def test_host_sync_negative(tmp_path):
    diags, _ = lint(tmp_path, HOST_SYNC_PKG, rules=["host-sync"])
    assert diags == []


def test_host_sync_positive_through_call_graph(tmp_path):
    files = dict(HOST_SYNC_PKG)
    files["ops/helpers.py"] = """
        import jax.numpy as jnp

        def traced_helper(x):
            n = x.item()
            return jnp.sum(x) + n
    """
    diags, _ = lint(tmp_path, files, rules=["host-sync"])
    assert len(diags) == 1
    d = diags[0]
    assert d.rule == "host-sync"
    assert d.path.endswith("ops/helpers.py")
    assert d.line == 5
    assert ".item()" in d.message


@pytest.mark.parametrize("snippet,expect", [
    ("jnp.sum(x)", 0),                       # clean
    ("x.tolist()", 1),                       # explicit fetch
    ("float(x)", 1),                         # concretization
    ("float(x.shape[0])", 0),                # shape metadata is host-known
    ("int(len(x.shape))", 0),                # len() is host-known
    ("np.asarray(x)", 1),                    # numpy forces host
    ("print(x)", 1),                         # host side effect
    ("time.time()", 1),                      # timestamps in the trace
    ("jax.device_get(x)", 1),                # device->host
    ("jax.debug.print('{}', x)", 1),         # lowers to a callback
])
def test_host_sync_catalog(tmp_path, snippet, expect):
    files = {
        "engine/mod.py": f"""
            import time
            import functools
            import jax
            import jax.numpy as jnp
            import numpy as np

            @functools.partial(jax.jit, donate_argnames=("cache",))
            def decode(x, cache):
                y = {snippet}
                return y, cache
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["host-sync"])
    assert len(diags) == expect, (snippet, diags)


def test_host_sync_suppressed_with_reason(tmp_path):
    files = {
        "engine/mod.py": """
            import jax

            @jax.jit
            def decode(x):
                n = x.item()  # jaxlint: disable=host-sync -- fixture: known-safe here
                return n
        """,
    }
    diags, suppressed = lint(tmp_path, files, rules=["host-sync"])
    assert diags == []
    assert suppressed == 1


def test_suppression_without_reason_is_reported(tmp_path):
    files = {
        "engine/mod.py": """
            import jax

            @jax.jit
            def decode(x):
                n = x.item()  # jaxlint: disable=host-sync
                return n
        """,
    }
    diags, suppressed = lint(tmp_path, files, rules=["host-sync"])
    assert suppressed == 0
    assert rules_hit(diags) == ["bad-suppression", "host-sync"]


def test_standalone_suppression_covers_next_line(tmp_path):
    files = {
        "engine/mod.py": """
            import jax

            @jax.jit
            def decode(x):
                # jaxlint: disable=host-sync -- fixture: next-line form
                n = x.item()
                return n
        """,
    }
    diags, suppressed = lint(tmp_path, files, rules=["host-sync"])
    assert diags == []
    assert suppressed == 1


# -- tracer-branch -----------------------------------------------------------

def test_tracer_branch_positive_and_negative(tmp_path):
    files = {
        "ops/kernels.py": """
            import jax.numpy as jnp

            def bad(x):
                if jnp.any(x > 0):
                    return x
                return -x

            def good(x):
                if x.shape[0] > 1:
                    return x
                if x is None:
                    return None
                return -x
        """,
        "serving/host.py": """
            import jax.numpy as jnp

            def fine_here(x):
                # serving/ is host code: data-dependent branching is normal
                if jnp.any(x > 0):
                    return x
                return -x
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["tracer-branch"])
    assert len(diags) == 1
    assert diags[0].path.endswith("ops/kernels.py")
    assert diags[0].line == 5


def test_tracer_branch_while_and_reduction_method(tmp_path):
    files = {
        "parallel/ring.py": """
            def spin(x):
                while x.sum() > 0:
                    x = x - 1
                return x
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["tracer-branch"])
    assert len(diags) == 1
    assert "while" in diags[0].message


# -- donate-cache ------------------------------------------------------------

def test_donation_positive_negative_argnums(tmp_path):
    files = {
        "engine/mod.py": """
            import functools
            import jax

            @functools.partial(jax.jit, donate_argnames=("cache",))
            def good_names(tokens, cache):
                return tokens, cache

            @functools.partial(jax.jit, static_argnames=("n",))
            def bad(tokens, cache, *, n):
                return tokens, cache

            @jax.jit
            def no_cache_arg(tokens):
                return tokens

            def build():
                def body(shared, tokens, cache):
                    return tokens, cache
                shmapped = wrap(body)
                return jax.jit(shmapped, donate_argnums=(2,))

            def build_bad():
                def body(shared, tokens, cache):
                    return tokens, cache
                shmapped = wrap(body)
                return jax.jit(shmapped, donate_argnums=(1,))

            def wrap(f):
                return f
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["donate-cache"])
    assert len(diags) == 2
    assert {d.line for d in diags} == {10, 27}  # `bad` def, build_bad's jit


def test_donation_shared_pool_exception(tmp_path):
    """Block-level prefix sharing: a `shared_pool` param is a READ-ONLY
    mapped pool — the rule inverts: leaving it undonated is correct, and
    donating it (which would let XLA recycle buffers other block tables
    still read) is the flagged defect."""
    files = {
        "engine/mod.py": """
            import functools
            import jax

            @jax.jit
            def good_gather(shared_pool, table_row):
                return shared_pool

            @functools.partial(jax.jit, donate_argnames=("shared_pool",))
            def bad_gather(shared_pool, table_row):
                return shared_pool

            @jax.jit
            def still_bad_plain_pool(pool, table_row):
                return pool
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["donate-cache"])
    assert len(diags) == 2
    by_line = {d.line: d.message for d in diags}
    assert 10 in by_line and "must not be donated" in by_line[10]
    assert 14 in by_line and "does not donate" in by_line[14]


def test_donation_shared_pool_reasoned_suppression(tmp_path):
    """A donated shared_pool under a REASONED suppression is accepted;
    dropping the reason downgrades to the bad-suppression diagnostic —
    same contract as every other rule's escape hatch."""
    files = {
        "engine/mod.py": """
            import functools
            import jax

            @functools.partial(jax.jit, donate_argnames=("shared_pool",))
            # jaxlint: disable=donate-cache -- single-tenant pool: no other table maps these blocks
            def gather_private(shared_pool, table_row):
                return shared_pool
        """,
    }
    diags, suppressed = lint(tmp_path, files, rules=["donate-cache"])
    assert diags == []
    assert suppressed == 1
    files_bad = {
        "engine/mod.py": files["engine/mod.py"].replace(
            " -- single-tenant pool: no other table maps these blocks", ""
        ),
    }
    diags, _ = lint(tmp_path, files_bad, rules=["donate-cache"])
    assert any(d.rule == "bad-suppression" for d in diags)


# -- static-args -------------------------------------------------------------

def test_static_args_fstring_call_site(tmp_path):
    files = {
        "engine/mod.py": """
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("mode",))
            def run(x, *, mode):
                return x

            def bad_caller(x, name):
                return run(x, mode=f"m-{name}")

            def good_caller(x):
                return run(x, mode="fixed")
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["static-args"])
    assert len(diags) == 1
    assert diags[0].line == 10


def test_static_args_computed_names(tmp_path):
    files = {
        "engine/mod.py": """
            import functools
            import jax

            NAMES = ("mode",)

            @functools.partial(jax.jit, static_argnames=NAMES)
            def run(x, *, mode):
                return x
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["static-args"])
    assert len(diags) == 1
    assert "literal" in diags[0].message


# -- metrics-labels ----------------------------------------------------------

def test_metrics_labels_literal_and_cap(tmp_path):
    files = {
        "serving/mod.py": """
            def setup(registry, names):
                ok = registry.counter(
                    "dli_good_total", "fine", ("route", "status"),
                )
                computed = registry.counter(
                    "dli_computed_total", "bad", tuple(names),
                )
                wide = registry.gauge(
                    "dli_wide", "bad",
                    ("a", "b", "c", "d", "e"),
                )
                unlabeled = registry.counter("dli_plain_total", "fine")
                not_a_metric = registry.counter("requests", "no dli_ prefix")
                return ok, computed, wide, unlabeled, not_a_metric
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["metrics-labels"])
    assert len(diags) == 2
    msgs = " / ".join(d.message for d in diags)
    assert "dli_computed_total" in msgs and "dli_wide" in msgs


# -- route-counter -----------------------------------------------------------

def test_route_counter_rule(tmp_path):
    files = {
        "serving/srv.py": """
            class Handler:
                def _send(self, code):
                    self._count(code)
                    self.send_response(code)

                def good_stream(self):
                    self._count(200)
                    self.send_response(200)

                def bad_stream(self):
                    self.send_response(200)
        """,
        "engine/not_serving.py": """
            class Other:
                def whatever(self):
                    self.send_response(200)  # not serving/: out of scope
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["route-counter"])
    assert len(diags) == 1
    assert diags[0].line == 12
    assert "bad_stream" in diags[0].message


# -- call-graph units on the REAL package ------------------------------------

@pytest.fixture(scope="module")
def real_reachable():
    index = build_index(PKG_ROOT)
    return traced_reachable(index)


def test_real_traced_set_includes_hot_path(real_reachable):
    for key in [
        ("engine.generate", "decode"),
        ("engine.generate", "stop_mask"),
        ("engine.generate", "slot_step"),
        ("ops.sampling", "sample_token"),
        ("ops.sampling", "_sample_warped"),
        ("models.api", "forward_layers"),
        ("models.llama", "forward_layers"),
        ("models.gpt2", "forward_layers"),  # family-dispatch fan-out
        ("ops.attention", "attend"),
        ("engine.paged", "make_paged_hook.hook"),  # nested closure
    ]:
        assert key in real_reachable, key


def test_real_traced_set_excludes_host_code(real_reachable):
    for key in [
        ("engine.generate", "pick_bucket"),  # host-side bucket picker
        ("engine.engine", "InferenceEngine.generate"),
        ("serving.server", "main"),
        ("utils.metrics", "MetricsRegistry.render"),
    ]:
        assert key not in real_reachable, key


def test_fault_hooks_decode_unreachable(real_reachable):
    """The fault-injection harness (utils/faults.py) is strictly
    host-side: no function in it — and none of the scheduler host-loop
    functions that call faults.check — may be reachable from any jit
    root. This is what keeps the chaos suite (tests/test_faults.py)
    invisible to the compiled-decode invariants: check() can sleep and
    raise precisely BECAUSE it can never be traced."""
    fault_funcs = sorted(k for k in real_reachable if k[0] == "utils.faults")
    assert not fault_funcs, fault_funcs
    # the host-loop callers of faults.check stay untraced too — if one of
    # these ever became a jit root, the hook (and its time.sleep wedge)
    # would land in compiled code
    for key in [
        ("engine.continuous", "ContinuousEngine._launch_chunk"),
        ("engine.continuous", "ContinuousEngine._process"),
        ("engine.continuous", "ContinuousEngine._admit_one"),
        ("engine.continuous", "ContinuousEngine._supervise"),
        ("engine.continuous", "ContinuousEngine._run_recovery"),
        ("engine.engine", "InferenceEngine._generate_locked"),
    ]:
        assert key not in real_reachable, key


def test_shadow_store_decode_unreachable(real_reachable):
    """The warm-recovery shadow store (engine/shadow.py) is strictly
    host-side: its copier thread blocks on device->host transfers and
    its persistence does file I/O — none of it may be reachable from a
    jit root, exactly like utils/faults.py. The engine-side capture /
    restore drivers stay untraced too; only the tiny gather/scatter
    PROGRAMS (engine/paged.gather_shadow_blocks /
    restore_shadow_blocks) touch the device, as their own jit roots."""
    shadow_funcs = sorted(
        k for k in real_reachable if k[0] == "engine.shadow"
    )
    assert not shadow_funcs, shadow_funcs
    for key in [
        ("engine.continuous", "ContinuousEngine._shadow_capture"),
        ("engine.continuous", "ContinuousEngine._restore_shadow"),
    ]:
        assert key not in real_reachable, key


def test_preemption_host_paths_decode_unreachable(real_reachable):
    """The SLO-aware preemption machinery (victim selection, the
    swap-to-host flush, the resume-queue restore, the pressure ladder)
    and the deadline/cancellation checks are strictly host-side launch-
    boundary logic: time.time/wall-clock comparisons, allocator walks,
    and a SYNCHRONOUS shadow flush — exactly the host syncs the hot-path
    lint exists to keep out of compiled code. None may be reachable from
    any jit root (the acceptance criterion's 'zero new host syncs in the
    decode hot path'); only the pre-existing restore/gather PROGRAMS
    touch the device, as their own jit roots."""
    for key in [
        ("engine.continuous", "ContinuousEngine._preempt_for"),
        ("engine.continuous", "ContinuousEngine._victim_for"),
        ("engine.continuous", "ContinuousEngine._alloc_with_pressure"),
        ("engine.continuous", "ContinuousEngine._prepare_resume"),
        ("engine.continuous", "ContinuousEngine._cancel_env"),
        ("engine.continuous", "ContinuousEngine._deadline_env"),
        ("engine.continuous", "ContinuousEngine._past_deadline"),
        ("engine.scheduler", "TokenBudgetScheduler.select_victim"),
        ("engine.scheduler", "TokenBudgetScheduler.victim_key"),
    ]:
        assert key not in real_reachable, key


def test_ragged_host_planner_decode_unreachable(real_reachable):
    """The ragged launch planner (engine/paged.build_ragged_meta — numpy
    metadata assembly) and the continuous engine's launch-loop callers
    are strictly host-side: none may be reachable from a jit root, or
    their numpy work would land inside compiled programs. The TRACED half
    of the ragged path (make_ragged_fill_hook's closure, the kernel) must
    stay reachable — that is what the host-sync rule audits."""
    for key in [
        ("engine.paged", "build_ragged_meta"),
        ("engine.continuous", "ContinuousEngine._ragged_ingest"),
        ("engine.continuous", "ContinuousEngine._ragged_launch_args"),
    ]:
        assert key not in real_reachable, key
    assert ("engine.paged", "make_ragged_fill_hook.hook") in real_reachable


def test_chunked_scheduler_decode_unreachable(real_reachable):
    """The SLO-aware chunked-prefill scheduler (engine/scheduler.py) is
    pure host-side planning — numpy/time/metrics work that must never
    land in a compiled program. Same pin as the ragged meta builder; the
    TRACED half of the chunked path (engine/paged.mixed_step_ragged's
    epilogue via slot_step) stays reachable."""
    sched_funcs = sorted(
        k for k in real_reachable if k[0] == "engine.scheduler"
    )
    assert not sched_funcs, sched_funcs
    for key in [
        ("engine.continuous", "ContinuousEngine._launch_mixed"),
        ("engine.continuous", "ContinuousEngine._process_mixed"),
        ("engine.continuous", "ContinuousEngine._start_job"),
        ("engine.continuous", "ContinuousEngine._sched_loop"),
    ]:
        assert key not in real_reachable, key
    assert ("engine.paged", "mixed_epilogue") in real_reachable


def test_router_tier_decode_unreachable(real_reachable):
    """The replica router (serving/router.py) is host-side glue — an
    HTTP front tier that never touches an engine or jax. Nothing in it
    may be reachable from any jit root: its blocking urllib calls,
    time.sleep waits, and subprocess management are exactly the host
    syncs the hot-path lint exists to keep out of compiled code. Same
    pin as utils/faults.py."""
    router_funcs = sorted(
        k for k in real_reachable if k[0] == "serving.router"
    )
    assert not router_funcs, router_funcs
    # the shared retry policy it leans on stays host-side too
    retry_funcs = sorted(k for k in real_reachable if k[0] == "utils.retry")
    assert not retry_funcs, retry_funcs


def test_kv_fabric_decode_unreachable(real_reachable):
    """The cross-replica KV fabric (serving/kv_fabric.py) is strictly
    host-side: blocking urllib fetches with deadlines, npz codec work,
    digest recomputation. None of it — and none of the continuous
    engine's fetch/import drivers — may be reachable from a jit root:
    fabric fetches happen ONLY at the admission host boundary, and the
    only device work they trigger is the pre-existing pre-warmed
    restore_shadow_blocks scatter, as its own jit root. Same pin as the
    router tier and utils/faults.py."""
    fabric_funcs = sorted(
        k for k in real_reachable if k[0] == "serving.kv_fabric"
    )
    assert not fabric_funcs, fabric_funcs
    for key in [
        ("engine.continuous", "ContinuousEngine._fabric_prefetch"),
        ("engine.continuous", "ContinuousEngine._import_fabric_chain"),
        ("engine.continuous", "ContinuousEngine.fabric_chain"),
        ("engine.continuous", "ContinuousEngine.fabric_digests"),
    ]:
        assert key not in real_reachable, key


def test_repo_is_clean():
    """The package itself lints clean — the same gate CI runs."""
    diags, _ = run_lint(PKG_ROOT)
    assert diags == [], "\n".join(d.format() for d in diags)


# -- CLI exit contract (acceptance criterion) --------------------------------

def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "distributed_llm_inference_tpu.analysis",
         *args],
        capture_output=True, text=True,
        cwd=os.path.dirname(PKG_ROOT),
    )


def test_cli_clean_repo_exits_zero():
    r = _run_cli()
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_item_in_decode_reachable_function_exits_nonzero(tmp_path):
    """A `.item()` injected into a decode-reachable function must fail the
    CLI with a file:line diagnostic."""
    import shutil

    bad_root = str(tmp_path / "pkg_with_item")
    shutil.copytree(PKG_ROOT, bad_root, ignore=shutil.ignore_patterns(
        "__pycache__", "*.pyc"
    ))
    gen = os.path.join(bad_root, "engine", "generate.py")
    with open(gen) as fh:
        src = fh.read()
    needle = "    m = tokens == jnp.int32(cfg.eos_token_id)"
    assert needle in src
    with open(gen, "w") as fh:
        fh.write(src.replace(
            needle, "    _bad = tokens.item()\n" + needle
        ))
    r = _run_cli("--root", bad_root)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "host-sync" in r.stdout
    # file:line diagnostics
    assert "generate.py:" in r.stdout and ".item()" in r.stdout


# -- compiled-artifact (HLO) assertions --------------------------------------

@pytest.fixture(scope="module")
def engine():
    return hlo.tiny_engine()


def test_solo_decode_artifact(engine):
    text = hlo.lower_solo_decode(engine)
    assert hlo.check_no_host_callbacks(text) == []
    assert hlo.check_while_compiled(text) == []
    cache = engine.backend.init_cache(1, engine.cfg.max_seq_len)
    n_leaves = hlo.count_cache_leaves(cache)
    assert hlo.check_donation(text, min_aliased=n_leaves) == []


def test_constrained_decode_artifact(engine):
    text = hlo.lower_solo_decode(engine, constrained=True)
    assert hlo.check_no_host_callbacks(text) == []
    assert hlo.check_while_compiled(text) == []


def test_donation_checker_catches_dropped_donation(engine):
    """check_donation must FAIL on a re-wrap that drops donate_argnames —
    the exact silent regression it exists to catch."""
    import jax as _jax
    import jax.numpy as jnp

    from distributed_llm_inference_tpu.engine import generate as G

    cfg = engine.cfg
    cache = engine.backend.init_cache(1, cfg.max_seq_len)
    undonated = _jax.jit(
        G.decode, static_argnames=("cfg", "max_steps"),
    ).lower(
        cfg, engine.backend.params, jnp.zeros((1,), jnp.int32), cache,
        jnp.int32(4), jnp.int32(8), _jax.random.PRNGKey(0),
        G.default_sampling(greedy=True), None, None, None, None, None,
        max_steps=16,
    ).as_text()
    assert hlo.check_donation(undonated, min_aliased=1) != []


def test_callback_checker_catches_injected_callback(engine):
    """check_no_host_callbacks must FAIL on a program that really does
    call back into Python per step."""
    import jax as _jax
    import jax.numpy as jnp

    def with_callback(x):
        _jax.debug.print("step {}", x)
        return x * 2

    text = _jax.jit(with_callback).lower(jnp.ones((4,))).as_text()
    assert hlo.check_no_host_callbacks(text) != []


def test_recompile_guard(engine):
    assert hlo.check_no_recompile(engine) == []


def test_run_hlo_checks_all_green():
    results = hlo.run_hlo_checks()
    bad = {k: v for k, v in results.items() if v}
    assert not bad, bad


@needs_shard_map
def test_pp_decode_artifact(eight_devices):
    if not hlo.pp_available():
        pytest.skip("pp HLO check needs >= 2 devices")
    text = hlo.lower_pp_decode()
    assert hlo.check_no_host_callbacks(text) == []
    assert hlo.check_pp_ring(text) == []
