"""The compiled-decode + host-control-plane invariant checker
(analysis/): rule fixtures (positive + negative + suppressed per rule,
the lock-discipline / resource-lifecycle / thread-reachability families
included), DERIVED thread-aware reachability on the real package (the
superset-of-the-old-pin-list regression), the CLI exit contract with
seeded-violation fixtures for each control-plane rule, and the
compiled-artifact (HLO) assertions for solo and pp decode.

Selectable standalone: `pytest -m analysis`.
"""

import os
import subprocess
import sys
import textwrap

import jax
import pytest

from distributed_llm_inference_tpu.analysis import hlo
from distributed_llm_inference_tpu.analysis.callgraph import (
    build_index, decode_unreachable, thread_roots, traced_reachable,
)
from distributed_llm_inference_tpu.analysis.lint import run_lint

pytestmark = pytest.mark.analysis

PKG_ROOT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "distributed_llm_inference_tpu",
)

needs_shard_map = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="this jax build has no jax.shard_map (pp backends unavailable)",
)


def make_pkg(tmp_path, files: dict) -> str:
    """Write a throwaway package tree and return its root."""
    root = tmp_path / "fixture_pkg"
    for rel, body in files.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(body))
    return str(root)


def lint(tmp_path, files, rules=None):
    return run_lint(make_pkg(tmp_path, files), rules=rules)


def rules_hit(diagnostics):
    return sorted({d.rule for d in diagnostics})


# -- host-sync: reachability-scoped sync detection ---------------------------

HOST_SYNC_PKG = {
    "engine/generate.py": """
        import functools
        import jax
        import jax.numpy as jnp
        from ..ops.helpers import traced_helper

        @functools.partial(jax.jit, donate_argnames=("cache",))
        def decode(tokens, cache):
            return traced_helper(tokens), cache

        def host_only(x):
            return x.item()  # NOT reachable from a jit root: no finding
    """,
    "ops/helpers.py": """
        import jax.numpy as jnp

        def traced_helper(x):
            return jnp.sum(x)
    """,
}


def test_host_sync_negative(tmp_path):
    diags, _ = lint(tmp_path, HOST_SYNC_PKG, rules=["host-sync"])
    assert diags == []


def test_host_sync_positive_through_call_graph(tmp_path):
    files = dict(HOST_SYNC_PKG)
    files["ops/helpers.py"] = """
        import jax.numpy as jnp

        def traced_helper(x):
            n = x.item()
            return jnp.sum(x) + n
    """
    diags, _ = lint(tmp_path, files, rules=["host-sync"])
    assert len(diags) == 1
    d = diags[0]
    assert d.rule == "host-sync"
    assert d.path.endswith("ops/helpers.py")
    assert d.line == 5
    assert ".item()" in d.message


@pytest.mark.parametrize("snippet,expect", [
    ("jnp.sum(x)", 0),                       # clean
    ("x.tolist()", 1),                       # explicit fetch
    ("float(x)", 1),                         # concretization
    ("float(x.shape[0])", 0),                # shape metadata is host-known
    ("int(len(x.shape))", 0),                # len() is host-known
    ("np.asarray(x)", 1),                    # numpy forces host
    ("print(x)", 1),                         # host side effect
    ("time.time()", 1),                      # timestamps in the trace
    ("jax.device_get(x)", 1),                # device->host
    ("jax.debug.print('{}', x)", 1),         # lowers to a callback
])
def test_host_sync_catalog(tmp_path, snippet, expect):
    files = {
        "engine/mod.py": f"""
            import time
            import functools
            import jax
            import jax.numpy as jnp
            import numpy as np

            @functools.partial(jax.jit, donate_argnames=("cache",))
            def decode(x, cache):
                y = {snippet}
                return y, cache
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["host-sync"])
    assert len(diags) == expect, (snippet, diags)


def test_host_sync_suppressed_with_reason(tmp_path):
    files = {
        "engine/mod.py": """
            import jax

            @jax.jit
            def decode(x):
                n = x.item()  # jaxlint: disable=host-sync -- fixture: known-safe here
                return n
        """,
    }
    diags, suppressed = lint(tmp_path, files, rules=["host-sync"])
    assert diags == []
    assert suppressed == 1


def test_suppression_without_reason_is_reported(tmp_path):
    files = {
        "engine/mod.py": """
            import jax

            @jax.jit
            def decode(x):
                n = x.item()  # jaxlint: disable=host-sync
                return n
        """,
    }
    diags, suppressed = lint(tmp_path, files, rules=["host-sync"])
    assert suppressed == 0
    assert rules_hit(diags) == ["bad-suppression", "host-sync"]


def test_standalone_suppression_covers_next_line(tmp_path):
    files = {
        "engine/mod.py": """
            import jax

            @jax.jit
            def decode(x):
                # jaxlint: disable=host-sync -- fixture: next-line form
                n = x.item()
                return n
        """,
    }
    diags, suppressed = lint(tmp_path, files, rules=["host-sync"])
    assert diags == []
    assert suppressed == 1


# -- tracer-branch -----------------------------------------------------------

def test_tracer_branch_positive_and_negative(tmp_path):
    files = {
        "ops/kernels.py": """
            import jax.numpy as jnp

            def bad(x):
                if jnp.any(x > 0):
                    return x
                return -x

            def good(x):
                if x.shape[0] > 1:
                    return x
                if x is None:
                    return None
                return -x
        """,
        "serving/host.py": """
            import jax.numpy as jnp

            def fine_here(x):
                # serving/ is host code: data-dependent branching is normal
                if jnp.any(x > 0):
                    return x
                return -x
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["tracer-branch"])
    assert len(diags) == 1
    assert diags[0].path.endswith("ops/kernels.py")
    assert diags[0].line == 5


def test_tracer_branch_while_and_reduction_method(tmp_path):
    files = {
        "parallel/ring.py": """
            def spin(x):
                while x.sum() > 0:
                    x = x - 1
                return x
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["tracer-branch"])
    assert len(diags) == 1
    assert "while" in diags[0].message


# -- donate-cache ------------------------------------------------------------

def test_donation_positive_negative_argnums(tmp_path):
    files = {
        "engine/mod.py": """
            import functools
            import jax

            @functools.partial(jax.jit, donate_argnames=("cache",))
            def good_names(tokens, cache):
                return tokens, cache

            @functools.partial(jax.jit, static_argnames=("n",))
            def bad(tokens, cache, *, n):
                return tokens, cache

            @jax.jit
            def no_cache_arg(tokens):
                return tokens

            def build():
                def body(shared, tokens, cache):
                    return tokens, cache
                shmapped = wrap(body)
                return jax.jit(shmapped, donate_argnums=(2,))

            def build_bad():
                def body(shared, tokens, cache):
                    return tokens, cache
                shmapped = wrap(body)
                return jax.jit(shmapped, donate_argnums=(1,))

            def wrap(f):
                return f
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["donate-cache"])
    assert len(diags) == 2
    assert {d.line for d in diags} == {10, 27}  # `bad` def, build_bad's jit


def test_donation_shared_pool_exception(tmp_path):
    """Block-level prefix sharing: a `shared_pool` param is a READ-ONLY
    mapped pool — the rule inverts: leaving it undonated is correct, and
    donating it (which would let XLA recycle buffers other block tables
    still read) is the flagged defect."""
    files = {
        "engine/mod.py": """
            import functools
            import jax

            @jax.jit
            def good_gather(shared_pool, table_row):
                return shared_pool

            @functools.partial(jax.jit, donate_argnames=("shared_pool",))
            def bad_gather(shared_pool, table_row):
                return shared_pool

            @jax.jit
            def still_bad_plain_pool(pool, table_row):
                return pool
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["donate-cache"])
    assert len(diags) == 2
    by_line = {d.line: d.message for d in diags}
    assert 10 in by_line and "must not be donated" in by_line[10]
    assert 14 in by_line and "does not donate" in by_line[14]


def test_donation_shared_pool_reasoned_suppression(tmp_path):
    """A donated shared_pool under a REASONED suppression is accepted;
    dropping the reason downgrades to the bad-suppression diagnostic —
    same contract as every other rule's escape hatch."""
    files = {
        "engine/mod.py": """
            import functools
            import jax

            @functools.partial(jax.jit, donate_argnames=("shared_pool",))
            # jaxlint: disable=donate-cache -- single-tenant pool: no other table maps these blocks
            def gather_private(shared_pool, table_row):
                return shared_pool
        """,
    }
    diags, suppressed = lint(tmp_path, files, rules=["donate-cache"])
    assert diags == []
    assert suppressed == 1
    files_bad = {
        "engine/mod.py": files["engine/mod.py"].replace(
            " -- single-tenant pool: no other table maps these blocks", ""
        ),
    }
    diags, _ = lint(tmp_path, files_bad, rules=["donate-cache"])
    assert any(d.rule == "bad-suppression" for d in diags)


# -- static-args -------------------------------------------------------------

def test_static_args_fstring_call_site(tmp_path):
    files = {
        "engine/mod.py": """
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("mode",))
            def run(x, *, mode):
                return x

            def bad_caller(x, name):
                return run(x, mode=f"m-{name}")

            def good_caller(x):
                return run(x, mode="fixed")
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["static-args"])
    assert len(diags) == 1
    assert diags[0].line == 10


def test_static_args_computed_names(tmp_path):
    files = {
        "engine/mod.py": """
            import functools
            import jax

            NAMES = ("mode",)

            @functools.partial(jax.jit, static_argnames=NAMES)
            def run(x, *, mode):
                return x
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["static-args"])
    assert len(diags) == 1
    assert "literal" in diags[0].message


# -- metrics-labels ----------------------------------------------------------

def test_metrics_labels_literal_and_cap(tmp_path):
    files = {
        "serving/mod.py": """
            def setup(registry, names):
                ok = registry.counter(
                    "dli_good_total", "fine", ("route", "status"),
                )
                computed = registry.counter(
                    "dli_computed_total", "bad", tuple(names),
                )
                wide = registry.gauge(
                    "dli_wide", "bad",
                    ("a", "b", "c", "d", "e"),
                )
                unlabeled = registry.counter("dli_plain_total", "fine")
                not_a_metric = registry.counter("requests", "no dli_ prefix")
                return ok, computed, wide, unlabeled, not_a_metric
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["metrics-labels"])
    assert len(diags) == 2
    msgs = " / ".join(d.message for d in diags)
    assert "dli_computed_total" in msgs and "dli_wide" in msgs


# -- route-counter -----------------------------------------------------------

def test_route_counter_rule(tmp_path):
    files = {
        "serving/srv.py": """
            class Handler:
                def _send(self, code):
                    self._count(code)
                    self.send_response(code)

                def good_stream(self):
                    self._count(200)
                    self.send_response(200)

                def bad_stream(self):
                    self.send_response(200)
        """,
        "engine/not_serving.py": """
            class Other:
                def whatever(self):
                    self.send_response(200)  # not serving/: out of scope
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["route-counter"])
    assert len(diags) == 1
    assert diags[0].line == 12
    assert "bad_stream" in diags[0].message


# -- thread-reach: thread-aware reachability (fixtures) ----------------------

THREAD_PKG = {
    "engine/mod.py": """
        import threading
        import jax
        import jax.numpy as jnp

        def worker():
            return jnp.sum(jnp.ones(3))

        def spawn():
            t = threading.Thread(target=worker, daemon=True)
            t.start()
            return t
    """,
}


def test_thread_reach_negative(tmp_path):
    diags, _ = lint(tmp_path, THREAD_PKG, rules=["thread-reach"])
    assert diags == []


def test_thread_reach_positive_traced_thread_target(tmp_path):
    files = dict(THREAD_PKG)
    files["engine/mod.py"] += """
        @jax.jit
        def decode(x):
            return worker() + x
    """
    diags, _ = lint(tmp_path, files, rules=["thread-reach"])
    assert len(diags) == 1
    assert "thread entry point" in diags[0].message
    assert "worker" in diags[0].message


def test_thread_reach_suppressed_with_reason(tmp_path):
    files = dict(THREAD_PKG)
    files["engine/mod.py"] = files["engine/mod.py"].replace(
        "t = threading.Thread(target=worker, daemon=True)",
        "t = threading.Thread(target=worker, daemon=True)"
        "  # jaxlint: disable=thread-reach -- fixture: eager-only helper",
    ) + """
        @jax.jit
        def decode(x):
            return worker() + x
    """
    diags, suppressed = lint(tmp_path, files, rules=["thread-reach"])
    assert diags == []
    assert suppressed == 1


def test_thread_reach_annotated_but_traced(tmp_path):
    files = {
        "engine/mod.py": """
            import jax
            import jax.numpy as jnp

            # jaxlint: decode-unreachable -- fixture: believed host-only
            def helper(x):
                return jnp.sum(x)

            @jax.jit
            def decode(x):
                return helper(x)
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["thread-reach"])
    assert len(diags) == 1
    assert "annotated decode-unreachable but IS reachable" in diags[0].message


def test_thread_reach_annotation_needs_reason(tmp_path):
    files = {
        "engine/mod.py": """
            # jaxlint: decode-unreachable
            def host_helper(x):
                return x
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["thread-reach"])
    assert len(diags) == 1
    assert "without a reason" in diags[0].message


def test_derived_reachability_on_fixture(tmp_path):
    """decode_unreachable() proves thread-spawned loops and their
    callees host-only, and keeps traced helpers out."""
    root = make_pkg(tmp_path, {
        "engine/mod.py": """
            import threading
            import time
            import jax
            import jax.numpy as jnp

            def hot(x):
                return jnp.sum(x)

            @jax.jit
            def decode(x):
                return hot(x)

            def loop_body():
                helper()

            def helper():
                time.sleep(0.01)

            def spawn():
                threading.Thread(target=loop_body, daemon=True).start()
        """,
    })
    index = build_index(root)
    derived = decode_unreachable(index)
    assert ("engine.mod", "loop_body") in derived
    assert ("engine.mod", "helper") in derived
    assert ("engine.mod", "hot") not in derived
    assert ("engine.mod", "decode") not in derived


# -- lock-order: acquisition-order inversions (fixtures) ---------------------

LOCK_ORDER_BAD = {
    "engine/locky.py": """
        import threading

        class A:
            def __init__(self):
                self.l1 = threading.Lock()
                self.l2 = threading.Lock()

            def forward(self):
                with self.l1:
                    with self.l2:
                        return 1

            def backward(self):
                with self.l2:
                    with self.l1:
                        return 2
    """,
}


def test_lock_order_inversion_flagged(tmp_path):
    diags, _ = lint(tmp_path, LOCK_ORDER_BAD, rules=["lock-order"])
    assert len(diags) == 2, diags  # both edges of the cycle
    assert all("inversion" in d.message for d in diags)
    assert {d.line for d in diags} == {11, 16}


def test_lock_order_consistent_is_clean(tmp_path):
    files = {
        "engine/locky.py": LOCK_ORDER_BAD["engine/locky.py"].replace(
            "with self.l2:\n                    with self.l1:",
            "with self.l1:\n                    with self.l2:",
        ),
    }
    diags, _ = lint(tmp_path, files, rules=["lock-order"])
    assert diags == []


def test_lock_order_inversion_through_a_call(tmp_path):
    """The deadlock shape that spans functions: forward holds l1 and
    CALLS a helper that takes l2; backward nests them the other way."""
    files = {
        "engine/locky.py": """
            import threading

            class A:
                def __init__(self):
                    self.l1 = threading.Lock()
                    self.l2 = threading.Lock()

                def forward(self):
                    with self.l1:
                        return self.helper()

                def helper(self):
                    with self.l2:
                        return 1

                def backward(self):
                    with self.l2:
                        with self.l1:
                            return 2
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["lock-order"])
    assert len(diags) == 2, diags
    assert {d.line for d in diags} == {11, 19}


def test_lock_order_suppressed_with_reason(tmp_path):
    files = {
        "engine/locky.py": LOCK_ORDER_BAD["engine/locky.py"]
        .replace(
            "with self.l2:\n                        return 1",
            "with self.l2:"
            "  # jaxlint: disable=lock-order -- fixture: A-then-B is canon\n"
            "                        return 1",
        )
        .replace(
            "with self.l1:\n                        return 2",
            "with self.l1:"
            "  # jaxlint: disable=lock-order -- fixture: migration window\n"
            "                        return 2",
        ),
    }
    diags, suppressed = lint(tmp_path, files, rules=["lock-order"])
    assert diags == []
    assert suppressed == 2


# -- blocking-under-lock (fixtures) ------------------------------------------

BLOCKING_PKG = {
    "serving/q.py": """
        import threading
        import time
        import urllib.request

        class Q:
            def __init__(self):
                self._cv = threading.Condition()

            def bad_sleep(self):
                with self._cv:
                    time.sleep(0.1)

            def ok_sleep_outside(self):
                time.sleep(0.1)
                with self._cv:
                    return 1

            def ok_wait_on_held(self):
                with self._cv:
                    self._cv.wait(timeout=0.1)

            def fetch(self):
                return urllib.request.urlopen("http://peer/ready")

            def bad_transitive(self):
                with self._cv:
                    return self.fetch()
    """,
}


def test_blocking_under_lock_catalog(tmp_path):
    diags, _ = lint(tmp_path, BLOCKING_PKG, rules=["blocking-under-lock"])
    assert len(diags) == 2, diags
    by_line = {d.line: d.message for d in diags}
    assert 12 in by_line and "time.sleep" in by_line[12]
    assert 28 in by_line and "fetch" in by_line[28]  # transitive call


def test_blocking_under_lock_queue_put_and_join(tmp_path):
    files = {
        "serving/q.py": """
            import threading

            class Q:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = None
                    self._t = None

                def bad_put(self, x):
                    with self._lock:
                        self._q.put(x, block=True)

                def ok_put_nowait(self, x):
                    with self._lock:
                        self._q.put_nowait(x)

                def bad_join(self):
                    with self._lock:
                        self._t.join()
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["blocking-under-lock"])
    assert len(diags) == 2, diags
    msgs = " / ".join(d.message for d in diags)
    assert "block=True" in msgs and ".join()" in msgs


def test_blocking_under_lock_suppressed(tmp_path):
    files = {
        "serving/q.py": BLOCKING_PKG["serving/q.py"].replace(
            "time.sleep(0.1)\n\n            def ok_sleep_outside",
            "time.sleep(0.1)"
            "  # jaxlint: disable=blocking-under-lock -- fixture: test-only pacing\n"
            "\n            def ok_sleep_outside",
        ).replace(
            "return self.fetch()",
            "return self.fetch()"
            "  # jaxlint: disable=blocking-under-lock -- fixture: startup path, single-threaded",
        ),
    }
    diags, suppressed = lint(tmp_path, files, rules=["blocking-under-lock"])
    assert diags == []
    assert suppressed == 2


# -- guarded-by (fixtures) ---------------------------------------------------

GUARDED_PKG = {
    "engine/state.py": """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.depth = 0  # guarded-by: _lock

            def good(self):
                with self._lock:
                    self.depth = 1

            def bad(self):
                self.depth = 2

            # guarded-by: _lock
            def _bump_locked(self):
                self.depth += 1

            def caller_bad(self):
                self._bump_locked()

            def caller_good(self):
                with self._lock:
                    self._bump_locked()
    """,
}


def test_guarded_by_write_and_call_violations(tmp_path):
    diags, _ = lint(tmp_path, GUARDED_PKG, rules=["guarded-by"])
    assert len(diags) == 2, diags
    by_line = {d.line: d.message for d in diags}
    assert 14 in by_line and "outside its declared lock" in by_line[14]
    assert 21 in by_line and "without holding" in by_line[21]


def test_guarded_by_init_exempt_and_subscript_write(tmp_path):
    files = {
        "engine/state.py": """
            import threading

            class S:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.table = {}  # guarded-by: _lock
                    self.table = {"seed": 1}  # __init__ is pre-sharing

                def good(self, k, v):
                    with self._lock:
                        self.table[k] = v

                def bad(self, k, v):
                    self.table[k] = v
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["guarded-by"])
    assert len(diags) == 1
    assert diags[0].line == 15


def test_guarded_by_suppressed_with_reason(tmp_path):
    files = {
        "engine/state.py": GUARDED_PKG["engine/state.py"].replace(
            "self.depth = 2",
            "self.depth = 2"
            "  # jaxlint: disable=guarded-by -- fixture: single-threaded setup phase",
        ).replace(
            "def caller_bad(self):\n                self._bump_locked()",
            "def caller_bad(self):\n                self._bump_locked()"
            "  # jaxlint: disable=guarded-by -- fixture: lock held by caller's caller",
        ),
    }
    diags, suppressed = lint(tmp_path, files, rules=["guarded-by"])
    assert diags == []
    assert suppressed == 2


# -- resource-lifecycle (fixtures) -------------------------------------------

PR4_LEAK_PKG = {
    "engine/admission.py": """
        _BLOCKED = object()

        class Admission:
            def __init__(self, alloc, ctable):
                self._alloc = alloc
                self._ctable = ctable

            def admit(self, req):
                blocks = self._alloc.alloc(req.need)
                if blocks is None:
                    return _BLOCKED
                off = self._ctable.acquire(req.cart)
                if off is None:
                    return _BLOCKED
                req.block_ids = blocks
                req.cart = (req.cart, off)
                return req
    """,
}


def test_lifecycle_catches_pr4_blocked_leak(tmp_path):
    """The exact PR-4 shape: blocks granted, a LATER acquisition
    backpressures, and the retry sentinel returns without decref'ing
    what is already held."""
    diags, _ = lint(tmp_path, PR4_LEAK_PKG, rules=["resource-lifecycle"])
    assert len(diags) == 1, diags
    assert diags[0].line == 15
    assert "blocks" in diags[0].message and "alloc" in diags[0].message


def test_lifecycle_release_on_every_path_is_clean(tmp_path):
    files = {
        "engine/admission.py": PR4_LEAK_PKG["engine/admission.py"].replace(
            "if off is None:\n                    return _BLOCKED",
            "if off is None:\n"
            "                    self._alloc.decref(blocks)\n"
            "                    return _BLOCKED",
        ),
    }
    diags, _ = lint(tmp_path, files, rules=["resource-lifecycle"])
    assert diags == []


def test_lifecycle_incref_and_finally_and_transfer(tmp_path):
    files = {
        "engine/admission.py": """
            class A:
                def leak_incref(self, shared, cond):
                    self._alloc.incref(shared)
                    if cond:
                        return None
                    self._alloc.decref(shared)
                    return 1

                def ok_finally(self, req):
                    blocks = self._alloc.alloc(req.need)
                    if blocks is None:
                        return None
                    try:
                        if req.bad:
                            return None
                        return blocks
                    finally:
                        self._alloc.decref(blocks)

                def ok_transfer(self, req):
                    blocks = self._alloc.alloc(req.need)
                    if blocks is None:
                        return None
                    req.block_ids = blocks
                    if req.fast:
                        return req
                    return req
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["resource-lifecycle"])
    assert len(diags) == 1, diags
    assert diags[0].line == 6
    assert "shared" in diags[0].message


def test_lifecycle_ownership_transfer_suppression(tmp_path):
    files = {
        "engine/admission.py": """
            class A:
                def handoff(self, pool):
                    blocks = pool.alloc(4)
                    if blocks is None:
                        return None
                    self.enqueue(blocks)
                    return True  # jaxlint: disable=resource-lifecycle -- ownership moved to the enqueue consumer
        """,
    }
    diags, suppressed = lint(
        tmp_path, files, rules=["resource-lifecycle"]
    )
    assert diags == []
    assert suppressed == 1


# -- join-hygiene (fixtures) -------------------------------------------------

def test_join_hygiene_non_daemon_without_join(tmp_path):
    files = {
        "serving/w.py": """
            import threading

            class W:
                def start(self):
                    self._t = threading.Thread(target=self._run)
                    self._t.start()

                def _run(self):
                    pass
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["join-hygiene"])
    assert len(diags) == 1
    assert "no join(timeout=...)" in diags[0].message


def test_join_hygiene_bounded_join_or_daemon_is_clean(tmp_path):
    files = {
        "serving/w.py": """
            import threading

            class W:
                def start(self):
                    self._t = threading.Thread(target=self._run)
                    self._t.start()
                    self._d = threading.Thread(target=self._run, daemon=True)
                    self._d.start()

                def close(self):
                    self._t.join(timeout=5)

                def _run(self):
                    pass
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["join-hygiene"])
    assert diags == []


def test_join_hygiene_unbounded_join_flagged(tmp_path):
    """The PR-9 follower-wedge shape: the drain path joins without a
    timeout, so one wedged thread holds shutdown hostage."""
    files = {
        "serving/w.py": """
            import threading

            class W:
                def start(self):
                    self._t = threading.Thread(target=self._run)
                    self._t.start()

                def close(self):
                    self._t.join()

                def _run(self):
                    pass
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["join-hygiene"])
    assert len(diags) == 2, diags
    msgs = " / ".join(d.message for d in diags)
    assert "UNBOUNDED" in msgs and "unbounded .join()" in msgs


def test_join_hygiene_suppressed(tmp_path):
    files = {
        "serving/w.py": """
            import threading

            class W:
                def start(self):
                    # jaxlint: disable=join-hygiene -- fixture: process-lifetime thread, reaped by exit
                    self._t = threading.Thread(target=self._run)
                    self._t.start()

                def _run(self):
                    pass
        """,
    }
    diags, suppressed = lint(tmp_path, files, rules=["join-hygiene"])
    assert diags == []
    assert suppressed == 1


# -- call-graph units on the REAL package ------------------------------------

@pytest.fixture(scope="module")
def real_reachable():
    index = build_index(PKG_ROOT)
    return traced_reachable(index)


def test_real_traced_set_includes_hot_path(real_reachable):
    for key in [
        ("engine.generate", "decode"),
        ("engine.generate", "stop_mask"),
        ("engine.generate", "slot_step"),
        ("ops.sampling", "sample_token"),
        ("ops.sampling", "_sample_warped"),
        ("models.api", "forward_layers"),
        ("models.llama", "forward_layers"),
        ("models.gpt2", "forward_layers"),  # family-dispatch fan-out
        ("ops.attention", "attend"),
        ("engine.paged", "make_paged_hook.hook"),  # nested closure
    ]:
        assert key in real_reachable, key


def test_real_traced_set_excludes_host_code(real_reachable):
    for key in [
        ("engine.generate", "pick_bucket"),  # host-side bucket picker
        ("engine.engine", "InferenceEngine.generate"),
        ("serving.server", "main"),
        ("utils.metrics", "MetricsRegistry.render"),
    ]:
        assert key not in real_reachable, key


# -- DERIVED thread-aware reachability (replaces the per-PR manual pin
# fixtures that grew here in PRs 5-11) --------------------------------------

@pytest.fixture(scope="module")
def real_index():
    return build_index(PKG_ROOT)


@pytest.fixture(scope="module")
def real_derived(real_index, real_reachable):
    return decode_unreachable(real_index, real_reachable)


# What this file used to assert by hand, pin by pin, PR by PR. Whole
# modules are enumerated at test time (so functions ADDED to a pinned
# module stay covered); the explicit keys are the exact pins the old
# fixtures carried. The derivation (host roots -> closure, minus the
# traced set, plus the annotated escape hatch) must prove ALL of it.
OLD_PIN_MODULES = (
    "utils.faults", "engine.shadow", "engine.scheduler",
    "serving.router", "utils.retry", "serving.kv_fabric",
)
OLD_PIN_FUNCS = [
    ("engine.continuous", "ContinuousEngine._launch_chunk"),
    ("engine.continuous", "ContinuousEngine._process"),
    ("engine.continuous", "ContinuousEngine._admit_one"),
    ("engine.continuous", "ContinuousEngine._supervise"),
    ("engine.continuous", "ContinuousEngine._run_recovery"),
    ("engine.engine", "InferenceEngine._generate_locked"),
    ("engine.continuous", "ContinuousEngine._shadow_capture"),
    ("engine.continuous", "ContinuousEngine._restore_shadow"),
    ("engine.continuous", "ContinuousEngine._preempt_for"),
    ("engine.continuous", "ContinuousEngine._victim_for"),
    ("engine.continuous", "ContinuousEngine._alloc_with_pressure"),
    ("engine.continuous", "ContinuousEngine._prepare_resume"),
    ("engine.continuous", "ContinuousEngine._cancel_env"),
    ("engine.continuous", "ContinuousEngine._deadline_env"),
    ("engine.continuous", "ContinuousEngine._past_deadline"),
    ("engine.scheduler", "TokenBudgetScheduler.select_victim"),
    ("engine.scheduler", "TokenBudgetScheduler.victim_key"),
    ("engine.paged", "build_ragged_meta"),
    ("engine.continuous", "ContinuousEngine._ragged_ingest"),
    ("engine.continuous", "ContinuousEngine._ragged_launch_args"),
    ("engine.continuous", "ContinuousEngine._launch_mixed"),
    ("engine.continuous", "ContinuousEngine._process_mixed"),
    ("engine.continuous", "ContinuousEngine._start_job"),
    ("engine.continuous", "ContinuousEngine._sched_loop"),
    ("engine.continuous", "ContinuousEngine._fabric_prefetch"),
    ("engine.continuous", "ContinuousEngine._import_fabric_chain"),
    ("engine.continuous", "ContinuousEngine.fabric_chain"),
    ("engine.continuous", "ContinuousEngine.fabric_digests"),
]


def test_derived_reachability_supersets_old_pins(real_index, real_derived):
    """The thread-aware derivation proves (at least) everything the old
    manual pin list asserted — the acceptance criterion that let the
    pins be deleted. A miss here means a host root went undetected
    (new spawn idiom?) or a helper lost its last host-side caller:
    either derive it or annotate it `# jaxlint: decode-unreachable`."""
    missing = [k for k in OLD_PIN_FUNCS if k not in real_derived]
    assert not missing, missing
    for mod_name in OLD_PIN_MODULES:
        funcs = [
            f.key for f in real_index.modules[mod_name].functions.values()
        ]
        missing = [k for k in funcs if k not in real_derived]
        assert not missing, (mod_name, missing)


def test_derived_set_disjoint_from_traced(real_derived, real_reachable):
    """Soundness: nothing the derivation (or an annotation) calls
    host-only may be reachable from a jit root. The thread-reach rule
    enforces the annotated half in CI; this is the belt to that
    suspender, over the whole derived set."""
    overlap = sorted(real_derived & real_reachable)
    assert not overlap, overlap


def test_thread_roots_cover_the_control_plane_loops(real_index):
    """The spawn-edge detector sees every long-lived control-plane
    thread this repo starts — supervisor loop, shadow copier, queue
    dispatcher, router prober, deadline-abandonment runner."""
    roots = thread_roots(real_index)
    for key in [
        ("engine.continuous", "ContinuousEngine._loop"),
        ("engine.shadow", "ShadowStore._copier"),
        ("serving.queue", "BatchingQueue._dispatch_loop"),
        ("serving.router", "Router.start_prober._loop"),
        ("engine.engine", "InferenceEngine._with_deadline.run"),
        ("serving.multihost", "MirroredEngine.shutdown_followers._bcast"),
    ]:
        assert key in roots, key


def test_traced_halves_stay_reachable(real_reachable):
    """The derivation must not swallow the TRACED halves of the paged
    path: the ragged fill closure and the mixed epilogue execute inside
    compiled programs, and the host-sync rule audits them only while
    they stay in the traced set."""
    assert ("engine.paged", "make_ragged_fill_hook.hook") in real_reachable
    assert ("engine.paged", "mixed_epilogue") in real_reachable


def test_repo_is_clean():
    """The package itself lints clean — the same gate CI runs."""
    diags, _ = run_lint(PKG_ROOT)
    assert diags == [], "\n".join(d.format() for d in diags)


# -- CLI exit contract (acceptance criterion) --------------------------------

def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "distributed_llm_inference_tpu.analysis",
         *args],
        capture_output=True, text=True,
        cwd=os.path.dirname(PKG_ROOT),
    )


def test_cli_clean_repo_exits_zero():
    r = _run_cli()
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_item_in_decode_reachable_function_exits_nonzero(tmp_path):
    """A `.item()` injected into a decode-reachable function must fail the
    CLI with a file:line diagnostic."""
    import shutil

    bad_root = str(tmp_path / "pkg_with_item")
    shutil.copytree(PKG_ROOT, bad_root, ignore=shutil.ignore_patterns(
        "__pycache__", "*.pyc"
    ))
    gen = os.path.join(bad_root, "engine", "generate.py")
    with open(gen) as fh:
        src = fh.read()
    needle = "    m = tokens == jnp.int32(cfg.eos_token_id)"
    assert needle in src
    with open(gen, "w") as fh:
        fh.write(src.replace(
            needle, "    _bad = tokens.item()\n" + needle
        ))
    r = _run_cli("--root", bad_root)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "host-sync" in r.stdout
    # file:line diagnostics
    assert "generate.py:" in r.stdout and ".item()" in r.stdout


_SEEDED_VIOLATIONS = {
    "lock-order": """
        import threading

        class A:
            def __init__(self):
                self.l1 = threading.Lock()
                self.l2 = threading.Lock()

            def forward(self):
                with self.l1:
                    with self.l2:
                        return 1

            def backward(self):
                with self.l2:
                    with self.l1:
                        return 2
    """,
    "blocking-under-lock": """
        import threading
        import time

        class Q:
            def __init__(self):
                self._lock = threading.Lock()

            def tick(self):
                with self._lock:
                    time.sleep(0.5)
    """,
    "guarded-by": """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.depth = 0  # guarded-by: _lock

            def bump(self):
                self.depth += 1
    """,
    "resource-lifecycle": """
        _BLOCKED = object()

        class Admission:
            def admit(self, req):
                blocks = self._alloc.alloc(req.need)
                if blocks is None:
                    return _BLOCKED
                off = self._ctable.acquire(req.cart)
                if off is None:
                    return _BLOCKED
                req.block_ids = blocks
                req.cart = (req.cart, off)
                return req
    """,
}


@pytest.mark.parametrize("rule", sorted(_SEEDED_VIOLATIONS))
def test_cli_seeded_violation_fixtures_exit_nonzero(tmp_path, rule):
    """The acceptance contract for the host-control-plane rules: a
    seeded violation of each family (lock inversion, blocking call
    under a lock, guarded-by write, the PR-4 refcount leak) fails the
    CLI with a file:line diagnostic naming the rule."""
    root = make_pkg(tmp_path, {
        "engine/seeded.py": _SEEDED_VIOLATIONS[rule],
    })
    r = _run_cli("--root", root)
    assert r.returncode == 1, r.stdout + r.stderr
    assert rule in r.stdout
    assert "seeded.py:" in r.stdout


# -- compiled-artifact (HLO) assertions --------------------------------------

@pytest.fixture(scope="module")
def engine():
    return hlo.tiny_engine()


def test_solo_decode_artifact(engine):
    text = hlo.lower_solo_decode(engine)
    assert hlo.check_no_host_callbacks(text) == []
    assert hlo.check_while_compiled(text) == []
    cache = engine.backend.init_cache(1, engine.cfg.max_seq_len)
    n_leaves = hlo.count_cache_leaves(cache)
    assert hlo.check_donation(text, min_aliased=n_leaves) == []


def test_constrained_decode_artifact(engine):
    text = hlo.lower_solo_decode(engine, constrained=True)
    assert hlo.check_no_host_callbacks(text) == []
    assert hlo.check_while_compiled(text) == []


def test_donation_checker_catches_dropped_donation(engine):
    """check_donation must FAIL on a re-wrap that drops donate_argnames —
    the exact silent regression it exists to catch."""
    import jax as _jax
    import jax.numpy as jnp

    from distributed_llm_inference_tpu.engine import generate as G

    cfg = engine.cfg
    cache = engine.backend.init_cache(1, cfg.max_seq_len)
    undonated = _jax.jit(
        G.decode, static_argnames=("cfg", "max_steps"),
    ).lower(
        cfg, engine.backend.params, jnp.zeros((1,), jnp.int32), cache,
        jnp.int32(4), jnp.int32(8), _jax.random.PRNGKey(0),
        G.default_sampling(greedy=True), None, None, None, None, None,
        max_steps=16,
    ).as_text()
    assert hlo.check_donation(undonated, min_aliased=1) != []


def test_callback_checker_catches_injected_callback(engine):
    """check_no_host_callbacks must FAIL on a program that really does
    call back into Python per step."""
    import jax as _jax
    import jax.numpy as jnp

    def with_callback(x):
        _jax.debug.print("step {}", x)
        return x * 2

    text = _jax.jit(with_callback).lower(jnp.ones((4,))).as_text()
    assert hlo.check_no_host_callbacks(text) != []


def test_recompile_guard(engine):
    assert hlo.check_no_recompile(engine) == []


def test_run_hlo_checks_all_green():
    results = hlo.run_hlo_checks()
    bad = {k: v for k, v in results.items() if v}
    assert not bad, bad


@needs_shard_map
def test_pp_decode_artifact(eight_devices):
    if not hlo.pp_available():
        pytest.skip("pp HLO check needs >= 2 devices")
    text = hlo.lower_pp_decode()
    assert hlo.check_no_host_callbacks(text) == []
    assert hlo.check_pp_ring(text) == []
