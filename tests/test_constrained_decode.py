"""Grammar-constrained decoding through the engine (single device, fast
tier): solo + batched property tests (greedy AND sampled output always
satisfies the constraint, judged by the independent Python re / json
oracle), composition with the other sampling features, the 400 surface for
unsupported combos, and the zero-Python-per-token guarantee (no host
callbacks in the compiled constrained program).
"""

import json
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_inference_tpu import EngineConfig, get_model_config
from distributed_llm_inference_tpu.engine import generate as G
from distributed_llm_inference_tpu.engine.engine import InferenceEngine


@pytest.fixture(scope="module")
def engine():
    cfg = get_model_config("test-llama-tiny")
    return InferenceEngine(cfg, engine_cfg=EngineConfig(prefill_buckets=(32, 64)))


CASES = [
    ({"regex": r"(red|green|blue)"},
     lambda t: re.fullmatch(r"(red|green|blue)", t)),
    ({"regex": r"[0-9]{2,4}"}, lambda t: re.fullmatch(r"[0-9]{2,4}", t)),
    ({"choices": ["alpha", "beta", "alphabet"]},
     lambda t: t in ("alpha", "beta", "alphabet")),
    ({"json_schema": {"type": "object",
                      "properties": {"name": {"type": "string"},
                                     "age": {"type": "integer"}},
                      "required": ["name", "age"]}},
     lambda t: isinstance(json.loads(t)["age"], int)),
]


@pytest.mark.parametrize("spec,check", CASES)
def test_solo_constrained_greedy_and_sampled(engine, spec, check):
    for kw in (dict(greedy=True), dict(temperature=1.5, top_k=0, top_p=1.0,
                                       seed=3)):
        r = engine.generate("the answer:", max_tokens=120, chat=False,
                            constraint=spec, **kw)
        assert r["status"] == "success", r
        assert r.get("constrained") is True
        assert check(r["response"]), (spec, r["response"])
        # the constraint completed inside the budget: finish_reason stop
        # (EOS forced at the accept state), never a length truncation
        assert r["finish_reason"] == "stop", r


def test_solo_sampled_many_seeds(engine):
    """Property: across many sampled draws, output ALWAYS matches."""
    pat = r"-?(0|[1-9][0-9]{0,2})(\.[0-9])?"
    for seed in range(6):
        r = engine.generate("n:", max_tokens=40, chat=False, seed=seed,
                            temperature=2.0, top_k=0, top_p=1.0,
                            constraint={"regex": pat})
        assert re.fullmatch(pat, r["response"]), r["response"]


def test_batched_constrained(engine):
    pat = r"(yes|no|maybe)"
    r = engine.generate_batch(
        ["q1:", "a much longer second prompt row", "q3:"],
        max_tokens=20, greedy=True, chat=False, constraint={"regex": pat},
    )
    assert r["status"] == "success", r
    assert r.get("constrained") is True
    for e in r["results"]:
        assert re.fullmatch(pat, e["response"]), e


def test_batched_constrained_sampled(engine):
    pat = r"[ab]{1,6}!"
    r = engine.generate_batch(
        ["x", "y"], max_tokens=20, temperature=1.7, top_k=0, top_p=1.0,
        seed=11, chat=False, constraint={"regex": pat},
    )
    for e in r["results"]:
        assert re.fullmatch(pat, e["response"]), e


def test_constraint_composes_with_penalties_and_bias(engine):
    """The mask stacks on top of logit_bias + penalties: a +100 bias on a
    banned token must NOT resurrect it."""
    banned = ord("c") + 3  # ByteTokenizer id for 'c'
    r = engine.generate(
        "go:", max_tokens=30, greedy=True, chat=False,
        constraint={"regex": "(ab|cd)"},
        logit_bias={banned: 100.0},
    )
    # 'c' carries +100 raw bias, so under the mask the only question is
    # whether cd (allowed) wins — either way the output matches
    assert re.fullmatch("ab|cd", r["response"]), r
    r2 = engine.generate(
        "go:", max_tokens=60, greedy=True, chat=False,
        repetition_penalty=1.3, frequency_penalty=0.5,
        constraint={"regex": "[ab]{1,8}"},
    )
    assert re.fullmatch("[ab]{1,8}", r2["response"]), r2


def test_constraint_with_textual_stop_chunks(engine):
    """stop strings route through the chunked decode path; the host-side
    FSM re-walk between chunks must keep the mask exact."""
    pat = "[0-9]{1,12}"
    r = engine.generate(
        "n:", max_tokens=25, greedy=True, chat=False,
        constraint={"regex": pat}, stop=["zzz-never-matches"],
    )
    assert r["status"] == "success"
    assert re.fullmatch(pat, r["response"]), r


def test_constraint_with_logprobs(engine):
    r = engine.generate(
        "pick:", max_tokens=20, greedy=True, chat=False, logprobs=True,
        constraint={"choices": ["on", "off"]},
    )
    assert r["response"] in ("on", "off")
    assert len(r["token_logprobs"]) == len(r["response"])  # byte tokenizer


def test_unsupported_combos_reject(engine):
    r = engine.generate("x", constraint={"regex": "a"}, num_beams=2)
    assert r["status"] == "failed" and r["error_type"] == "invalid_request"
    r = engine.generate("x", constraint={"regex": "a"}, speculative=True,
                        greedy=True)
    assert r["status"] == "failed" and r["error_type"] == "invalid_request"


def test_malformed_constraints_reject(engine):
    for bad in ({"bogus": 1}, {"regex": ""}, {"regex": "("},
                {"choices": []}, {"json_schema": {"type": "tuple"}},
                {"regex": "a", "choices": ["b"]}):
        r = engine.generate("x", constraint=bad)
        assert r["status"] == "failed", bad
        assert r["error_type"] == "invalid_request", (bad, r)


def test_artifact_cache_reuse(engine):
    spec = {"regex": "cache(d|r)"}
    engine.generate("x", max_tokens=15, greedy=True, chat=False,
                    constraint=spec)
    n = len(engine._constraint_cache)
    engine.generate("y", max_tokens=15, greedy=True, chat=False,
                    constraint=spec)
    assert len(engine._constraint_cache) == n  # hash hit, no recompile


def test_constrained_decode_has_no_host_callbacks(engine):
    """Acceptance: the constrained decode loop stays zero-Python-per-token
    — the lowered program contains no host callback custom-calls. The
    assertions live in the shared checker (analysis/hlo.py, the CI gate);
    this test pins them to THIS module's engine fixture. Lowering goes
    through the real jitted G.decode, so the donation aliasing check runs
    here too (the old ad-hoc re-wrap silently dropped donate_argnames)."""
    from distributed_llm_inference_tpu.analysis import hlo

    text = hlo.lower_solo_decode(engine, constrained=True)
    assert hlo.check_no_host_callbacks(text) == []
    assert hlo.check_while_compiled(text) == []  # the loop really is compiled
    cache = engine.backend.init_cache(1, engine.cfg.max_seq_len)
    assert hlo.check_donation(
        text, min_aliased=hlo.count_cache_leaves(cache)
    ) == []


def test_unconstrained_loop_carry_unchanged(engine):
    """constraint=None traces the SAME loop carry as before the feature
    (no dummy fsm rides unconstrained programs): the lowered while-loop
    carries one fewer tensor than the constrained variant."""
    cfg = engine.cfg

    def n_carry(constraint):
        cache = engine.backend.init_cache(1, cfg.max_seq_len)
        lowered = jax.jit(
            G.decode, static_argnames=("cfg", "max_steps"),
        ).lower(
            cfg, engine.backend.params, jnp.zeros((1,), jnp.int32), cache,
            jnp.int32(4), jnp.int32(8), jax.random.PRNGKey(0),
            G.default_sampling(greedy=True),
            None, None, None, None, constraint,
            max_steps=16,
        )
        import re as _re

        # count the while op's carry arity in the stablehlo text
        m = _re.search(r"stablehlo\.while", lowered.as_text())
        return lowered.as_text().count("stablehlo.while"), m is not None

    art = engine._compile_constraint({"regex": "[ab]{1,8}"})
    cm, ct = art.device_tables()
    un = jax.jit(G.decode, static_argnames=("cfg", "max_steps")).lower(
        cfg, engine.backend.params, jnp.zeros((1,), jnp.int32),
        engine.backend.init_cache(1, cfg.max_seq_len),
        jnp.int32(4), jnp.int32(8), jax.random.PRNGKey(0),
        G.default_sampling(greedy=True), None, None, None, None, None,
        max_steps=16,
    ).as_text()
    con = jax.jit(G.decode, static_argnames=("cfg", "max_steps")).lower(
        cfg, engine.backend.params, jnp.zeros((1,), jnp.int32),
        engine.backend.init_cache(1, cfg.max_seq_len),
        jnp.int32(4), jnp.int32(8), jax.random.PRNGKey(0),
        G.default_sampling(greedy=True), None, None, None, None,
        (jnp.zeros((1,), jnp.int32), cm, ct),
        max_steps=16,
    ).as_text()
    # the constrained trace gathers from the [S, V] tables; the
    # unconstrained trace must not even mention their shape
    S = art.num_states
    assert f"{S}x{cfg.vocab_size}" in con
    assert f"{S}x{cfg.vocab_size}" not in un


def test_decode_slots_constrained_matches_plain_when_free(engine):
    """Device-level: with every slot at the FREE state (row 0), the
    constrained slot program emits exactly what plain decode_slots emits —
    the free row really is a no-op."""
    cfg = engine.cfg
    backend = engine.backend
    sampling = G.default_sampling(greedy=True)
    key = jax.random.PRNGKey(7)
    tokens = jnp.asarray(
        [[cfg.bos_token_id, 11, 12, 13, 14, 15, 16, 17]], jnp.int32
    )
    tokens = jnp.pad(tokens, ((0, 0), (0, 24)), constant_values=cfg.pad_token_id)
    plen = jnp.int32(8)

    def arm(cache, state, sparams, first):
        return G.insert_slot(
            cfg, cache, scratch, state, sparams, 1, first[0], plen,
            jnp.int32(9),
            jnp.float32(1.0), jnp.int32(0), jnp.float32(1.0), jnp.bool_(True),
            jnp.float32(0.0), jnp.float32(1.0),
            jnp.float32(0.0), jnp.float32(0.0),
            jnp.zeros((cfg.vocab_size,), bool),
        )

    outs = []
    for constrained in (False, True):
        cache = backend.init_cache(2, cfg.max_seq_len)
        state, sparams = G.init_slots(2, cfg.vocab_size)
        scratch = backend.init_cache(1, cfg.max_seq_len)
        first, _, scratch = backend.prefill(tokens, plen, scratch, key, sampling)
        cache, state, sparams = arm(cache, state, sparams, first)
        if constrained:
            # free-state tables: 1 row, everything allowed, self-loop
            cm = jnp.ones((1, cfg.vocab_size), bool)
            ct = jnp.zeros((1, cfg.vocab_size), jnp.int32)
            fsm = jnp.zeros((2,), jnp.int32)
            emitted, mask, state, cache, fsm = backend.decode_slots_constrained(
                state, cache, key, sparams, fsm, cm, ct, num_steps=10
            )
            assert (np.asarray(fsm) == 0).all()
        else:
            emitted, mask, state, cache = backend.decode_slots(
                state, cache, key, sparams, num_steps=10
            )
        emitted, mask = np.asarray(emitted), np.asarray(mask)
        outs.append([int(t) for t in emitted[mask[:, 1], 1]])
    assert outs[0] == outs[1]
