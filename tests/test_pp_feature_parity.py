"""Full request surface on the pp mesh (round-2 review #3): scoring,
per-token logprobs, logit_bias, and beam search must be BIT-CONSISTENT
between the single-device backend and a pp=2 pipeline built from the same
params — the reference served its one feature set on its one topology
(/root/reference/orchestration.py:144-178); here every topology serves
everything.
"""

import numpy as np
import pytest

import jax

from distributed_llm_inference_tpu import EngineConfig, MeshConfig, create_engine, get_model_config
from distributed_llm_inference_tpu.engine.engine import InferenceEngine
from distributed_llm_inference_tpu.models import api as M

# fast-tier exclusion: pp-mesh compiles per feature; run the full suite (plain
# `pytest`) to include it
pytestmark = pytest.mark.slow


class _NumTok:
    """Lossless ids<->text ('12 7 9'), so token-exact comparisons survive
    decode round-trips."""

    def encode(self, text):
        return [int(t) % 250 + 3 for t in text.split()] or [3]

    def decode(self, toks, skip_special_tokens=True):
        return " ".join(str(int(t)) for t in toks)


@pytest.fixture(scope="module", params=["pipeline", "pipeline-1f1b"])
def engines(request):
    """(single-device, mesh) engine pair — parametrized over the plain pp
    ring AND the microbatched 1F1B backend (round-3 review #3: the full
    request surface on config 5's topology too; 1F1B dispatches these
    solo/variant calls to its inherited plain-ring programs)."""
    cfg = get_model_config("test-llama-tiny", eos_token_id=-1)
    params = M.init_params(cfg, jax.random.PRNGKey(5))
    ecfg = EngineConfig(prefill_buckets=(32, 64))
    sd = InferenceEngine(cfg, params=params, tokenizer=_NumTok(), engine_cfg=ecfg)
    mb = 2 if request.param == "pipeline-1f1b" else 1
    pp = create_engine(
        cfg, mesh_cfg=MeshConfig(pp=2), microbatches=mb, params=params,
        tokenizer=_NumTok(), engine_cfg=ecfg,
    )
    assert pp.backend.name == request.param
    return sd, pp


PROMPT = "12 44 91 7 33 5"


def test_score_bit_consistent(engines):
    sd, pp = engines
    a = sd.score(PROMPT, top_n=3)
    b = pp.score(PROMPT, top_n=3)
    assert a["status"] == b["status"] == "success"
    assert a["token_logprobs"][0] is None and b["token_logprobs"][0] is None
    np.testing.assert_allclose(
        a["token_logprobs"][1:], b["token_logprobs"][1:], rtol=0, atol=1e-6
    )
    for ta, tb in zip(a["top_logprobs"][1:], b["top_logprobs"][1:]):
        assert list(ta) == list(tb)


def test_logprobs_bit_consistent(engines):
    sd, pp = engines
    a = sd.generate(PROMPT, max_tokens=6, greedy=True, chat=False, logprobs=True)
    b = pp.generate(PROMPT, max_tokens=6, greedy=True, chat=False, logprobs=True)
    assert a["status"] == b["status"] == "success"
    assert a["response"] == b["response"]
    np.testing.assert_allclose(
        a["token_logprobs"], b["token_logprobs"], rtol=0, atol=1e-6
    )


def test_logit_bias_bit_consistent(engines):
    sd, pp = engines
    kw = dict(max_tokens=5, greedy=True, chat=False, logit_bias={"17": 100.0})
    a = sd.generate(PROMPT, **kw)
    b = pp.generate(PROMPT, **kw)
    assert a["status"] == b["status"] == "success"
    assert a["response"] == b["response"]
    # +100 bias under greedy forces the token every step
    assert set(a["response"].split()) == {"17"}


def test_logit_bias_sampled_consistent(engines):
    sd, pp = engines
    kw = dict(max_tokens=6, chat=False, temperature=0.8, seed=11,
              logit_bias={"29": 4.0, "41": -100.0})
    a = sd.generate(PROMPT, **kw)
    b = pp.generate(PROMPT, **kw)
    assert a["response"] == b["response"]
    assert "41" not in a["response"].split()


def test_beam_search_bit_consistent(engines):
    sd, pp = engines
    kw = dict(max_tokens=8, num_beams=3, chat=False)
    a = sd.generate(PROMPT, **kw)
    b = pp.generate(PROMPT, **kw)
    assert a["status"] == b["status"] == "success"
    assert a["response"] == b["response"]
    assert len(a["beams"]) == len(b["beams"]) == 3
    for ba, bb in zip(a["beams"], b["beams"]):
        assert ba["text"] == bb["text"]
        np.testing.assert_allclose(ba["score"], bb["score"], atol=1e-5)


def test_beam_count_on_fleet_granularity(engines):
    """num_beams == 2 lands exactly on the 1F1B backend's fleet
    granularity: the beam prefill must still seed from REAL logits (the
    engine prefills batch-1 and tiles — an [num_beams]-row prefill on the
    fleet path returned zero-width logits and crashed decode_beam's
    top_k; caught driving the HTTP surface, round 4)."""
    sd, pp = engines
    kw = dict(max_tokens=6, num_beams=2, chat=False)
    a = sd.generate(PROMPT, **kw)
    b = pp.generate(PROMPT, **kw)
    assert a["status"] == b["status"] == "success"
    assert a["response"] == b["response"]
    for ba, bb in zip(a["beams"], b["beams"]):
        assert ba["text"] == bb["text"]


def test_repetition_penalty_with_bias_pp(engines):
    """presence (repetition penalty) composes with bias on the pp mesh —
    the (pres, bias) program variant."""
    sd, pp = engines
    kw = dict(max_tokens=6, greedy=True, chat=False,
              repetition_penalty=1.3, logit_bias={"55": 2.5})
    a = sd.generate(PROMPT, **kw)
    b = pp.generate(PROMPT, **kw)
    assert a["response"] == b["response"]


def test_speculative_pp_matches_plain_greedy(engines):
    """Prompt-lookup speculation on the pp ring: every emitted token is
    still the argmax — exact vs plain greedy in fp32, and identical to
    the single-device speculative path."""
    sd, pp = engines
    plain = sd.generate(PROMPT, max_tokens=8, greedy=True, chat=False)
    a = sd.generate(PROMPT, max_tokens=8, greedy=True, chat=False,
                    speculative=True)
    b = pp.generate(PROMPT, max_tokens=8, greedy=True, chat=False,
                    speculative=True)
    assert a["response"] == plain["response"]
    assert b["response"] == plain["response"]


def test_draft_speculative_pp_matches_plain_greedy(engines):
    """Two-model draft speculation on the pp ring (replicated draft)."""
    import jax as _jax

    from distributed_llm_inference_tpu import get_model_config

    sd, pp = engines
    dcfg = get_model_config("test-llama-tiny", eos_token_id=-1)
    dparams = M.init_params(dcfg, _jax.random.PRNGKey(77))
    pp.set_draft(dcfg, dparams)
    try:
        plain = sd.generate(PROMPT, max_tokens=8, greedy=True, chat=False)
        r = pp.generate(PROMPT, max_tokens=8, greedy=True, chat=False,
                        speculative=True)
        assert r["status"] == "success"
        assert r["response"] == plain["response"]
    finally:
        pp._draft = None
        pp._draft_cache = None
