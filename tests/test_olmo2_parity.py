"""Logits parity: our JAX OLMo-2 vs a tiny-random HF Olmo2ForCausalLM.

OLMo-2 reorders the block: NO pre-sublayer norms — the residual adds
norm(sublayer(x)) (cfg.pre_norms=False, post_norms carries the weights)
— and RMSNorms q/k over the WHOLE projection before the head split
(cfg.qk_norm_dim="proj", weights [H*Dh] / [KV*Dh]).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")
pytest.importorskip("transformers.models.olmo2")

from distributed_llm_inference_tpu import EngineConfig, MeshConfig, get_model_config
from distributed_llm_inference_tpu.engine.engine import InferenceEngine
from distributed_llm_inference_tpu.models import llama
from distributed_llm_inference_tpu.models.convert import params_from_hf_model

# fast-tier exclusion: HF-parity family file; run the full suite (plain
# `pytest`) to include it
pytestmark = pytest.mark.slow


def _tiny_hf_olmo2(n_kv_heads=4):
    cfg = transformers.Olmo2Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4,
        num_key_value_heads=n_kv_heads, max_position_embeddings=128,
        rms_norm_eps=1e-6, rope_theta=500000.0,
        pad_token_id=0, eos_token_id=2, bos_token_id=1,
        attn_implementation="eager",
    )
    torch.manual_seed(23)
    model = transformers.Olmo2ForCausalLM(cfg)
    model.eval()
    return model


@pytest.mark.parametrize("n_kv_heads", [4, 2])
def test_olmo2_logits_match_hf(n_kv_heads):
    hf = _tiny_hf_olmo2(n_kv_heads)
    cfg, params = params_from_hf_model(hf, dtype="float32")
    assert not cfg.pre_norms and cfg.post_norms
    assert cfg.use_qk_norm and cfg.qk_norm_dim == "proj"
    assert "attn_norm" not in params["layers"]
    assert params["layers"]["q_norm"].shape == (3, 4 * cfg.head_dim)
    assert params["layers"]["k_norm"].shape == (3, n_kv_heads * cfg.head_dim)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(2, 17), dtype=np.int64)
    with torch.no_grad():
        hf_logits = hf(torch.from_numpy(tokens)).logits.numpy()
    cache = llama.init_kv_cache(cfg, batch=2, max_seq=32)
    logits, _ = llama.forward(
        cfg, params, jnp.asarray(tokens, jnp.int32), cache, jnp.int32(0)
    )
    np.testing.assert_allclose(np.asarray(logits), hf_logits,
                               rtol=2e-4, atol=2e-4)


def test_olmo2_decode_matches_hf_generate():
    from distributed_llm_inference_tpu.engine import generate as G

    hf = _tiny_hf_olmo2()
    cfg, params = params_from_hf_model(hf, dtype="float32")
    rng = np.random.default_rng(5)
    prompt_ids = rng.integers(3, cfg.vocab_size, size=8, dtype=np.int64)
    steps = 8
    with torch.no_grad():
        hf_out = hf.generate(
            torch.from_numpy(prompt_ids[None]), max_new_tokens=steps,
            do_sample=False, pad_token_id=0,
        )[0, len(prompt_ids):].numpy().tolist()
    if cfg.eos_token_id in hf_out:
        hf_out = hf_out[: hf_out.index(cfg.eos_token_id)]

    bucket = 16
    tokens = jnp.asarray(
        [prompt_ids.tolist() + [cfg.pad_token_id] * (bucket - len(prompt_ids))],
        jnp.int32,
    )
    plen = jnp.int32(len(prompt_ids))
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(0))
    cache = llama.init_kv_cache(cfg, 1, max_seq=64)
    first, _, cache = G.prefill(cfg, params, tokens, plen, cache, kp, sampling)
    out, n, _ = G.decode(
        cfg, params, first, cache, plen, jnp.int32(steps - 1), kd, sampling,
        max_steps=steps,
    )
    ours = [int(first[0])] + [int(t) for t in np.asarray(out[0][: int(n[0])])]
    if cfg.eos_token_id in ours:
        ours = ours[: ours.index(cfg.eos_token_id)]
    assert ours == hf_out


def test_olmo2_pipeline_pp_matches_single_device(eight_devices):
    """pp slices the post-norms + proj qk-norms with their layers
    bit-exactly (tp>1 is rejected for proj qk-norm — the norm statistic
    spans the whole projection)."""
    from distributed_llm_inference_tpu.engine import generate as G
    from distributed_llm_inference_tpu.models import api as M
    from distributed_llm_inference_tpu.parallel.mesh import build_mesh
    from distributed_llm_inference_tpu.parallel.partition import validate_mesh
    from distributed_llm_inference_tpu.parallel.pipeline import PipelineBackend

    cfg = get_model_config("test-olmo2-tiny")
    with pytest.raises(NotImplementedError, match="proj"):
        validate_mesh(cfg, pp=1, tp=2)

    params = M.init_params(cfg, jax.random.PRNGKey(0))
    ids = [5, 9, 13, 21, 8]
    bucket, steps = 16, 6
    tokens = jnp.asarray([ids + [cfg.pad_token_id] * (bucket - len(ids))], jnp.int32)
    plen = jnp.int32(len(ids))
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(3))

    cache_s = M.init_kv_cache(cfg, 1, max_seq=64)
    f_s, logits_s, cache_s = G.prefill(cfg, params, tokens, plen, cache_s, kp, sampling)
    out_s, n_s, _ = G.decode(
        cfg, params, f_s, cache_s, plen, jnp.int32(steps), kd, sampling,
        max_steps=steps,
    )

    mesh = build_mesh(MeshConfig(dp=1, pp=2, tp=1), eight_devices)
    pb = PipelineBackend(cfg, params, mesh)
    cache_p = pb.init_cache(1, 64)
    f_p, logits_p, cache_p = pb.prefill(tokens, plen, cache_p, kp, sampling)
    out_p, n_p, _ = pb.decode(
        f_p, cache_p, plen, jnp.int32(steps), kd, sampling, max_steps=steps
    )
    np.testing.assert_allclose(
        np.asarray(logits_p), np.asarray(logits_s), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out_s))


def test_olmo2_engine_smoke():
    eng = InferenceEngine(
        get_model_config("test-olmo2-tiny"),
        engine_cfg=EngineConfig(prefill_buckets=(32,)),
    )
    r = eng.generate("hello olmo", max_tokens=5, greedy=True)
    assert r["status"] == "success", r


# -- IBM Granite (llama structure + four scalar multipliers) ----------------


def test_granite_logits_match_hf():
    pytest.importorskip("transformers.models.granite")
    cfg_hf = transformers.GraniteConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=10000.0,
        embedding_multiplier=12.0, residual_multiplier=0.22,
        attention_multiplier=0.0156, logits_scaling=8.0,
        pad_token_id=0, eos_token_id=2, bos_token_id=1,
        attn_implementation="eager",
    )
    torch.manual_seed(29)
    hf = transformers.GraniteForCausalLM(cfg_hf)
    hf.eval()
    cfg, params = params_from_hf_model(hf, dtype="float32")
    assert cfg.embed_multiplier == 12.0
    assert cfg.residual_multiplier == 0.22
    assert cfg.attn_scale_override == 0.0156
    assert cfg.logits_divider == 8.0

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(2, 15), dtype=np.int64)
    with torch.no_grad():
        hf_logits = hf(torch.from_numpy(tokens)).logits.numpy()
    cache = llama.init_kv_cache(cfg, batch=2, max_seq=32)
    logits, _ = llama.forward(
        cfg, params, jnp.asarray(tokens, jnp.int32), cache, jnp.int32(0)
    )
    np.testing.assert_allclose(np.asarray(logits), hf_logits,
                               rtol=2e-4, atol=2e-4)
