"""Real-weights file path: HF save_pretrained dir -> our loader -> parity.

The reference's whole entry point is loading actual checkpoint weights
(/root/reference/orchestration.py:39, Worker1.py:60-65). Here the
round-trip is through FILES — save_pretrained(safe_serialization=True) →
our hand-rolled safetensors reader → stacked pytree — with logits parity
against the in-memory torch model, plus the conversion CLI into the local
checkpoint store.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from distributed_llm_inference_tpu.models import checkpoint, gpt2, llama
from distributed_llm_inference_tpu.models.convert import (
    load_hf_checkpoint,
    load_safetensors_dir,
    load_safetensors_file,
    main as convert_main,
    params_from_hf_model,
)


def _tiny_hf_llama(tmp_path, qkv_bias=False):
    cfg = transformers.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
        attention_bias=qkv_bias,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    d = os.path.join(tmp_path, "hf")
    model.save_pretrained(d, safe_serialization=True)
    return model, d


def test_file_roundtrip_logits_parity(tmp_path):
    hf, d = _tiny_hf_llama(tmp_path)
    cfg, params = load_hf_checkpoint(d, dtype="float32")
    assert cfg.arch == "llama" and cfg.n_layers == 3

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(2, 11), dtype=np.int64)
    with torch.no_grad():
        hf_logits = hf(torch.from_numpy(tokens)).logits.numpy()
    cache = llama.init_kv_cache(cfg, batch=2, max_seq=32)
    logits, _ = llama.forward(
        cfg, params, jnp.asarray(tokens, jnp.int32), cache, jnp.int32(0)
    )
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=2e-4, atol=2e-4)


def test_file_load_matches_in_memory_conversion(tmp_path):
    hf, d = _tiny_hf_llama(tmp_path)
    cfg_mem, params_mem = params_from_hf_model(hf, dtype="float32")
    cfg_file, params_file = load_hf_checkpoint(d, dtype="float32")
    assert cfg_file.replace(name=cfg_mem.name) == cfg_mem
    flat_mem = jax.tree_util.tree_leaves_with_path(params_mem)
    flat_file = jax.tree_util.tree_leaves_with_path(params_file)
    assert [p for p, _ in flat_mem] == [p for p, _ in flat_file]
    for (_, a), (_, b) in zip(flat_mem, flat_file):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_qkv_bias_checkpoint_roundtrip(tmp_path):
    """ADVICE r1: biased checkpoints must map their biases, not drop them."""
    hf, d = _tiny_hf_llama(tmp_path, qkv_bias=True)
    cfg, params = load_hf_checkpoint(d, dtype="float32")
    assert cfg.attn_qkv_bias
    assert "bq" in params["layers"] and params["layers"]["bq"].shape == (3, 64)

    rng = np.random.default_rng(1)
    tokens = rng.integers(0, cfg.vocab_size, size=(1, 9), dtype=np.int64)
    with torch.no_grad():
        hf_logits = hf(torch.from_numpy(tokens)).logits.numpy()
    cache = llama.init_kv_cache(cfg, batch=1, max_seq=32)
    logits, _ = llama.forward(
        cfg, params, jnp.asarray(tokens, jnp.int32), cache, jnp.int32(0)
    )
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=2e-4, atol=2e-4)


def test_sharded_index_load(tmp_path):
    """Sharded model.safetensors.index.json checkpoints merge correctly."""
    from safetensors.numpy import save_file

    hf, d = _tiny_hf_llama(tmp_path)
    whole = load_safetensors_dir(d)
    sharded = os.path.join(tmp_path, "sharded")
    os.makedirs(sharded)
    names = sorted(whole)
    half = len(names) // 2
    shards = {
        "model-00001-of-00002.safetensors": {k: np.ascontiguousarray(whole[k]) for k in names[:half]},
        "model-00002-of-00002.safetensors": {k: np.ascontiguousarray(whole[k]) for k in names[half:]},
    }
    weight_map = {}
    for fname, tensors in shards.items():
        save_file(tensors, os.path.join(sharded, fname))
        weight_map.update({k: fname for k in tensors})
    with open(os.path.join(sharded, "model.safetensors.index.json"), "w") as f:
        json.dump({"weight_map": weight_map}, f)
    import shutil

    shutil.copy(os.path.join(d, "config.json"), os.path.join(sharded, "config.json"))

    cfg1, params1 = load_hf_checkpoint(d, dtype="float32")
    cfg2, params2 = load_hf_checkpoint(sharded, dtype="float32")
    for a, b in zip(
        jax.tree_util.tree_leaves(params1), jax.tree_util.tree_leaves(params2)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_bf16_safetensors_load(tmp_path):
    """BF16 tensors (how real checkpoints ship) decode bit-exactly."""
    import ml_dtypes
    from safetensors.numpy import save_file

    rng = np.random.default_rng(2)
    arr = rng.standard_normal((4, 8)).astype(np.float32).astype(ml_dtypes.bfloat16)
    path = os.path.join(tmp_path, "x.safetensors")
    # safetensors.numpy rejects ml_dtypes; write the raw bit pattern and
    # patch the header dtype to BF16 like real checkpoints carry
    save_file({"x": arr.view(np.uint16)}, path)
    raw = open(path, "rb").read()
    n = int.from_bytes(raw[:8], "little")
    header = json.loads(raw[8 : 8 + n])
    header["x"]["dtype"] = "BF16"
    new_header = json.dumps(header).encode()
    with open(path, "wb") as f:
        f.write(len(new_header).to_bytes(8, "little") + new_header + raw[8 + n :])
    out = load_safetensors_file(path)
    assert out["x"].dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(out["x"].view(np.uint16), arr.view(np.uint16))


def test_convert_cli_roundtrip(tmp_path, capsys):
    """`--in hf_dir --out ckpt` lands a loadable checkpoint-store dir."""
    hf, d = _tiny_hf_llama(tmp_path)
    out = os.path.join(tmp_path, "ckpt")
    rc = convert_main(["--in", d, "--out", out, "--dtype", "float32"])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip())
    assert summary["arch"] == "llama" and summary["n_layers"] == 3

    cfg, params = checkpoint.load_params(out)
    cfg_mem, params_mem = params_from_hf_model(hf, dtype="float32")
    for a, b in zip(
        jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(params_mem)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_gpt2_file_roundtrip(tmp_path):
    cfg_hf = transformers.GPT2Config(
        vocab_size=160,
        n_positions=64,
        n_embd=32,
        n_layer=2,
        n_head=4,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(cfg_hf)
    hf.eval()
    d = os.path.join(tmp_path, "hf_gpt2")
    hf.save_pretrained(d, safe_serialization=True)

    cfg, params = load_hf_checkpoint(d, dtype="float32")
    assert cfg.arch == "gpt2"
    rng = np.random.default_rng(3)
    tokens = rng.integers(0, cfg.vocab_size, size=(1, 13), dtype=np.int64)
    with torch.no_grad():
        hf_logits = hf(torch.from_numpy(tokens)).logits.numpy()
    cache = gpt2.init_kv_cache(cfg, batch=1, max_seq=32)
    logits, _ = gpt2.forward(
        cfg, params, jnp.asarray(tokens, jnp.int32), cache, jnp.int32(0)
    )
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=2e-4, atol=2e-4)


def test_gemma2_file_roundtrip(tmp_path):
    """Gemma-2 checkpoint through FILES: config.json carries head_dim,
    softcaps, query_pre_attn_scalar, sliding_window, hidden_activation —
    the _JsonConfig attribute view + config_from_hf must pick them all up
    and the loaded params must match the in-memory conversion's logits."""
    cfg_hf = transformers.Gemma2Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=24, max_position_embeddings=128, rms_norm_eps=1e-6,
        hidden_activation="gelu_pytorch_tanh", query_pre_attn_scalar=24,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        sliding_window=16, attn_implementation="eager",
    )
    torch.manual_seed(11)
    hf = transformers.Gemma2ForCausalLM(cfg_hf)
    hf.eval()
    d = str(tmp_path / "gemma2")
    hf.save_pretrained(d, safe_serialization=True)

    cfg, params = load_hf_checkpoint(d, dtype="float32")
    assert cfg.post_norms and cfg.attn_softcap == 50.0
    assert cfg.head_dim == 24 and cfg.attn_window == 16
    assert cfg.attn_window_pattern == "even" and cfg.norm_unit_offset
    assert "window_flag" in params["layers"]

    rng = np.random.default_rng(12)
    tokens = rng.integers(0, cfg.vocab_size, size=(1, 33), dtype=np.int64)
    with torch.no_grad():
        hf_logits = hf(torch.from_numpy(tokens)).logits.numpy()
    cache = llama.init_kv_cache(cfg, batch=1, max_seq=64)
    logits, _ = llama.forward(
        cfg, params, jnp.asarray(tokens, jnp.int32), cache, jnp.int32(0)
    )
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=3e-4, atol=3e-4)


def test_phi3_file_roundtrip(tmp_path):
    """Phi-3 checkpoint through FILES: fused qkv_proj / gate_up_proj split
    at load, <|end|> stop id added for the big-vocab real model path
    (vocab here is tiny so no stop id is injected)."""
    cfg_hf = transformers.Phi3Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, pad_token_id=0, eos_token_id=2,
        bos_token_id=1, attn_implementation="eager",
    )
    torch.manual_seed(13)
    hf = transformers.Phi3ForCausalLM(cfg_hf)
    hf.eval()
    d = str(tmp_path / "phi3")
    hf.save_pretrained(d, safe_serialization=True)

    cfg, params = load_hf_checkpoint(d, dtype="float32")
    assert cfg.chat_template == "phi3"
    assert params["layers"]["wq"].shape[-1] == cfg.n_heads * cfg.head_dim
    assert cfg.stop_token_ids == ()  # tiny vocab: no 32007 injection

    rng = np.random.default_rng(14)
    tokens = rng.integers(0, cfg.vocab_size, size=(2, 21), dtype=np.int64)
    with torch.no_grad():
        hf_logits = hf(torch.from_numpy(tokens)).logits.numpy()
    cache = llama.init_kv_cache(cfg, batch=2, max_seq=32)
    logits, _ = llama.forward(
        cfg, params, jnp.asarray(tokens, jnp.int32), cache, jnp.int32(0)
    )
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=2e-4, atol=2e-4)


def test_qwen3_file_roundtrip(tmp_path):
    """Qwen3 checkpoint through FILES: config.json carries head_dim and
    the model ships per-head q/k norms — the loader must stack them and
    the logits must match HF."""
    cfg_hf = transformers.Qwen3Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        head_dim=24, max_position_embeddings=128, rms_norm_eps=1e-6,
        rope_theta=1000000.0, pad_token_id=0, eos_token_id=2,
        bos_token_id=1, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(17)
    hf = transformers.Qwen3ForCausalLM(cfg_hf)
    hf.eval()
    d = str(tmp_path / "qwen3")
    hf.save_pretrained(d, safe_serialization=True)

    cfg, params = load_hf_checkpoint(d, dtype="float32")
    assert cfg.use_qk_norm and cfg.head_dim == 24
    assert params["layers"]["q_norm"].shape == (3, 24)

    rng = np.random.default_rng(18)
    tokens = rng.integers(0, cfg.vocab_size, size=(2, 17), dtype=np.int64)
    with torch.no_grad():
        hf_logits = hf(torch.from_numpy(tokens)).logits.numpy()
    cache = llama.init_kv_cache(cfg, batch=2, max_seq=32)
    logits, _ = llama.forward(
        cfg, params, jnp.asarray(tokens, jnp.int32), cache, jnp.int32(0)
    )
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=2e-4, atol=2e-4)


def test_gemma3_file_roundtrip_with_sliding_window_pattern(tmp_path):
    """Released gemma-3 config.json files encode the 5:1 pattern as
    sliding_window_pattern (no layer_types list) — the raw-JSON checkpoint
    path must derive the pattern and still match HF logits."""
    pytest.importorskip("transformers.models.gemma3")
    cfg_hf = transformers.Gemma3TextConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=6, num_attention_heads=4, num_key_value_heads=2,
        head_dim=24, max_position_embeddings=128, rms_norm_eps=1e-6,
        rope_theta=1000000.0, rope_local_base_freq=10000.0,
        sliding_window=16, query_pre_attn_scalar=24,
        pad_token_id=0, eos_token_id=1, bos_token_id=2,
        attn_implementation="eager",
    )
    torch.manual_seed(41)
    hf = transformers.Gemma3ForCausalLM(cfg_hf)
    hf.eval()
    d = str(tmp_path / "gemma3")
    hf.save_pretrained(d, safe_serialization=True)
    # rewrite config.json the way the Hub releases ship it
    import os

    cfg_path = os.path.join(d, "config.json")
    with open(cfg_path) as f:
        raw = json.load(f)
    raw.pop("layer_types", None)
    raw["sliding_window_pattern"] = 6
    raw["model_type"] = "gemma3_text"
    with open(cfg_path, "w") as f:
        json.dump(raw, f)

    cfg, params = load_hf_checkpoint(d, dtype="float32")
    assert cfg.attn_window_layer_types == (1, 1, 1, 1, 1, 0)
    assert cfg.rope_local_theta == 10000.0 and cfg.use_qk_norm

    rng = np.random.default_rng(42)
    tokens = rng.integers(0, cfg.vocab_size, size=(1, 29), dtype=np.int64)
    with torch.no_grad():
        hf_logits = hf(torch.from_numpy(tokens)).logits.numpy()
    cache = llama.init_kv_cache(cfg, batch=1, max_seq=64)
    logits, _ = llama.forward(
        cfg, params, jnp.asarray(tokens, jnp.int32), cache, jnp.int32(0)
    )
    np.testing.assert_allclose(np.asarray(logits), hf_logits,
                               rtol=3e-4, atol=3e-4)
