"""OpenAI SSE streaming against a --continuous server: real per-chunk
deltas from the slot fleet (tests/test_openai_api.py covers the
single-chunk emulation on a plain server)."""

import json
import urllib.request

import pytest

from distributed_llm_inference_tpu import EngineConfig, create_engine
from distributed_llm_inference_tpu.engine.continuous import ContinuousEngine
from distributed_llm_inference_tpu.serving.server import InferenceServer


@pytest.fixture(scope="module")
def served():
    engine = create_engine(
        "test-llama-tiny",
        engine_cfg=EngineConfig(prefill_buckets=(64,)),
    )
    cont = ContinuousEngine(engine, n_slots=2, chunk_steps=4)
    server = InferenceServer(engine, host="127.0.0.1", port=0,
                             continuous=cont)
    server.start()
    yield server
    server.shutdown()


def _post_raw(server, path, body, timeout=180):
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    return urllib.request.urlopen(req, timeout=timeout)


def _events(raw: str):
    return [json.loads(line[len("data: "):])
            for line in raw.strip().split("\n\n")
            if line.startswith("data: ") and line != "data: [DONE]"]


def test_chat_stream_real_deltas(served):
    with _post_raw(served, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "stream continuous"}],
        "max_tokens": 12, "temperature": 0, "stream": True,
    }) as r:
        assert r.headers["Content-Type"].startswith("text/event-stream")
        raw = r.read().decode()
    events = _events(raw)
    assert raw.strip().endswith("data: [DONE]")
    # chunk_steps=4 against 12 tokens: the fleet emits MULTIPLE content
    # deltas (the emulation path would emit exactly one)
    content = [e["choices"][0]["delta"].get("content", "")
               for e in events if e["choices"][0]["delta"].get("content")]
    assert len(content) >= 2
    text = "".join(content)
    ref = served.engine.generate(
        "stream continuous", max_tokens=12, greedy=True, chat=True,
    )
    assert text == ref["response"]
    finals = [e for e in events if e["choices"][0]["finish_reason"]]
    assert len(finals) == 1
    assert finals[0]["usage"]["prompt_tokens"] > 0


def test_completions_stream_seeded_solo_fallback_has_text(served):
    """A seeded stream takes the continuous engine's solo fallback (no
    per-chunk deltas) — the SSE adapter must still deliver the full
    completion text."""
    with _post_raw(served, "/v1/completions", {
        "prompt": "seeded stream", "max_tokens": 6, "temperature": 0.8,
        "seed": 11, "stream": True,
    }) as r:
        raw = r.read().decode()
    events = _events(raw)
    text = "".join(e["choices"][0]["text"] for e in events)
    # same sampler mapping the OpenAI layer uses: no top-k, top_p off
    ref = served.engine.generate(
        "seeded stream", max_tokens=6, temperature=0.8, top_k=0, top_p=1.0,
        seed=11, chat=False,
    )
    assert text == ref["response"]
