"""Logits parity: our JAX Phi-3 vs a tiny-random HF Phi3ForCausalLM.

Phi-3 is llama-arch (RMSNorm/RoPE/GQA/SwiGLU, silu) but HF stores fused
projections — qkv_proj [(H+2KV)*Dh, D] and gate_up_proj [2F, D] — which the
converter splits into the canonical stacked leaves at load time, so tp
sharding / quant / pipeline slicing see one layout.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from distributed_llm_inference_tpu import EngineConfig, get_model_config
from distributed_llm_inference_tpu.engine.engine import InferenceEngine
from distributed_llm_inference_tpu.models import llama
from distributed_llm_inference_tpu.models.convert import params_from_hf_model

# fast-tier exclusion: HF-parity family file; run the full suite (plain
# `pytest`) to include it
pytestmark = pytest.mark.slow


def _tiny_hf_phi3(n_kv_heads=2):
    cfg = transformers.Phi3Config(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=n_kv_heads,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        pad_token_id=0,
        eos_token_id=2,
        bos_token_id=1,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.Phi3ForCausalLM(cfg)
    model.eval()
    return model


@pytest.mark.parametrize("n_kv_heads", [4, 2])  # MHA and GQA splits
def test_phi3_logits_match_hf(n_kv_heads):
    hf = _tiny_hf_phi3(n_kv_heads)
    cfg, params = params_from_hf_model(hf, dtype="float32")
    assert cfg.arch == "llama" and cfg.chat_template == "phi3"
    assert cfg.n_kv_heads == n_kv_heads
    # fused projections were split into canonical leaves
    assert params["layers"]["wq"].shape[-1] == cfg.n_heads * cfg.head_dim
    assert params["layers"]["w_gate"].shape[-1] == cfg.ffn_dim

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(2, 21), dtype=np.int64)
    with torch.no_grad():
        hf_logits = hf(torch.from_numpy(tokens)).logits.numpy()

    cache = llama.init_kv_cache(cfg, batch=2, max_seq=32)
    logits, _ = llama.forward(
        cfg, params, jnp.asarray(tokens, jnp.int32), cache, jnp.int32(0)
    )
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=2e-4, atol=2e-4)


def test_phi3_preset_and_chat():
    cfg = get_model_config("phi3-mini-4k")
    assert cfg.attn_window == 2047 and 32007 in cfg.stop_token_ids
    from distributed_llm_inference_tpu.engine.chat import format_chat_prompt

    t = format_chat_prompt("hi", arch="llama", template="phi3")
    # native <|system|> role (HF Phi-3 chat template has a system turn)
    assert t.startswith("<|system|>\n") and "<|user|>\nhi<|end|>" in t
    assert t.endswith("<|assistant|>\n")
    t2 = format_chat_prompt("hi", system="", arch="llama", template="phi3")
    assert t2.startswith("<|user|>")


def test_phi3_engine_smoke():
    hf = _tiny_hf_phi3()
    cfg, params = params_from_hf_model(hf, dtype="float32")
    eng = InferenceEngine(
        cfg, params=params, engine_cfg=EngineConfig(prefill_buckets=(32, 64))
    )
    r = eng.generate("hello phi", max_tokens=6, greedy=True)
    assert r["status"] == "success"
