"""Speculative decoding on the ragged paged fleet (ISSUES 13 + 15).

The bar: draft-then-verify inside the mixed launch is a LAUNCH strategy,
not a semantics change — greedy output must be bit-identical to
non-speculative decode (threaded fleets, warm prefix reuse, crash and
preemption landing mid-spec-cycle included), speculated tokens must
debit step_token_budget so the SLO layer can throttle K to 0 under TPOT
pressure, decode rows stay reserved ahead of prefill chunks, and the
whole accept/reject decision stays traced (the spec-mixed HLO checks
pin the artifact half).

Device-derived launch metadata (ISSUE 15, engine_cfg.spec_device_meta):
decode/verify q_start and positions come from the device-resident slot
state, so an unfetched verify row never freezes its slot — verify rows
launch EVERY step, back to back (pinned by the pipelined-launch count:
>0 with the freeze deleted, 0 on the legacy host-planned baseline),
greedy output stays bit-identical to BOTH the plain fleet and the
legacy path, and per-slot adaptive K (acceptance-rate EWMA) sizes each
draft between 0 and spec_draft_len.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_inference_tpu import EngineConfig, get_model_config
from distributed_llm_inference_tpu.engine import generate as G
from distributed_llm_inference_tpu.engine import paged as EP
from distributed_llm_inference_tpu.engine.continuous import ContinuousEngine
from distributed_llm_inference_tpu.engine.engine import InferenceEngine
from distributed_llm_inference_tpu.engine.scheduler import (
    SLOClass,
    TokenBudgetScheduler,
    ngram_draft,
)
from distributed_llm_inference_tpu.models import api as M
from distributed_llm_inference_tpu.utils import faults

TILE = 8
SERVE_CFG = dict(dtype="float32", eos_token_id=-1, max_seq_len=512)

# byte-fallback tokenization makes word repeats literal token repeats,
# so the bigram planner finds drafts and the model (even a random-weight
# tiny one) verifies SOME of them on a fully periodic stream
REPEAT_PROMPT = "the cat sat on the mat " * 10
MIXED_PROMPTS = [
    REPEAT_PROMPT,
    "the quick brown fox jumps over the lazy dog",
    "abc xyz " * 14,
    "short",
]


@pytest.fixture(scope="module")
def setup():
    cfg = get_model_config("test-llama-tiny", **SERVE_CFG)
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(autouse=True)
def _always_disarm():
    faults.disarm()
    yield
    faults.disarm()


def _cont(cfg, params, spec, **kw):
    ecfg = dict(
        prefix_cache_entries=4, chunked_prefill=True,
        step_token_budget=64, prefill_buckets=(64, 128, 256),
        spec_decode=spec, spec_draft_len=4 if spec else 0,
    )
    ecfg.update(kw.pop("engine_cfg", {}))
    eng = InferenceEngine(cfg, params=params, engine_cfg=EngineConfig(**ecfg))
    args = dict(n_slots=4, chunk_steps=8, slot_max_seq=512,
                kv_pool_blocks=120, kv_block_size=16,
                restart_backoff_s=0.01)
    args.update(kw)
    return ContinuousEngine(eng, **args)


# -- planner units (no engine, no device) ------------------------------------

def _sched(width=64, n_slots=4):
    classes = {
        "interactive": SLOClass("interactive", 0.5, 0.1, 4.0, True),
        "standard": SLOClass("standard", 2.0, 0.5, 2.0, True),
    }
    return TokenBudgetScheduler(classes, "standard", width, TILE, n_slots)


def test_ngram_draft_rules():
    # most recent earlier occurrence of the current bigram wins
    hist = [1, 2, 3, 9, 1, 2, 5, 6, 1, 2]
    assert ngram_draft(hist, 3) == [5, 6, 1]
    # no earlier occurrence -> NO draft (plain decode row, zero cost)
    assert ngram_draft([1, 2, 3, 4, 5], 3) == []
    # short histories never draft
    assert ngram_draft([1, 2], 2) == []
    assert ngram_draft(hist, 0) == []
    # a draft near the end of the history may be short, never empty
    assert ngram_draft([7, 8, 7, 8], 4) == [7, 8]
    # short-period repetition: an earlier match supplies the FULL draft
    # where the latest occurrence truncates at the history end
    assert ngram_draft([9, 9, 9, 9, 9, 9], 4) == [9, 9, 9, 9]
    assert ngram_draft([1, 2, 1, 2, 1, 2, 1, 2], 4) == [1, 2, 1, 2]


def test_spec_tokens_debit_step_token_budget():
    """A verify row reserves ceil((1+K)/tile) tiles out of the same
    budget prefill chunks draw from — with a fat draft the pending job
    gets strictly fewer tiles than with plain decode rows."""
    import test_scheduler as TS

    sched = _sched(width=64)  # 8 tiles
    cls = sched.classes["standard"]
    job = TS._job(cls, tail=64, enqueued=0.0)
    # plain: 4 decode rows = 4 tiles -> 4 tiles (32 tokens) for prefill
    plain = sched.plan(4, [job], now=10.0)
    assert plain == [(job, 32)]
    # speculative: 4 verify rows of 1+15 tokens = 2 tiles each -> 8
    # tiles of decode reservation... clamp: spec_draft_len would never
    # plan that; use 3 spec rows of 2 tiles + 1 plain = 7 tiles -> 1
    spec = sched.plan(3 * 2 + 1, [job], now=10.0)
    assert spec == [(job, 8)]


def test_spec_draft_len_throttles_to_zero_under_tpot_pressure():
    sched = _sched()
    assert sched.spec_draft_len(4, 2, 1, active_classes={"standard"}) == 4
    # observed TPOT over the class target: the SAME decode-protection
    # signal that halves the prefill budget disables speculation
    sched.observe("standard", 0.01, 5.0)
    assert sched.spec_draft_len(4, 2, 1, active_classes={"standard"}) == 0
    # other classes under target keep speculating
    assert sched.spec_draft_len(4, 2, 1, active_classes=set()) == 4


def test_spec_draft_len_fits_the_step_budget():
    sched = _sched(width=64, n_slots=4)  # 8 tiles
    # 4 verify rows must coexist with one prefill-progress tile: K=7
    # keeps each row at one tile (1+7 <= tile)
    assert sched.spec_draft_len(7, 4, 0, jobs_pending=True) == 7
    # K=15 would need 2 tiles per row (8 + 1 > 8) -> shrink until it fits
    assert sched.spec_draft_len(15, 4, 0, jobs_pending=True) == 7
    # fewer rows leave room for fatter drafts
    assert sched.spec_draft_len(15, 3, 0, jobs_pending=True) == 15
    assert sched.spec_draft_len(0, 4, 0) == 0
    assert sched.spec_draft_len(4, 0, 4) == 0


def test_decode_rows_reserved_before_prefill_with_spec():
    """Verify rows never starve prefill liveness and vice versa: even
    with the decode reservation at budget, the oldest job still gets a
    tile — and decode tiles were reserved FIRST."""
    import test_scheduler as TS

    sched = _sched(width=64)
    cls = sched.classes["standard"]
    job = TS._job(cls, tail=64, enqueued=0.0)
    out = sched.plan(7, [job], now=10.0)  # 7 of 8 tiles to decode/spec
    assert out == [(job, 8)]


# -- adaptive per-slot K (acceptance-EWMA throttle, ISSUE 15) ----------------

def test_adaptive_k_converges_down_and_reprobes():
    """A slot whose drafts keep rejecting degrades to K=0 (plain decode
    rows — no verify tiles burnt) and re-probes with a 1-token draft
    after SPEC_REPROBE skipped plans."""
    from distributed_llm_inference_tpu.engine.scheduler import SPEC_REPROBE

    sched = _sched()
    assert sched.spec_slot_k(0, 4) == 4  # no data: probe at full depth
    for _ in range(8):
        sched.observe_spec(0, 4, 0)
    # re-probe: after SPEC_REPROBE consecutive skipped plans, one
    # 1-token draft goes out so a stream that turns repetitive recovers
    ks = [sched.spec_slot_k(0, 4) for _ in range(SPEC_REPROBE)]
    assert ks[-1] == 1 and all(k == 0 for k in ks[:-1])
    # the probe reset the skip counter: the next plan skips again
    assert sched.spec_slot_k(0, 4) == 0


def test_adaptive_k_converges_back_up():
    sched = _sched()
    for _ in range(8):
        sched.observe_spec(0, 4, 0)
    assert sched.spec_slot_k(0, 4) == 0
    for _ in range(16):
        sched.observe_spec(0, 4, 4)  # full acceptance again
    assert sched.spec_slot_k(0, 4) == 4
    # partial acceptance sizes the draft proportionally, never 0
    sched2 = _sched()
    for _ in range(16):
        sched2.observe_spec(1, 4, 2)
    assert 1 <= sched2.spec_slot_k(1, 4) <= 3


def test_adaptive_k_is_per_slot_and_resettable():
    sched = _sched()
    for _ in range(8):
        sched.observe_spec(0, 4, 0)
    assert sched.spec_slot_k(0, 4) == 0
    assert sched.spec_slot_k(1, 4) == 4  # untouched slot unaffected
    sched.spec_reset(0)  # new tenant on the slot: history forgotten
    assert sched.spec_slot_k(0, 4) == 4


def test_adaptive_k_tpot_pressure_still_forces_zero():
    """The global TPOT-pressure gate runs BEFORE the per-slot EWMA: a
    perfectly-accepting slot still drafts nothing under decode
    pressure (engine/continuous clamps kb = min(spec_draft_len(...),
    spec_slot_k(...)))."""
    sched = _sched()
    for _ in range(8):
        sched.observe_spec(0, 4, 4)
    assert sched.spec_slot_k(0, 4) == 4
    sched.observe("standard", 0.01, 5.0)  # TPOT over target
    assert sched.spec_draft_len(4, 1, 0, active_classes={"standard"}) == 0


def test_spec_block_cap_pessimistic_frontier():
    """The allocation clamp under back-to-back verify rows: the device
    may lead the lagged host position by every pending launch's maximum
    advance, so the cap must use the pessimistic frontier."""
    from distributed_llm_inference_tpu.engine.scheduler import spec_block_cap

    # 4 blocks of 16 = positions 0..63; at host pos 50 with nothing
    # pending a draft may extend to position 62 (write at pos..pos+k)
    assert spec_block_cap(4, 16, 50) == 13
    # two pending verify launches of 4 drafts each could have advanced
    # the device by up to 2 * (4 + 1): the cap shrinks accordingly
    assert spec_block_cap(4, 16, 50 + 2 * 5) == 3
    # at/near the allocation end the cap goes non-positive -> no draft
    assert spec_block_cap(4, 16, 63) <= 0


# -- traced verify unit (device math vs a slot_step simulation) --------------

def _simulate_plain(cfg, tokens, remaining):
    """Reference: what slot_step's greedy bookkeeping does with this
    emission stream, one token per step."""
    emitted, pos_adv, rem = [], 0, remaining
    for t in tokens:
        stop = t in cfg.all_stop_ids
        can_emit = not stop and rem > 0
        pos_adv += 1
        if stop:
            return emitted, pos_adv, rem, False, 0
        if rem <= 0:
            break
        emitted.append(t)
        rem -= 1
        if rem == 0:
            return emitted, pos_adv, rem, False, t
    return emitted, pos_adv, rem, True, emitted[-1] if emitted else 0


@pytest.mark.parametrize(
    "window,draft,n_draft,remaining",
    [
        ([5, 6, 7, 8, 9], [5, 6, 7, 8], 4, 20),   # full accept + bonus
        ([5, 6, 7, 8, 9], [5, 9, 7, 8], 4, 20),   # partial accept
        ([5, 6, 7, 8, 9], [1, 2, 3, 4], 4, 20),   # all rejected
        ([5, 2, 7, 8, 9], [5, 2, 7, 8], 4, 20),   # EOS (id 2) mid-window
        ([2, 6, 7, 8, 9], [5, 6, 7, 8], 4, 20),   # EOS first
        ([5, 6, 7, 8, 9], [5, 6, 7, 8], 4, 3),    # budget clamps
        ([5, 6, 2, 8, 9], [5, 6, 2, 8], 4, 2),    # budget before the EOS
        ([5, 6, 7, 8, 9], [5, 6, 0, 0], 2, 20),   # short draft
    ],
)
def test_spec_verify_matches_slot_step_semantics(window, draft, n_draft,
                                                 remaining):
    cfg = get_model_config("test-llama-tiny")  # eos_token_id = 2
    state, _ = G.init_slots(1, cfg.vocab_size)
    state = state._replace(
        active=jnp.ones((1,), bool),
        remaining=jnp.asarray([remaining], jnp.int32),
        pos=jnp.asarray([10], jnp.int32),
        token=jnp.asarray([5], jnp.int32),
    )
    win = jnp.asarray([window], jnp.int32)
    dr = jnp.asarray([draft], jnp.int32)
    new, emit, mask, adv = EP.spec_verify(
        cfg, state, win, dr, jnp.asarray([n_draft], jnp.int32),
        jnp.asarray([True]),
    )
    # the accepted stream = matched draft prefix + correction token,
    # then the slot_step simulation over it
    n_acc = 0
    for j in range(n_draft):
        if draft[j] == window[j]:
            n_acc += 1
        else:
            break
    stream = window[: n_acc + 1]
    ref_emit, ref_adv, ref_rem, ref_active, ref_tok = _simulate_plain(
        cfg, stream, remaining
    )
    got = [int(t) for t, m in zip(np.asarray(emit)[0], np.asarray(mask)[0])
           if m]
    assert got == ref_emit, (got, ref_emit)
    assert int(adv[0]) == ref_adv
    assert int(new.remaining[0]) == ref_rem
    assert bool(new.active[0]) == (ref_active and ref_rem > 0)
    assert int(new.pos[0]) == 10 + ref_adv
    if ref_active and ref_rem > 0:
        assert int(new.token[0]) == ref_tok


def test_spec_verify_inactive_and_off_rows_frozen():
    cfg = get_model_config("test-llama-tiny")
    state, _ = G.init_slots(2, cfg.vocab_size)
    state = state._replace(
        active=jnp.asarray([False, True]),
        remaining=jnp.asarray([0, 5], jnp.int32),
        pos=jnp.asarray([3, 7], jnp.int32),
    )
    win = jnp.asarray([[5, 6], [5, 6]], jnp.int32)
    dr = jnp.asarray([[5], [5]], jnp.int32)
    nd = jnp.asarray([1, 1], jnp.int32)
    # row 0: on but device-inactive; row 1: not on at all
    new, emit, mask, adv = EP.spec_verify(
        cfg, state, win, dr, nd, jnp.asarray([True, False]) & state.active
    )
    assert not np.asarray(mask).any()
    assert np.asarray(new.pos).tolist() == [3, 7]
    assert np.asarray(new.remaining).tolist() == [0, 5]


# -- engine level -------------------------------------------------------------

def test_spec_greedy_bit_identical_and_accepts(setup):
    """The acceptance bar: a speculating mixed fleet serves the exact
    greedy token streams the plain fleet serves — threaded, with warm
    prefix reuse — while verify rows actually launch on the repetitive
    stream (deterministic acceptance itself is pinned by
    test_mixed_verify_accepts_model_argmax and the draft-model leg).
    Runs THREE ways: plain, device-derived metadata (the default
    unfrozen back-to-back loop), and the legacy host-planned freeze —
    all three must be token-identical (the ISSUE 15 bit-exactness leg:
    device-meta greedy output == host-planned output across threads and
    warm prefix reuse)."""
    cfg, params = setup
    shared = " ".join(f"ctx{j}" for j in range(24))
    prompts = MIXED_PROMPTS + [shared + " question one",
                               shared + " question two"]
    modes = {
        "plain": (False, {}),
        "devmeta": (True, {}),
        "legacy": (True, {"spec_device_meta": False}),
    }
    outs = {}
    for name, (spec, extra) in modes.items():
        cont = _cont(cfg, params, spec, engine_cfg=dict(extra))
        try:
            warm = [
                cont.submit(p, max_tokens=12, greedy=True, chat=False)
                for p in prompts
            ]
            wave = [None] * len(prompts)

            def run(i, c=cont, w=wave):
                w[i] = c.submit(prompts[i], max_tokens=12, greedy=True,
                                chat=False)

            ts = [
                threading.Thread(target=run, args=(i,))
                for i in range(len(prompts))
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=300)
            st = cont.stats()
        finally:
            cont.close()
        assert all(
            r is not None and r["status"] == "success" for r in warm + wave
        ), (name, warm, wave)
        outs[name] = [r["response"] for r in warm + wave]
        if spec:
            sb = st["speculative"]
            assert sb["mode"] == "ngram"
            assert sb["device_meta"] == (name == "devmeta")
            assert sb["launches"] > 0, st
            assert sb["drafted_tokens"] > 0, st
    assert outs["devmeta"] == outs["plain"]
    assert outs["legacy"] == outs["plain"]


def test_mixed_verify_accepts_model_argmax():
    """Deterministic acceptance + program-level bit-identity: decode 5
    tokens with plain 1-token mixed launches, then replay the SAME
    start as ONE verify row drafting the model's own chain — the traced
    verify must emit the identical stream and leave the identical slot
    state (the chunked-vs-whole discipline, speculation edition)."""
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(3))
    W, B, bs, MB = 16, 1, 16, 4
    table = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    K = 4
    K1 = K + 1

    def fresh_state():
        state, sparams = G.init_slots(B, cfg.vocab_size)
        state = state._replace(
            token=jnp.asarray([7], jnp.int32),
            pos=jnp.asarray([4], jnp.int32),
            active=jnp.asarray([True]),
            remaining=jnp.asarray([6], jnp.int32),
        )
        sparams = sparams._replace(greedy=jnp.asarray([True]))
        return state, sparams

    arm = EP.idle_mixed_arm(B, cfg.vocab_size)
    key = jax.random.PRNGKey(11)

    # --- reference: 5 plain decode launches, state/pool chained
    state, sparams = fresh_state()
    pool = EP.init_pool(cfg, MB + 2, bs)
    plain = []
    for t in range(5):
        meta, tok_row, tok_pos, offs, _ = EP.build_ragged_meta(
            [(0, 4 + t, 1, EP.RAGGED_DECODE)], width=W, tile=TILE
        )
        dec_flag = np.zeros((W,), bool)
        dec_flag[offs[0]] = True
        packed, state, sparams, pool = EP.mixed_step_ragged(
            cfg, params, jnp.zeros((W,), jnp.int32), jnp.asarray(tok_row),
            jnp.asarray(tok_pos), jnp.asarray(dec_flag), jnp.asarray(meta),
            pool, table, state, sparams, key, jnp.asarray([offs[0]],
                                                          jnp.int32), arm,
        )
        p = np.asarray(packed)
        if p[1, 0]:
            plain.append(int(p[0, 0]))
    ref_state = state

    # --- one verify row drafting the chain the model just produced
    draft = (plain + [0] * K)[:K]
    state, sparams = fresh_state()
    pool = EP.init_pool(cfg, MB + 2, bs)
    meta, tok_row, tok_pos, offs, _ = EP.build_ragged_meta(
        [(0, 4, 1 + K, EP.RAGGED_PREFILL)], width=W, tile=TILE
    )
    toks = np.zeros((W,), np.int32)
    toks[offs[0] + 1 : offs[0] + 1 + K] = draft
    dec_flag = np.zeros((W,), bool)
    dec_flag[offs[0]] = True
    spec = EP.SpecPlan(
        jnp.asarray([False]), jnp.asarray([True]),
        jnp.asarray([[offs[0] + j for j in range(K1)]], jnp.int32),
        jnp.asarray([K], jnp.int32),
    )
    packed, state, sparams, pool = EP.mixed_step_ragged(
        cfg, params, jnp.asarray(toks), jnp.asarray(tok_row),
        jnp.asarray(tok_pos), jnp.asarray(dec_flag), jnp.asarray(meta),
        pool, table, state, sparams, key, jnp.zeros((B,), jnp.int32), arm,
        spec=spec,
    )
    p = np.asarray(packed)
    em = p[5 : 5 + K1, 0]
    mk = p[5 + K1 : 5 + 2 * K1, 0].astype(bool)
    got = em[mk].tolist()
    assert got == plain, (got, plain)
    assert len(got) >= 2  # the draft actually won tokens (accept > 0)
    for field in ("pos", "token", "active", "remaining"):
        assert (
            np.asarray(getattr(state, field)).tolist()
            == np.asarray(getattr(ref_state, field)).tolist()
        ), field


def test_device_meta_derives_positions_on_device():
    """The ISSUE 15 derivation contract at the program level: a verify
    row launched with GARBAGE host-planned positions but DeviceMeta
    masks produces the bit-identical packed fetch and slot state as the
    host-exact launch — the kernel metadata and write/RoPE positions
    really come from state.pos, not the host plan."""
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(3))
    W, B, bs, MB = 16, 1, 16, 6
    table = jnp.asarray([[1, 2, 3, 4, 5, 6]], jnp.int32)
    K = 4
    K1 = K + 1
    arm = EP.idle_mixed_arm(B, cfg.vocab_size)
    key = jax.random.PRNGKey(11)
    draft = [9, 17, 3, 250]
    # a real prefilled prefix so positions MATTER: a wrong q_start both
    # mis-masks the context window and mis-rotates RoPE relative to it
    prefix = [(31 + 13 * j) % cfg.vocab_size for j in range(64)]

    def fresh():
        pool = EP.init_pool(cfg, MB + 2, bs)
        for c in range(2):
            meta, tok_row, tok_pos, _, _ = EP.build_ragged_meta(
                [(0, c * 32, 32, EP.RAGGED_PREFILL)], width=32, tile=TILE
            )
            pool = EP.extend_ragged_paged(
                cfg, params,
                jnp.asarray(prefix[c * 32 : (c + 1) * 32], jnp.int32),
                jnp.asarray(tok_row), jnp.asarray(tok_pos),
                jnp.asarray(meta), pool, table,
            )
        state, sparams = G.init_slots(B, cfg.vocab_size)
        state = state._replace(
            token=jnp.asarray([prefix[-1]], jnp.int32),
            pos=jnp.asarray([63], jnp.int32),
            active=jnp.asarray([True]),
            remaining=jnp.asarray([6], jnp.int32),
        )
        sparams = sparams._replace(greedy=jnp.asarray([True]))
        return state, sparams, pool

    def run(start, dev):
        entries = [(0, start, 1 + K, EP.RAGGED_PREFILL)]
        meta, tok_row, tok_pos, offs, _ = EP.build_ragged_meta(
            entries, width=W, tile=TILE
        )
        toks = np.zeros((W,), np.int32)
        toks[offs[0] + 1 : offs[0] + 1 + K] = draft
        dec_flag = np.zeros((W,), bool)
        dec_flag[offs[0]] = True
        spec = EP.SpecPlan(
            jnp.asarray([False]), jnp.asarray([True]),
            jnp.asarray([[offs[0] + j for j in range(K1)]], jnp.int32),
            jnp.asarray([K], jnp.int32),
        )
        dev_op = None
        if dev:
            t_on, t_off, k_on, k_off = EP.build_device_meta(
                entries, offs, 1, width=W, tile=TILE
            )
            dev_op = EP.DeviceMeta(
                jnp.asarray(t_on), jnp.asarray(t_off),
                jnp.asarray(k_on), jnp.asarray(k_off),
            )
        state, sparams, pool = fresh()
        packed, state, _, _ = EP.mixed_step_ragged(
            cfg, params, jnp.asarray(toks), jnp.asarray(tok_row),
            jnp.asarray(tok_pos), jnp.asarray(dec_flag), jnp.asarray(meta),
            pool, table, state, sparams, key, jnp.zeros((B,), jnp.int32),
            arm, spec=spec, spec_toks=None, dev=dev_op,
        )
        return np.asarray(packed), state

    exact, state_e = run(start=63, dev=False)  # host-exact baseline
    derived, state_d = run(start=7, dev=True)  # garbage host plan
    assert exact.tolist() == derived.tolist()
    for field in ("pos", "token", "active", "remaining"):
        assert (
            np.asarray(getattr(state_d, field)).tolist()
            == np.asarray(getattr(state_e, field)).tolist()
        ), field
    # and the garbage plan WITHOUT derivation really is garbage (the
    # test would otherwise prove nothing)
    junk, _ = run(start=7, dev=False)
    assert junk.tolist() != exact.tolist()


def test_spec_launches_every_step_back_to_back(setup):
    """The freeze is deleted (ISSUE 15 acceptance): with device-derived
    metadata a speculating slot submits a verify row while its previous
    one is still unfetched (pipelined_launches > 0); the legacy
    host-planned baseline never does (the skip-until-fetched
    alternation); and both serve the bit-identical greedy stream."""
    cfg, params = setup
    outs, stats = {}, {}
    for devmeta in (True, False):
        cont = _cont(cfg, params, True,
                     engine_cfg={"spec_device_meta": devmeta})
        try:
            r = cont.submit(REPEAT_PROMPT, max_tokens=24, greedy=True,
                            chat=False)
            st = cont.stats()
        finally:
            cont.close()
        assert r["status"] == "success"
        outs[devmeta] = r["response"]
        stats[devmeta] = st["speculative"]
    assert outs[True] == outs[False]
    sb, sb_legacy = stats[True], stats[False]
    assert sb["launches"] > 0 and sb_legacy["launches"] > 0
    # every-step verify: back-to-back rows while earlier ones are
    # unfetched — impossible by construction on the frozen path
    assert sb["pipelined_launches"] > 0, sb
    assert sb_legacy["pipelined_launches"] == 0, sb_legacy
    # and the unfrozen loop never launches FEWER verify rows
    assert sb["launches"] >= sb_legacy["launches"], (sb, sb_legacy)


def test_spec_metrics_and_envelope(setup):
    cfg, params = setup
    cont = _cont(cfg, params, True)
    try:
        r = cont.submit(REPEAT_PROMPT, max_tokens=16, greedy=True,
                        chat=False, speculative=True)
        snap = cont.engine.metrics.snapshot()
    finally:
        cont.close()
    assert r["status"] == "success"
    assert r.get("continuous") is True  # served in-fleet, not solo
    assert r.get("speculative") is True
    assert r.get("spec_path") == "fleet"
    assert r.get("spec_drafted", 0) >= r.get("spec_accepted", 0) >= 0
    assert r["spec_drafted"] > 0
    total = sum(
        s["value"]
        for s in snap.get("dli_spec_drafted_tokens_total", {}).get(
            "series", []
        )
    )
    assert total > 0
    assert "dli_spec_launches_total" in snap
    assert "dli_spec_tokens_per_launch" in snap
    # adaptive drafting observability (ISSUE 15): planned K histogram
    # populated per verify row, acceptance-EWMA gauge present
    k_hist = snap.get("dli_spec_draft_len", {}).get("series", [])
    assert sum(s["count"] for s in k_hist) > 0, snap.get(
        "dli_spec_draft_len"
    )
    assert "dli_spec_accept_ewma" in snap


def test_speculative_request_runs_in_fleet_even_when_fleet_default_off(setup):
    """Satellite: the solo fallback for speculative requests is lifted —
    a greedy "speculative": true request on a spec-capable fleet decodes
    in-fleet (and matches the plain fleet's greedy stream); seeded
    requests keep the solo contract."""
    cfg, params = setup
    cont = _cont(cfg, params, False,
                 engine_cfg={"spec_draft_len": 4, "spec_decode": False})
    try:
        plain = cont.submit(REPEAT_PROMPT, max_tokens=10, greedy=True,
                            chat=False)
        spec = cont.submit(REPEAT_PROMPT, max_tokens=10, greedy=True,
                           chat=False, speculative=True)
        seeded = cont.submit(REPEAT_PROMPT, max_tokens=10, greedy=True,
                             chat=False, speculative=True, seed=7)
    finally:
        cont.close()
    assert spec.get("continuous") is True
    assert spec["spec_path"] == "fleet"
    assert spec["response"] == plain["response"]
    # seeded/debug contracts still go solo (per-request RNG stream)
    assert "continuous" not in seeded
    assert seeded.get("spec_path") == "solo"


def test_spec_disables_under_tpot_pressure_engine(setup):
    """Engine leg of the throttle: with observed TPOT over every active
    class target, the fleet plans no verify rows at all."""
    cfg, params = setup
    cont = _cont(cfg, params, True)
    try:
        # poison the feedback EWMA before any traffic: decode pressure
        for name in cont._slo:
            cont._sched.observe(name, 0.01, 99.0)
        r = cont.submit(REPEAT_PROMPT, max_tokens=12, greedy=True,
                        chat=False)
        st = cont.stats()
    finally:
        cont.close()
    assert r["status"] == "success"
    assert st["speculative"]["launches"] == 0


def test_non_greedy_request_never_speculates_but_stays_in_fleet(setup):
    cfg, params = setup
    cont = _cont(cfg, params, True)
    try:
        r = cont.submit(REPEAT_PROMPT, max_tokens=8, temperature=0.9,
                        chat=False, speculative=True)
        st = cont.stats()
    finally:
        cont.close()
    assert r["status"] == "success"
    assert r.get("continuous") is True
    assert st["speculative"]["launches"] == 0


def test_spec_with_long_prompt_interleaving(setup):
    """Verify rows and prefill chunks share launches: a long admission
    mid-flight neither stalls nor corrupts a speculating decoder."""
    cfg, params = setup
    long_prompt = "y " * 150
    outs = {}
    for spec in (False, True):
        cont = _cont(cfg, params, spec)
        try:
            cont.submit(REPEAT_PROMPT, max_tokens=4, greedy=True,
                        chat=False)  # warm
            res = [None, None]

            def d(c=cont, r=res):
                r[0] = c.submit(REPEAT_PROMPT, max_tokens=20, greedy=True,
                                chat=False)

            def l(c=cont, r=res):
                time.sleep(0.05)
                r[1] = c.submit(long_prompt, max_tokens=6, greedy=True,
                                chat=False)

            ts = [threading.Thread(target=d), threading.Thread(target=l)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=300)
        finally:
            cont.close()
        assert all(r is not None and r["status"] == "success" for r in res)
        outs[spec] = [r["response"] for r in res]
    assert outs[True] == outs[False]


# -- chaos: crash / preemption mid-spec-cycle --------------------------------

@pytest.mark.chaos
def test_crash_mid_spec_cycle_salvages_bit_identical(setup):
    """A scheduler crash while verify rows are in flight salvages every
    request with greedy output bit-identical to a fault-free plain run —
    unfetched verify emissions drop exactly like unfetched chunks. Runs
    the crashed leg on BOTH position disciplines: device-derived
    metadata (back-to-back pending verify windows die with the fleet)
    and the legacy host-planned freeze."""
    cfg, params = setup
    prompts = [REPEAT_PROMPT, "the quick brown fox"]

    def serve(spec_decode, rules, devmeta=True):
        faults.disarm()
        cont = _cont(cfg, params, spec_decode,
                     engine_cfg={"prefix_cache_entries": 0,
                                 "spec_device_meta": devmeta})
        try:
            if rules:
                faults.arm(rules)
            out = {
                p: cont.submit(p, max_tokens=12, greedy=True, chat=False)
                for p in prompts
            }
            return out, cont.restarts_total, cont.stats()
        finally:
            faults.disarm()
            cont.close()

    clean, _, _ = serve(False, None)
    assert all(r["status"] == "success" for r in clean.values())
    # crash a later decode launch: by then the repetitive stream has
    # fetched history and speculates, so the crash lands mid-spec-cycle
    for devmeta in (True, False):
        crashed, restarts, st = serve(
            True,
            [faults.FaultRule("decode_launch", "transient", on_call=4)],
            devmeta=devmeta,
        )
        assert restarts >= 1
        assert st["speculative"]["launches"] > 0
        for p in prompts:
            assert crashed[p]["status"] == "success", (devmeta, crashed[p])
            assert crashed[p]["response"] == clean[p]["response"], (
                devmeta, p,
            )


@pytest.mark.chaos
def test_preemption_mid_spec_stays_bit_identical(setup):
    """A pool-pressure preemption landing while the victim speculates
    resumes bit-identical: in-flight verify emissions drop via the
    drop_seq barrier and regenerate after resume."""
    cfg, params = setup

    def serve(spec):
        cont = _cont(
            cfg, params, spec,
            kv_pool_blocks=24, kv_block_size=16, n_slots=2,
            slot_max_seq=256,
            engine_cfg={
                "prefix_cache_entries": 0, "preempt_policy": "recompute",
                "kv_shadow": False, "kv_fabric": False,
            },
        )
        try:
            cont.submit("warm", max_tokens=2, greedy=True, chat=False)
            out = [None, None]
            started = threading.Event()

            def d(c=cont, r=out):
                started.set()
                r[0] = c.submit(REPEAT_PROMPT, max_tokens=24, greedy=True,
                                chat=False)

            def l(c=cont, r=out):
                started.wait(10)
                # wait until the decoder actually DECODES (past prefill)
                # so the pressure ladder can pick it as a victim
                for _ in range(200):
                    st = cont.stats()
                    if (
                        st["occupied"] >= 1
                        and st.get("scheduler", {}).get("prefilling", 0)
                        == 0
                    ):
                        break
                    time.sleep(0.02)
                r[1] = c.submit("z " * 120, max_tokens=4, greedy=True,
                                chat=False)

            ts = [threading.Thread(target=d), threading.Thread(target=l)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=300)
            return out, cont.preempted_total
        finally:
            cont.close()

    plain, pre_plain = serve(False)
    spec, pre_spec = serve(True)
    assert all(r is not None and r["status"] == "success" for r in plain)
    assert all(r is not None and r["status"] == "success" for r in spec)
    # the eviction really landed (otherwise this test pins nothing)
    assert pre_spec > 0 and pre_plain > 0, (pre_spec, pre_plain)
    assert [r["response"] for r in spec] == [r["response"] for r in plain]


# -- draft-model flavor -------------------------------------------------------

def test_draft_model_fleet_accepts_everything_with_identical_draft(setup):
    """cfg-gated draft model sharing the pool: with the draft == the
    target, every draft matches the target's argmax — acceptance is
    total, output identical to the plain fleet."""
    cfg, params = setup
    eng = InferenceEngine(
        cfg, params=params,
        engine_cfg=EngineConfig(
            prefix_cache_entries=0, chunked_prefill=True,
            step_token_budget=64, prefill_buckets=(64, 128, 256),
            spec_decode=True, spec_draft_len=3,
            spec_draft_model="test-llama-tiny",
        ),
    )
    eng.set_draft(cfg, params)  # attached draft wins over the named cfg
    cont = ContinuousEngine(
        eng, n_slots=2, chunk_steps=8, slot_max_seq=512,
        kv_pool_blocks=120, kv_block_size=16, restart_backoff_s=0.01,
    )
    try:
        r = cont.submit("the quick brown fox jumps", max_tokens=12,
                        greedy=True, chat=False)
        st = cont.stats()
    finally:
        cont.close()
    assert r["status"] == "success"
    sb = st["speculative"]
    assert sb["mode"] == "draft_model"
    assert sb["launches"] > 0
    # a perfect draft accepts every drafted token it has budget for
    assert sb["accepted_tokens"] > 0
    # bit-identity against the plain fleet
    cont2 = _cont(cfg, params, False)
    try:
        r2 = cont2.submit("the quick brown fox jumps", max_tokens=12,
                          greedy=True, chat=False)
    finally:
        cont2.close()
    assert r["response"] == r2["response"]


# -- pp shard_map twin --------------------------------------------------------

@pytest.mark.skipif(
    not hasattr(jax, "shard_map"), reason="jax.shard_map unavailable"
)
def test_pp_spec_mixed_step_token_identical(setup, eight_devices):
    """The pipeline's spec-mixed program produces the identical packed
    fetch / slot state as the single-device program on the same
    operands — pp verify rows cannot drift."""
    from distributed_llm_inference_tpu import MeshConfig
    from distributed_llm_inference_tpu.analysis.hlo import _spec_mixed_args
    from distributed_llm_inference_tpu.parallel.mesh import build_mesh
    from distributed_llm_inference_tpu.parallel.pipeline import (
        PipelineBackend,
    )

    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))

    class _Eng:
        pass

    eng = _Eng()
    eng.cfg = cfg
    # NOTE: a class body cannot close over these function locals (plain
    # attribute assignment instead — `class _B: cfg = cfg` NameErrors)
    backend = _Eng()
    backend.cfg = cfg
    backend.params = params
    eng.backend = backend
    mesh = build_mesh(MeshConfig(dp=1, pp=2, tp=1), eight_devices)
    for device_meta in (False, True):
        args = _spec_mixed_args(
            eng, n_spec=1, n_draft=3, chunk=9, device_meta=device_meta
        )
        (acfg, aparams, toks, tok_row, tok_pos, dec_flag, meta, pool,
         table, state, sparams, key, dec_idx, arm, spec), extra = (
            args[:15], args[15:]
        )
        spec_toks, dev = (extra + (None, None))[:2] if extra else (None,
                                                                   None)
        cpu_cfg = acfg.replace(attn_impl="xla")
        packed_s, state_s, _, _ = EP.mixed_step_ragged(
            cpu_cfg, params, toks, tok_row, tok_pos, dec_flag, meta,
            EP.init_pool(cpu_cfg, 10, 16), table, state, sparams, key,
            dec_idx, arm, spec=spec, spec_toks=spec_toks, dev=dev,
        )
        pb = PipelineBackend(cpu_cfg, params, mesh)
        pool_pp = pb.init_paged_pool(10, 16)
        packed_p, state_p, _, _ = pb.mixed_step_ragged(
            toks, tok_row, tok_pos, dec_flag, meta, pool_pp, table,
            state, sparams, key, dec_idx, arm, spec=spec,
            spec_toks=spec_toks, dev=dev,
        )
        assert (
            np.asarray(packed_s).tolist() == np.asarray(packed_p).tolist()
        ), device_meta
        assert (
            np.asarray(state_s.pos).tolist()
            == np.asarray(state_p.pos).tolist()
        )
        assert (
            np.asarray(state_s.token).tolist()
            == np.asarray(state_p.token).tolist()
        )
