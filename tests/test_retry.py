"""Shared retry/backoff policy units (utils/retry.py) — the one copy of
the discipline client.py and the router's upstream calls both follow.
"""

import random

from distributed_llm_inference_tpu.utils import retry


def test_retry_statuses_are_the_serving_edge_contract():
    assert retry.RETRY_STATUSES == (429, 503)
    assert retry.is_retryable(429) and retry.is_retryable(503)
    for code in (200, 400, 404, 500, 502):
        assert not retry.is_retryable(code)


def test_parse_retry_after_numeric_forms():
    assert retry.parse_retry_after("3") == 3.0
    assert retry.parse_retry_after("0.4") == 0.4
    assert retry.parse_retry_after(2) == 2.0
    assert retry.parse_retry_after("-5") == 0.0  # clamp: retry immediately
    assert retry.parse_retry_after("0") == 0.0


def test_parse_retry_after_junk_falls_back_to_none():
    # HTTP-date form and garbage both mean "use local backoff"
    for junk in (None, "", "Wed, 21 Oct 2015 07:28:00 GMT", "soon", object()):
        assert retry.parse_retry_after(junk) is None


def test_backoff_delay_bounds_and_growth():
    rng = random.Random(7)
    for attempt in range(6):
        upper = min(retry.BACKOFF_CAP_S, 0.5 * (2 ** attempt))
        for _ in range(50):
            d = retry.backoff_delay(attempt, base_s=0.5, rng=rng)
            # full jitter on the upper half: [upper/2, upper]
            assert upper / 2 <= d <= upper, (attempt, d)


def test_backoff_delay_caps():
    rng = random.Random(3)
    for _ in range(50):
        assert retry.backoff_delay(30, base_s=0.5, rng=rng) <= retry.BACKOFF_CAP_S


def test_retry_delay_server_directed_wins():
    assert retry.retry_delay(0, retry_after="4") == 4.0
    # junk Retry-After falls through to jittered backoff
    d = retry.retry_delay(0, retry_after="junk", base_s=0.5,
                          rng=random.Random(1))
    assert 0.25 <= d <= 0.5


def test_overload_retry_after_scales_with_depth():
    # empty queue still says "wait a beat", deeper backlog says longer
    assert retry.overload_retry_after(0, 1) == 1
    assert retry.overload_retry_after(8, 8) == 2
    assert retry.overload_retry_after(32, 8) == 5
    hints = [retry.overload_retry_after(d, 4) for d in range(0, 64, 4)]
    assert hints == sorted(hints)  # monotone in depth
    # bounded: a huge backlog never directs an unbounded wait
    assert retry.overload_retry_after(10_000, 1) == int(retry.BACKOFF_CAP_S)
