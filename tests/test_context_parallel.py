"""ContextParallelBackend (sp ring) vs SingleDeviceBackend equivalence.

Prefill ring attention + context-sharded decode must produce the same
greedy tokens and (to fp32 tolerance) the same first-token logits as the
whole-cache single-device path. Runs on the virtual 8-device CPU mesh.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llm_inference_tpu.config import MeshConfig
from distributed_llm_inference_tpu.engine import generate as G
from distributed_llm_inference_tpu.engine.engine import SingleDeviceBackend
from distributed_llm_inference_tpu.models import api as M
from distributed_llm_inference_tpu.models.registry import get_model_config
from distributed_llm_inference_tpu.parallel.context import ContextParallelBackend
from distributed_llm_inference_tpu.parallel.mesh import build_mesh

# fast-tier exclusion: sp shard_map compiles; run the full suite (plain
# `pytest`) to include it
pytestmark = pytest.mark.slow


def _run(backend, cfg, tokens, plen, steps, max_seq):
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(7))
    cache = backend.init_cache(tokens.shape[0], max_seq)
    first, logits, cache = backend.prefill(tokens, jnp.int32(plen), cache, kp, sampling)
    out, n_gen, cache = backend.decode(
        first, cache, jnp.int32(plen), jnp.int32(steps), kd, sampling,
        max_steps=steps,
    )
    return np.asarray(first), np.asarray(logits), np.asarray(out), np.asarray(n_gen)


@pytest.mark.parametrize("sp,plen", [(4, 9), (4, 16), (2, 13)])
def test_cp_backend_matches_single_device(sp, plen):
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    bucket, steps, max_seq = 16, 6, 48
    rng = np.random.default_rng(1)
    ids = rng.integers(3, cfg.vocab_size, size=(1, plen))
    tokens = jnp.asarray(
        np.pad(ids, ((0, 0), (0, bucket - plen)), constant_values=cfg.pad_token_id),
        jnp.int32,
    )

    ref_first, ref_logits, ref_out, ref_n = _run(
        SingleDeviceBackend(cfg, params), cfg, tokens, plen, steps, max_seq
    )

    mesh = build_mesh(MeshConfig(sp=sp), jax.devices())
    cp = ContextParallelBackend(cfg, params, mesh)
    got_first, got_logits, got_out, got_n = _run(
        cp, cfg, tokens, plen, steps, max_seq
    )

    np.testing.assert_allclose(got_logits, ref_logits, rtol=1e-4, atol=1e-4)
    assert got_first.tolist() == ref_first.tolist()
    assert got_out.tolist() == ref_out.tolist()
    assert got_n.tolist() == ref_n.tolist()


def test_cp_backend_eos_early_exit():
    """EOS mid-decode stops the CP loop exactly like the dense path."""
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(3))
    bucket, plen, steps, max_seq = 16, 10, 8, 48
    tokens = jnp.asarray([[5] * plen + [cfg.pad_token_id] * (bucket - plen)], jnp.int32)

    ref = _run(SingleDeviceBackend(cfg, params), cfg, tokens, plen, steps, max_seq)
    mesh = build_mesh(MeshConfig(sp=4), jax.devices())
    got = _run(
        ContextParallelBackend(cfg, params, mesh), cfg, tokens, plen, steps, max_seq
    )
    assert got[2].tolist() == ref[2].tolist()
    assert got[3].tolist() == ref[3].tolist()


def test_cp_backend_serving_engine():
    """Full engine path (tokenize -> prefill -> decode -> detokenize) over sp."""
    from distributed_llm_inference_tpu import EngineConfig, create_engine

    engine = create_engine(
        "test-llama-tiny",
        mesh_cfg=MeshConfig(sp=4),
        engine_cfg=EngineConfig(prefill_buckets=(64, 128)),
    )
    r = engine.generate("Hello ring", max_tokens=5, greedy=True, seed=0)
    assert r["status"] == "success", r
    assert r["backend"] == "context-parallel"
    assert r["tokens_generated"] <= 5


@pytest.mark.parametrize("sp,pp", [(2, 1), (2, 2)])
def test_gpt2_sp_matches_single_device(eight_devices, sp, pp):
    """Round-5: gpt2 rides context parallelism through the shared
    attn_hook seam (its learned position rows are absolute — exactly the
    coordinate the ring/merge masks key on), alone and composed with
    pp. Greedy tokens match the single-device path."""
    cfg = get_model_config("test-gpt2-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    bucket, plen, steps, max_seq = 16, 13, 6, 48
    rng = np.random.default_rng(1)
    ids = rng.integers(3, 250, size=(1, plen))
    tokens = jnp.asarray(
        np.pad(ids, ((0, 0), (0, bucket - plen)),
               constant_values=cfg.pad_token_id),
        jnp.int32,
    )
    ref = _run(SingleDeviceBackend(cfg, params), cfg, tokens, plen, steps, max_seq)
    mesh = build_mesh(MeshConfig(sp=sp, pp=pp), jax.devices()[: sp * pp])
    got = _run(
        ContextParallelBackend(cfg, params, mesh), cfg, tokens, plen, steps,
        max_seq,
    )
    np.testing.assert_allclose(got[1], ref[1], rtol=1e-4, atol=1e-4)
    assert got[0].tolist() == ref[0].tolist()
    assert got[2].tolist() == ref[2].tolist()


def test_cp_backend_rejects_trivial_sp_and_bad_bucket():
    llama_cfg = get_model_config("test-llama-tiny")
    llama_params = M.init_params(llama_cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(MeshConfig(sp=4), jax.devices())
    with pytest.raises(ValueError, match="sp >= 2"):
        ContextParallelBackend(
            llama_cfg, llama_params, build_mesh(MeshConfig(sp=1), jax.devices())
        )
    cp = ContextParallelBackend(llama_cfg, llama_params, mesh)
    with pytest.raises(ValueError, match="not divisible by sp"):
        cp.prefill(  # bucket 18 % sp 4 != 0
            jnp.zeros((1, 18), jnp.int32), jnp.int32(5),
            cp.init_cache(1, 48), jax.random.PRNGKey(0),
            G.default_sampling(greedy=True),
        )


def test_cp_prefill_heavy_shard_does_not_overflow():
    """Prompt filling one shard's whole chunk + decode to the cache limit:
    least-filled placement must keep going where pos%sp round-robin would
    overflow the prefill-heavy shard (code-review regression)."""
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    sp, bucket, max_seq = 4, 16, 24
    # Tc = 4, Sc = 24/4+1 = 7. plen=5: shard 0 exits prefill FULL (4 slots),
    # shard 1 has 1, shards 2-3 empty. 18 decode steps under pos%sp
    # round-robin would push shard 0 to 4+5 > Sc and truncate; least-filled
    # placement keeps max fill at ceil(23/4)=6 <= Sc.
    plen = 5
    steps = max_seq - plen - 1
    tokens = jnp.asarray(
        [[5] * plen + [cfg.pad_token_id] * (bucket - plen)], jnp.int32
    )

    ref = _run(SingleDeviceBackend(cfg, params), cfg, tokens, plen, steps, max_seq)
    mesh = build_mesh(MeshConfig(sp=sp), jax.devices())
    got = _run(
        ContextParallelBackend(cfg, params, mesh), cfg, tokens, plen, steps, max_seq
    )
    assert got[2].tolist() == ref[2].tolist()
    assert got[3].tolist() == ref[3].tolist()


@pytest.mark.parametrize("sp,plen", [(2, 13), (4, 9)])
def test_ulysses_matches_single_device(sp, plen):
    """Ulysses (all-to-all head-scatter) prefill == single device: same
    greedy decode tokens, same first-token logits. test-llama-tiny has
    n_kv_heads=2, so sp=4 uses an MHA variant (kv heads must scatter)."""
    cfg = get_model_config("test-llama-tiny")
    if cfg.n_kv_heads % sp:
        cfg = cfg.replace(n_kv_heads=cfg.n_heads)  # MHA so heads split
    params = M.init_params(cfg, jax.random.PRNGKey(2))
    bucket, steps, max_seq = 16, 6, 48
    rng = np.random.default_rng(3)
    ids = rng.integers(3, cfg.vocab_size, size=(1, plen))
    tokens = jnp.asarray(
        np.pad(ids, ((0, 0), (0, bucket - plen)), constant_values=cfg.pad_token_id),
        jnp.int32,
    )

    ref_first, ref_logits, ref_out, ref_n = _run(
        SingleDeviceBackend(cfg, params), cfg, tokens, plen, steps, max_seq
    )
    mesh = build_mesh(MeshConfig(sp=sp), jax.devices())
    upb = ContextParallelBackend(cfg, params, mesh, sp_strategy="ulysses")
    got_first, got_logits, got_out, got_n = _run(
        upb, cfg, tokens, plen, steps, max_seq
    )
    np.testing.assert_allclose(got_logits, ref_logits, rtol=2e-4, atol=2e-5)
    assert int(got_first[0]) == int(ref_first[0])
    np.testing.assert_array_equal(got_out, ref_out)
    np.testing.assert_array_equal(got_n, ref_n)


def test_ulysses_rejects_indivisible_heads():
    cfg = get_model_config("test-llama-tiny")  # n_kv_heads=2
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(MeshConfig(sp=4), jax.devices())
    with pytest.raises(ValueError, match="ulysses"):
        ContextParallelBackend(cfg, params, mesh, sp_strategy="ulysses")
    with pytest.raises(ValueError, match="sp_strategy"):
        ContextParallelBackend(cfg, params, mesh, sp_strategy="spiral")


def test_ulysses_tp_aware_guard_and_runtime_gate():
    """tp shards the head axis, so the ulysses divisibility check must use
    LOCAL head counts; and --sp-strategy without sp>1 fails loudly."""
    from distributed_llm_inference_tpu.runtime import create_backend

    cfg = get_model_config("test-llama-tiny").replace(n_kv_heads=4)  # MHA
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    # 4 heads / tp=2 = 2 local heads, sp=4 > 2 -> loud ValueError
    mesh = build_mesh(MeshConfig(sp=4, tp=2), jax.devices())
    with pytest.raises(ValueError, match="LOCAL"):
        ContextParallelBackend(cfg, params, mesh, sp_strategy="ulysses")

    with pytest.raises(ValueError, match="sp > 1"):
        create_backend(cfg, mesh_cfg=MeshConfig(), sp_strategy="ulysses",
                       params=params)


@pytest.mark.slow
def test_sp_full_solo_surface_matches_single_device(eight_devices):
    """Round-4: the solo request-surface variants — repetition penalty,
    OpenAI penalties, logit_bias, per-token logprobs — serve on the sp
    ring, token-identical to the single-device engine (replicated logits
    make every variant a local op, same as the pp backend)."""
    from distributed_llm_inference_tpu import (
        EngineConfig, MeshConfig, create_engine, get_model_config,
    )
    from distributed_llm_inference_tpu.engine.engine import InferenceEngine
    from distributed_llm_inference_tpu.models import api as M

    cfg = get_model_config("test-llama-tiny", eos_token_id=-1)
    params = M.init_params(cfg, jax.random.PRNGKey(5))
    ecfg = EngineConfig(prefill_buckets=(32, 64))
    sd = InferenceEngine(cfg, params=params, engine_cfg=ecfg)
    sp = create_engine(
        cfg, mesh_cfg=MeshConfig(sp=2), params=params, engine_cfg=ecfg,
    )
    assert sp.backend.name == "context-parallel"
    prompt = "the quick brown fox"
    for kw in (
        dict(repetition_penalty=1.3),
        dict(frequency_penalty=1.0, presence_penalty=0.3),
        dict(logit_bias={"17": 100.0}),
        dict(logprobs=True),
        dict(repetition_penalty=1.2, logit_bias={"55": 2.5}),
    ):
        a = sd.generate(prompt, max_tokens=6, greedy=True, chat=False, **kw)
        b = sp.generate(prompt, max_tokens=6, greedy=True, chat=False, **kw)
        assert a["status"] == b["status"] == "success", (kw, b)
        assert a["response"] == b["response"], kw
        if "logprobs" in kw:
            # merged-softmax reduction order differs from the monolithic
            # softmax by ~1 ulp; tokens are identical
            np.testing.assert_allclose(
                a["token_logprobs"], b["token_logprobs"], atol=1e-5
            )


@pytest.mark.parametrize("strategy", ["ring", "ulysses"])
@pytest.mark.slow
def test_sp_sliding_window_matches_single_device(eight_devices, strategy):
    """Round-4: uniform sliding-window attention (Mistral-style) composes
    with context parallelism — the ring/ulysses masks and the cp decode
    slot mask all window by absolute position. Greedy tokens match the
    single-device windowed engine exactly."""
    from distributed_llm_inference_tpu import (
        EngineConfig, MeshConfig, create_engine, get_model_config,
    )
    from distributed_llm_inference_tpu.engine.engine import InferenceEngine
    from distributed_llm_inference_tpu.models import api as M

    cfg = get_model_config("test-llama-tiny", eos_token_id=-1).replace(
        attn_window=7
    )
    params = M.init_params(cfg, jax.random.PRNGKey(6))
    ecfg = EngineConfig(prefill_buckets=(32, 64))
    sd = InferenceEngine(cfg, params=params, engine_cfg=ecfg)
    sp = create_engine(
        cfg, mesh_cfg=MeshConfig(sp=2), params=params, engine_cfg=ecfg,
        sp_strategy=strategy,
    )
    for prompt in ("the quick brown fox jumps over a dog", "hello there"):
        a = sd.generate(prompt, max_tokens=10, greedy=True, chat=False)
        b = sp.generate(prompt, max_tokens=10, greedy=True, chat=False)
        assert a["status"] == b["status"] == "success"
        assert a["response"] == b["response"]


@pytest.mark.slow
def test_sp_softcap_and_scale_override_match_single_device(eight_devices):
    """Gemma-2-style attention softcapping + query-scale override on the
    sp ring: elementwise pre-mask capping commutes with the log-sum-exp
    merge, so tokens match single-device exactly."""
    from distributed_llm_inference_tpu import (
        EngineConfig, MeshConfig, create_engine, get_model_config,
    )
    from distributed_llm_inference_tpu.engine.engine import InferenceEngine
    from distributed_llm_inference_tpu.models import api as M

    cfg = get_model_config("test-llama-tiny", eos_token_id=-1).replace(
        attn_softcap=20.0, query_scale_override=8
    )
    params = M.init_params(cfg, jax.random.PRNGKey(7))
    ecfg = EngineConfig(prefill_buckets=(32,))
    sd = InferenceEngine(cfg, params=params, engine_cfg=ecfg)
    sp = create_engine(
        cfg, mesh_cfg=MeshConfig(sp=2), params=params, engine_cfg=ecfg,
    )
    a = sd.generate("cap these scores", max_tokens=8, greedy=True, chat=False)
    b = sp.generate("cap these scores", max_tokens=8, greedy=True, chat=False)
    assert a["status"] == b["status"] == "success"
    assert a["response"] == b["response"]


@pytest.mark.parametrize("name", ["test-gemma2-tiny", "test-gemma3-tiny"])
def test_sp_per_layer_window_pattern_matches_single_device(
    eight_devices, name
):
    """Round-5: per-layer window patterns — BOTH spellings (Gemma-2's
    pattern='even', Gemma-3's layer-type list) — compose with context
    parallelism: each layer's width reaches the ring/merge masks as a
    traced scalar derived from the stacked window_flag leaf
    (ContextParallelBackend._layer_window). Greedy tokens must match the
    single-device path, windows binding (attn_window < prompt)."""
    cfg = get_model_config(name, eos_token_id=-1).replace(attn_window=4)
    params = M.init_params(cfg, jax.random.PRNGKey(2))
    bucket, plen, steps, max_seq = 16, 13, 6, 48
    rng = np.random.default_rng(9)
    ids = rng.integers(3, cfg.vocab_size, size=(1, plen))
    tokens = jnp.asarray(
        np.pad(ids, ((0, 0), (0, bucket - plen)),
               constant_values=cfg.pad_token_id),
        jnp.int32,
    )

    ref = _run(SingleDeviceBackend(cfg, params), cfg, tokens, plen, steps, max_seq)
    mesh = build_mesh(MeshConfig(sp=2), jax.devices())
    got = _run(
        ContextParallelBackend(cfg, params, mesh), cfg, tokens, plen, steps,
        max_seq,
    )
    np.testing.assert_allclose(got[1], ref[1], rtol=1e-4, atol=1e-4)
    assert got[0].tolist() == ref[0].tolist()
    assert got[2].tolist() == ref[2].tolist()
    assert got[3].tolist() == ref[3].tolist()


@pytest.mark.parametrize("strategy,sp", [("ring", 4), ("ulysses", 2)])
def test_sp_ragged_batch_matches_single_device(eight_devices, strategy, sp):
    """Round-5: ragged (left-padded, per-row valid_start) batches ride the
    sp backends — valid_start flows through the ring/ulysses prefill masks
    and the cp decode slot mask as a per-row floor on absolute positions,
    so the queue-coalesced batched serving path shards over sp."""
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    bucket, steps, max_seq = 16, 6, 48
    row_lens = [9, 16, 12, 5]
    rng = np.random.default_rng(4)
    rows = []
    for n in row_lens:
        ids = rng.integers(3, cfg.vocab_size, size=n)
        rows.append(
            np.concatenate([np.full(bucket - n, cfg.pad_token_id), ids])
        )
    tokens = jnp.asarray(np.stack(rows), jnp.int32)
    valid_start = jnp.asarray([bucket - n for n in row_lens], jnp.int32)
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(7))

    def run(backend):
        cache = backend.init_cache(tokens.shape[0], max_seq)
        first, logits, cache = backend.prefill(
            tokens, jnp.int32(bucket), cache, kp, sampling,
            valid_start=valid_start,
        )
        out, n_gen, _ = backend.decode(
            first, cache, jnp.int32(bucket), jnp.int32(steps), kd, sampling,
            valid_start, max_steps=steps,
        )
        return (np.asarray(first), np.asarray(logits), np.asarray(out),
                np.asarray(n_gen))

    ref = run(SingleDeviceBackend(cfg, params))
    mesh = build_mesh(MeshConfig(sp=sp), jax.devices())
    got = run(ContextParallelBackend(cfg, params, mesh, sp_strategy=strategy))
    np.testing.assert_allclose(got[1], ref[1], rtol=1e-4, atol=1e-4)
    assert got[0].tolist() == ref[0].tolist()
    assert got[2].tolist() == ref[2].tolist()
    assert got[3].tolist() == ref[3].tolist()


def test_sp_generate_batch_matches_single_device(eight_devices):
    """Engine-level: the queue-coalesced batched path (generate_batch)
    serves on an sp mesh, row-identical to the single-device engine."""
    from distributed_llm_inference_tpu import (
        EngineConfig, MeshConfig, create_engine, get_model_config,
    )
    from distributed_llm_inference_tpu.engine.engine import InferenceEngine

    cfg = get_model_config("test-llama-tiny", eos_token_id=-1)
    params = M.init_params(cfg, jax.random.PRNGKey(5))
    ecfg = EngineConfig(prefill_buckets=(32, 64))
    sd = InferenceEngine(cfg, params=params, engine_cfg=ecfg)
    sp = create_engine(
        cfg, mesh_cfg=MeshConfig(sp=2), params=params, engine_cfg=ecfg,
    )
    assert sp.backend.name == "context-parallel"
    prompts = [
        "the quick brown fox",
        "hi",
        "a much longer prompt with several words in it",
    ]
    a = sd.generate_batch(prompts, max_tokens=6, greedy=True, chat=False)
    b = sp.generate_batch(prompts, max_tokens=6, greedy=True, chat=False)
    assert a["status"] == b["status"] == "success", (a, b)
    assert [r["response"] for r in a["results"]] == [
        r["response"] for r in b["results"]
    ]


@pytest.mark.parametrize(
    "mesh_kw,strategy",
    [
        (dict(sp=2, pp=2), "ring"),
        (dict(sp=2, pp=2, tp=2), "ring"),
        (dict(sp=2, pp=2), "ulysses"),
    ],
)
def test_sp_pp_matches_single_device(eight_devices, mesh_kw, strategy):
    """Round-5: sp x pp composes — layers shard over pp (the gated
    microstep ring, activations ppermute between stages) while the
    sequence stays sharded over sp (ring prefill / log-sum-exp merge
    decode inside each stage's layer scan) and embed/lm_head take the
    vocab-sharded pp forms. Greedy tokens match the single-device path;
    tp composes on top."""
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    bucket, plen, steps, max_seq = 16, 13, 6, 48
    rng = np.random.default_rng(1)
    ids = rng.integers(3, cfg.vocab_size, size=(1, plen))
    tokens = jnp.asarray(
        np.pad(ids, ((0, 0), (0, bucket - plen)),
               constant_values=cfg.pad_token_id),
        jnp.int32,
    )

    ref = _run(SingleDeviceBackend(cfg, params), cfg, tokens, plen, steps, max_seq)
    n_dev = 2 * 2 * mesh_kw.get("tp", 1)
    mesh = build_mesh(MeshConfig(**mesh_kw), jax.devices()[:n_dev])
    got = _run(
        ContextParallelBackend(cfg, params, mesh, sp_strategy=strategy),
        cfg, tokens, plen, steps, max_seq,
    )
    np.testing.assert_allclose(got[1], ref[1], rtol=1e-4, atol=1e-4)
    assert got[0].tolist() == ref[0].tolist()
    assert got[2].tolist() == ref[2].tolist()
    assert got[3].tolist() == ref[3].tolist()


def test_sp_pp_kv_quant_and_ragged(eight_devices):
    """sp x pp x int8-KV serves ragged batches: the quantized chunks ride
    the ring inside each stage, writes gate on (owner shard & own
    microstep), and valid_start masks per-row pad keys — token-identical
    to the single-device int8 ragged path."""
    cfg = get_model_config("test-llama-tiny", kv_quant="int8")
    params = M.init_params(cfg, jax.random.PRNGKey(2))
    bucket, steps, max_seq = 16, 5, 48
    row_lens = [9, 16, 12, 5]
    rng = np.random.default_rng(6)
    rows = [
        np.concatenate(
            [np.full(bucket - n, cfg.pad_token_id),
             rng.integers(3, cfg.vocab_size, size=n)]
        )
        for n in row_lens
    ]
    tokens = jnp.asarray(np.stack(rows), jnp.int32)
    valid_start = jnp.asarray([bucket - n for n in row_lens], jnp.int32)
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(8))

    def run(be):
        cache = be.init_cache(tokens.shape[0], max_seq)
        first, logits, cache = be.prefill(
            tokens, jnp.int32(bucket), cache, kp, sampling,
            valid_start=valid_start,
        )
        out, n_gen, _ = be.decode(
            first, cache, jnp.int32(bucket), jnp.int32(steps), kd, sampling,
            valid_start, max_steps=steps,
        )
        return np.asarray(first), np.asarray(out), np.asarray(n_gen)

    ref = run(SingleDeviceBackend(cfg, params))
    mesh = build_mesh(MeshConfig(sp=2, pp=2), jax.devices()[:4])
    got = run(ContextParallelBackend(cfg, params, mesh))
    assert got[0].tolist() == ref[0].tolist()
    assert got[1].tolist() == ref[1].tolist()
    assert got[2].tolist() == ref[2].tolist()


def test_sp_pp_serving_engine(eight_devices):
    """Engine path over sp=2 x pp=2: same greedy text as single device;
    /workers reports pipeline stages spanning their context rings."""
    from distributed_llm_inference_tpu import (
        EngineConfig, create_engine, get_model_config,
    )
    from distributed_llm_inference_tpu.engine.engine import InferenceEngine
    from distributed_llm_inference_tpu.models import api as M_

    cfg = get_model_config("test-llama-tiny", eos_token_id=-1)
    params = M_.init_params(cfg, jax.random.PRNGKey(5))
    ecfg = EngineConfig(prefill_buckets=(32, 64))
    sd = InferenceEngine(cfg, params=params, engine_cfg=ecfg)
    eng = create_engine(
        cfg, mesh_cfg=MeshConfig(sp=2, pp=2), params=params, engine_cfg=ecfg,
    )
    a = sd.generate("the quick brown fox", max_tokens=6, greedy=True, chat=False)
    b = eng.generate("the quick brown fox", max_tokens=6, greedy=True, chat=False)
    assert a["status"] == b["status"] == "success"
    assert a["response"] == b["response"]
    h = eng.backend.health()
    assert len(h) == 2 and h[0]["role"] == "pipeline-stage+context-ring"


def test_sp_pp_uneven_layers_reject(eight_devices):
    cfg = get_model_config("test-llama-tiny").replace(n_layers=3)
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(MeshConfig(sp=2, pp=2), jax.devices()[:4])
    with pytest.raises(NotImplementedError, match="divisible"):
        ContextParallelBackend(cfg, params, mesh)


@pytest.mark.parametrize("name", ["test-llama-tiny", "test-gpt2-tiny"])
def test_sp_score_matches_single_device(eight_devices, name):
    """Echo-scoring on the sp ring (both families): per-token logprobs of
    a teacher-forced prompt match the single-device engine. On sp x pp
    the capability gate rejects cleanly as invalid_request (the score
    program is whole-model per ring member), not a 500."""
    from distributed_llm_inference_tpu import (
        EngineConfig, create_engine, get_model_config,
    )
    from distributed_llm_inference_tpu.engine.engine import InferenceEngine
    from distributed_llm_inference_tpu.models import api as M_

    cfg = get_model_config(name)
    params = M_.init_params(cfg, jax.random.PRNGKey(4))
    ecfg = EngineConfig(prefill_buckets=(32,))
    sd = InferenceEngine(cfg, params=params, engine_cfg=ecfg)
    sp = create_engine(
        cfg, mesh_cfg=MeshConfig(sp=2), params=params, engine_cfg=ecfg,
    )
    text = "the quick brown fox jumps"
    a = sd.score(text)
    b = sp.score(text)
    assert a["status"] == b["status"] == "success", (a, b)
    np.testing.assert_allclose(
        np.asarray(b["token_logprobs"][1:], np.float64),
        np.asarray(a["token_logprobs"][1:], np.float64),
        atol=1e-4,
    )

    if cfg.arch == "llama":  # composed-mesh gate: one check suffices
        spp = create_engine(
            cfg, mesh_cfg=MeshConfig(sp=2, pp=2), params=params,
            engine_cfg=ecfg,
        )
        assert spp.backend.supports_score is False
        r = spp.score(text)
        assert r["status"] == "failed"
        assert r.get("error_type") == "invalid_request", r
