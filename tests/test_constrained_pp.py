"""Constrained decoding on the multi-device backends and the continuous
fleet: bit-exact greedy equivalence single-device vs the pp ring (and the
1F1B backend's plain-ring dispatch), every-path property coverage, and
mixed constrained/unconstrained slots coexisting mid-decode.

Fast-tier exclusion: pp-mesh + fleet compiles per variant; run the full
suite (plain `pytest`) to include it.
"""

import json
import re
import threading

import numpy as np
import pytest

import jax

from distributed_llm_inference_tpu import (
    EngineConfig, MeshConfig, create_engine, get_model_config,
)
from distributed_llm_inference_tpu.engine.continuous import ContinuousEngine
from distributed_llm_inference_tpu.engine.engine import InferenceEngine
from distributed_llm_inference_tpu.models import api as M

pytestmark = pytest.mark.slow

needs_shard_map = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="this jax build has no jax.shard_map (pp backends unavailable)",
)

SCHEMA = {
    "type": "object",
    "properties": {"name": {"type": "string"}, "age": {"type": "integer"}},
    "required": ["name", "age"],
}


@pytest.fixture(scope="module")
def pair():
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(5))
    ecfg = EngineConfig(prefill_buckets=(32, 64))
    sd = InferenceEngine(cfg, params=params, engine_cfg=ecfg)
    pp = create_engine(cfg, mesh_cfg=MeshConfig(pp=2), params=params,
                       engine_cfg=ecfg)
    return sd, pp


@needs_shard_map
def test_pp_greedy_bit_exact(pair):
    """Acceptance: bit-exact greedy equivalence single-device vs the pp
    ring on the 8-virtual-device CPU mesh, for every constraint kind."""
    sd, pp = pair
    for spec in (
        {"regex": "(red|green|blue|[0-9]{1,3})"},
        {"choices": ["alpha", "beta"]},
        {"json_schema": SCHEMA},
    ):
        a = sd.generate("the answer is", max_tokens=80, greedy=True,
                        chat=False, constraint=spec)
        b = pp.generate("the answer is", max_tokens=80, greedy=True,
                        chat=False, constraint=spec)
        assert a["status"] == b["status"] == "success"
        assert a["response"] == b["response"], spec


@needs_shard_map
def test_pp_sampled_satisfies_constraint(pair):
    _, pp = pair
    pat = r"[0-9]{2,4}"
    for seed in range(4):
        r = pp.generate("n:", max_tokens=30, chat=False, seed=seed,
                        temperature=1.8, top_k=0, top_p=1.0,
                        constraint={"regex": pat})
        assert re.fullmatch(pat, r["response"]), r["response"]


@needs_shard_map
def test_pp_schema_parses(pair):
    _, pp = pair
    r = pp.generate("json:", max_tokens=120, greedy=True, chat=False,
                    constraint={"json_schema": SCHEMA})
    obj = json.loads(r["response"])
    assert isinstance(obj["name"], str) and isinstance(obj["age"], int)


@needs_shard_map
def test_1f1b_routes_constraint_to_plain_ring(pair):
    sd, _ = pair
    cfg = get_model_config("test-llama-tiny")
    params = sd.backend.params
    mb = create_engine(cfg, mesh_cfg=MeshConfig(pp=2), microbatches=2,
                       params=params,
                       engine_cfg=EngineConfig(prefill_buckets=(32, 64)))
    assert mb.backend.name == "pipeline-1f1b"
    spec = {"regex": "(red|green|blue|[0-9]{1,3})"}
    a = sd.generate("the answer is", max_tokens=40, greedy=True, chat=False,
                    constraint=spec)
    b = mb.generate("the answer is", max_tokens=40, greedy=True, chat=False,
                    constraint=spec)
    assert a["response"] == b["response"]


# -- continuous fleet (single-device backend, no shard_map needed) -----------

@pytest.fixture(scope="module")
def solo_engine():
    cfg = get_model_config("test-llama-tiny")
    return InferenceEngine(cfg, engine_cfg=EngineConfig(prefill_buckets=(32, 64)))


def test_continuous_mixed_slots(solo_engine):
    """Constrained and unconstrained requests coexist mid-decode in one
    fleet; every constrained result satisfies its OWN constraint and the
    unconstrained result matches its solo greedy run."""
    solo_free = solo_engine.generate(
        "tell me something", max_tokens=10, greedy=True, chat=False
    )
    cont = ContinuousEngine(solo_engine, n_slots=2, chunk_steps=4,
                            max_queue=16)
    try:
        results = {}
        lock = threading.Lock()

        def run(name, prompt, **kw):
            r = cont.submit(prompt, **kw)
            with lock:
                results[name] = r

        jobs = [
            ("color", "pick a color:", dict(
                max_tokens=20, greedy=True, chat=False,
                constraint={"regex": "(red|green|blue)"})),
            ("free", "tell me something", dict(
                max_tokens=10, greedy=True, chat=False)),
            ("digits", "digits:", dict(
                max_tokens=20, greedy=True, chat=False,
                constraint={"regex": "[0-9]{2,3}x"})),
            ("json", "emit:", dict(
                max_tokens=140, greedy=True, chat=False,
                constraint={"json_schema": SCHEMA})),
            ("choice", "pick:", dict(
                max_tokens=20, temperature=1.5, top_k=0, top_p=1.0,
                chat=False, constraint={"choices": ["on", "off"]})),
        ]
        threads = [
            threading.Thread(target=run, args=(n, p), kwargs=kw)
            for n, p, kw in jobs
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert set(results) == {n for n, _, _ in jobs}
        for name, r in results.items():
            assert r["status"] == "success", (name, r)
        assert re.fullmatch("red|green|blue", results["color"]["response"])
        assert re.fullmatch("[0-9]{2,3}x", results["digits"]["response"])
        obj = json.loads(results["json"]["response"])
        assert isinstance(obj["age"], int)
        assert results["choice"]["response"] in ("on", "off")
        # the unconstrained tenant decoded EXACTLY its solo stream even
        # while constrained tenants shared the fleet
        assert results["free"]["response"] == solo_free["response"]
        assert results["free"].get("constrained") is None
        assert results["color"].get("constrained") is True
        # residency drained back to zero active
        st = cont.stats()
        assert st["constraints"]["active"] == 0
    finally:
        cont.close()


def test_continuous_constraint_reuse_and_release(solo_engine):
    """Same constraint admitted twice reuses the resident table rows
    (refcount), and release frees them for compaction."""
    cont = ContinuousEngine(solo_engine, n_slots=2, chunk_steps=4,
                            max_queue=16)
    try:
        spec = {"choices": ["yes", "no"]}
        for _ in range(2):
            r = cont.submit("q:", max_tokens=15, greedy=True, chat=False,
                            constraint=spec)
            assert r["response"] in ("yes", "no")
        st = cont.stats()["constraints"]
        assert st["resident"] == 1 and st["active"] == 0
    finally:
        cont.close()


def test_continuous_paged_falls_back_solo(solo_engine):
    """constraint x paged fleet: served via the solo fallback (correct,
    just not fleet-batched) — never a failure, never unvalidated output."""
    cont = ContinuousEngine(solo_engine, n_slots=2, chunk_steps=4,
                            max_queue=16, kv_pool_blocks=40, kv_block_size=16)
    try:
        r = cont.submit("pick:", max_tokens=20, greedy=True, chat=False,
                        constraint={"regex": "(red|green|blue)"})
        assert r["status"] == "success"
        assert re.fullmatch("red|green|blue", r["response"])
        # solo fallback: the envelope is the solo engine's, not the fleet's
        assert r.get("continuous") is None
    finally:
        cont.close()


def test_continuous_streaming_constrained(solo_engine):
    """A constrained streaming request: deltas concatenate to the exact
    final (constraint-satisfying) response."""
    cont = ContinuousEngine(solo_engine, n_slots=2, chunk_steps=4,
                            max_queue=16)
    try:
        deltas = []
        final = None
        for ev in cont.stream("pick a color:", max_tokens=20, greedy=True,
                              chat=False,
                              constraint={"regex": "(red|green|blue)"}):
            if ev.get("done"):
                final = ev
                break
            deltas.append(ev.get("delta", ""))
        assert final is not None and final["status"] == "success"
        assert "".join(deltas) == final["response"]
        assert re.fullmatch("red|green|blue", final["response"])
    finally:
        cont.close()


def test_fleet_table_overflow_routes_solo(solo_engine):
    """A constraint whose DFA can never fit the fleet table serves via the
    solo engine instead of deadlocking the queue."""
    cont = ContinuousEngine(solo_engine, n_slots=2, chunk_steps=4,
                            max_queue=16)
    # shrink the fleet table so the schema constraint cannot ever fit
    cont._ctable.max_states = 8
    try:
        r = cont.submit("emit:", max_tokens=140, greedy=True, chat=False,
                        constraint={"json_schema": SCHEMA})
        assert r["status"] == "success"
        assert isinstance(json.loads(r["response"])["age"], int)
        assert r.get("continuous") is None  # solo envelope
    finally:
        cont.close()
