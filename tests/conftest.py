"""Test harness: force the CPU backend with 8 virtual devices so N-stage
pipeline tests run on any host with no TPU (SURVEY.md §4). Must run before
any test module initializes a JAX backend."""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"
# Run every Pallas kernel (flash / paged decode / ragged) in interpret
# mode regardless of backend (ops/flash_attention.resolve_interpret reads
# this), so tier-1 exercises the kernels' exact math on CPU — the ragged
# kernel's bit-exactness suite (tests/test_ragged_attention.py) depends
# on it. Set to "0" to force real Mosaic lowering on a TPU host.
os.environ.setdefault("DLI_PALLAS_INTERPRET", "1")

import jax

# The axon site package pins JAX_PLATFORMS=axon at interpreter start; the
# config update (pre-backend-init) wins over the env var.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

import pytest


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {devs}"
    return devs
