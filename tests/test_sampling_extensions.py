"""HF-parity tests for the sampling extensions: repetition penalty + min-p.

The HF logits processors (RepetitionPenaltyLogitsProcessor, MinPLogitsWarper)
are the behavioral spec, checked directly on logits; then end-to-end greedy
generation with a repetition penalty is checked token-for-token against HF
`generate` on a tiny-random llama — across the solo, pipeline, and
continuous-slots paths.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from distributed_llm_inference_tpu import EngineConfig, MeshConfig, get_model_config
from distributed_llm_inference_tpu.engine import generate as G
from distributed_llm_inference_tpu.engine.continuous import ContinuousEngine
from distributed_llm_inference_tpu.engine.engine import InferenceEngine
from distributed_llm_inference_tpu.models.convert import params_from_hf_model
from distributed_llm_inference_tpu.ops.sampling import (
    apply_repetition_penalty,
    min_p_filter,
    sample_token,
)


def test_repetition_penalty_matches_hf_processor():
    from transformers import RepetitionPenaltyLogitsProcessor

    rng = np.random.default_rng(0)
    logits = rng.normal(size=(2, 64)).astype(np.float32)
    input_ids = np.array([[3, 7, 7, 12], [1, 2, 3, 4]], dtype=np.int64)
    proc = RepetitionPenaltyLogitsProcessor(penalty=1.7)
    want = proc(torch.from_numpy(input_ids), torch.from_numpy(logits)).numpy()

    presence = np.zeros((2, 64), bool)
    for b in range(2):
        presence[b, input_ids[b]] = True
    got = apply_repetition_penalty(
        jnp.asarray(logits), jnp.asarray(presence), jnp.float32(1.7)
    )
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6, atol=1e-6)


def test_min_p_matches_hf_warper():
    from transformers import MinPLogitsWarper

    rng = np.random.default_rng(1)
    logits = rng.normal(size=(3, 64)).astype(np.float32) * 3
    warper = MinPLogitsWarper(min_p=0.2)
    want = warper(None, torch.from_numpy(logits)).numpy()
    got = np.asarray(min_p_filter(jnp.asarray(logits), jnp.float32(0.2)))
    # both mark removed tokens with a large negative; compare the KEEP masks
    # and the surviving values
    np.testing.assert_array_equal(np.isfinite(want) & (want > -1e30),
                                  got > -1e30)
    keep = got > -1e30
    np.testing.assert_allclose(got[keep], logits[keep])


def test_min_p_in_fused_sampler_restricts_support():
    """With a sharp distribution and min_p, only the dominant tokens can be
    drawn (the fused sampler's keep-mask matches the spec filter)."""
    logits = jnp.asarray([[10.0, 9.9, 0.0, -5.0] + [-20.0] * 60], jnp.float32)
    draws = set()
    for i in range(50):
        t = sample_token(
            jax.random.PRNGKey(i), logits,
            jnp.float32(1.0), jnp.int32(0), jnp.float32(1.0),
            jnp.bool_(False), jnp.float32(0.5), None, None,
        )
        draws.add(int(t[0]))
    assert draws <= {0, 1}, draws


def _tiny_hf_llama():
    cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(3)
    m = transformers.LlamaForCausalLM(cfg)
    m.eval()
    return m


def _hf_greedy_penalized(hf, ids, n_new, penalty):
    out = hf.generate(
        torch.tensor([ids]), max_new_tokens=n_new, do_sample=False,
        repetition_penalty=penalty, use_cache=True,
        pad_token_id=0,
    )
    return [int(t) for t in out[0][len(ids):]]


@pytest.fixture(scope="module")
def penalized_setup():
    hf = _tiny_hf_llama()
    cfg, params = params_from_hf_model(hf, dtype="float32")
    cfg = cfg.replace(eos_token_id=-1)  # force full-length generation
    rng = np.random.default_rng(5)
    ids = [int(t) for t in rng.integers(3, 250, size=12)]
    want = _hf_greedy_penalized(hf, ids, 10, 1.8)
    return cfg, params, ids, want


def _engine_tokens(engine, ids, want_len, **kw):
    prompt = "".join(chr(min(i, 110)) for i in ids)  # placeholder; use ids directly

    # bypass the tokenizer: encode() must produce exactly `ids`
    class FixedTok:
        def encode(self, text):
            return list(ids)

        def decode(self, toks, skip_special_tokens=True):
            return " ".join(str(t) for t in toks)

    engine.tokenizer = FixedTok()
    r = engine.generate(
        prompt, max_tokens=want_len, greedy=True, chat=False,
        repetition_penalty=1.8, **kw,
    )
    assert r["status"] == "success", r
    return [int(t) for t in r["response"].split()]


@pytest.mark.slow
def test_greedy_repetition_penalty_matches_hf_generate(penalized_setup):
    cfg, params, ids, want = penalized_setup
    eng = InferenceEngine(
        cfg, params=params, engine_cfg=EngineConfig(prefill_buckets=(32,))
    )
    got = _engine_tokens(eng, ids, len(want))
    assert got == want


@pytest.mark.slow
def test_pipeline_repetition_penalty_matches_hf(penalized_setup, eight_devices):
    from distributed_llm_inference_tpu.parallel.mesh import build_mesh
    from distributed_llm_inference_tpu.parallel.pipeline import PipelineBackend

    cfg, params, ids, want = penalized_setup
    mesh = build_mesh(MeshConfig(dp=1, pp=3, tp=1), eight_devices)
    eng = InferenceEngine(
        cfg, backend=PipelineBackend(cfg, params, mesh),
        engine_cfg=EngineConfig(prefill_buckets=(32,)),
    )
    got = _engine_tokens(eng, ids, len(want))
    assert got == want


@pytest.mark.slow
def test_continuous_repetition_penalty_matches_hf(penalized_setup):
    cfg, params, ids, want = penalized_setup
    eng = InferenceEngine(
        cfg, params=params, engine_cfg=EngineConfig(prefill_buckets=(32,))
    )

    class FixedTok:
        def encode(self, text):
            return list(ids)

        def decode(self, toks, skip_special_tokens=True):
            return " ".join(str(t) for t in toks)

    eng.tokenizer = FixedTok()
    cont = ContinuousEngine(eng, n_slots=2, chunk_steps=4)
    try:
        r = cont.submit(
            "x", max_tokens=len(want), greedy=True, chat=False,
            repetition_penalty=1.8,
        )
        assert r["status"] == "success", r
        got = [int(t) for t in r["response"].split()]
        assert got == want
    finally:
        cont.close()


@pytest.mark.slow
def test_penalty_disables_speculation(penalized_setup):
    """speculative=true with a repetition penalty falls back to plain
    decode (the penalty changes the argmax the draft verifies against) —
    and still matches HF."""
    cfg, params, ids, want = penalized_setup
    eng = InferenceEngine(
        cfg, params=params, engine_cfg=EngineConfig(prefill_buckets=(32,))
    )
    got = _engine_tokens(eng, ids, len(want), speculative=True)
    assert got == want
