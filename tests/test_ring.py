"""Ring attention / context-parallel decode vs single-device attention.

Runs on the virtual 8-device CPU mesh (conftest.py) via shard_map over an
`sp` axis; reference is ops.attention.attend over the full sequence.
"""

import functools

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from distributed_llm_inference_tpu.ops.attention import attend, causal_mask
from distributed_llm_inference_tpu.parallel.ring import (
    AXIS_SP,
    cp_cache_append,
    cp_decode_attend,
    ring_attend,
)


def _sp_mesh(n):
    return Mesh(np.array(jax.devices()[:n]), (AXIS_SP,))


def _full_attend_ref(q, k, v):
    """Causal full attention from [B,S,H,Dh] q and [B,S,KV,Dh] k/v."""
    S = q.shape[1]
    ck = k.transpose(0, 2, 1, 3)  # [B,KV,S,Dh]
    cv = v.transpose(0, 2, 1, 3)
    return attend(q, ck, cv, causal_mask(jnp.int32(0), S, S))


@pytest.mark.parametrize("sp,B,S,H,KV,Dh", [(4, 2, 32, 4, 2, 16), (8, 1, 64, 8, 8, 8)])
@pytest.mark.slow
def test_ring_attend_matches_full(sp, B, S, H, KV, Dh):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, S, H, Dh), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, KV, Dh), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, KV, Dh), jnp.float32)
    ref = _full_attend_ref(q, k, v)

    mesh = _sp_mesh(sp)
    spec = P(None, AXIS_SP)  # shard the sequence axis
    fn = shard_map(
        ring_attend,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    got = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=2e-5)


@pytest.mark.parametrize("T", [1, 3])
@pytest.mark.slow
def test_cp_decode_attend_matches_full(T):
    """Scatter a 20-token history across 4 devices in arbitrary slot order;
    CP decode of the next chunk must equal single-device cached attention."""
    sp, B, H, KV, Dh, Sc = 4, 2, 4, 2, 16, 8
    hist = 20
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (B, T, H, Dh), jnp.float32)
    k_hist = jax.random.normal(ks[1], (B, hist + T, KV, Dh), jnp.float32)
    v_hist = jax.random.normal(ks[2], (B, hist + T, KV, Dh), jnp.float32)

    # Reference: ordinary cache with history+chunk at slots 0..hist+T.
    S = 32
    ck = jnp.zeros((B, KV, S, Dh)).at[:, :, : hist + T].set(
        k_hist.transpose(0, 2, 1, 3)
    )
    cv = jnp.zeros((B, KV, S, Dh)).at[:, :, : hist + T].set(
        v_hist.transpose(0, 2, 1, 3)
    )
    ref = attend(q, ck, cv, causal_mask(jnp.int32(hist), T, S))

    # CP cache: position p on device p % sp, in REVERSED local slot order to
    # prove permutation invariance. Unused slots have pos_id -1 and garbage K/V.
    rng = np.random.default_rng(0)
    lk = np.asarray(rng.normal(size=(sp, B, KV, Sc, Dh)), np.float32)
    lv = np.asarray(rng.normal(size=(sp, B, KV, Sc, Dh)), np.float32)
    lpos = np.full((sp, Sc), -1, np.int32)
    fill = np.zeros(sp, np.int32)
    for p in range(hist + T):
        d = p % sp
        slot = Sc - 1 - fill[d]  # reversed order
        lk[d, :, :, slot] = np.asarray(k_hist[:, p])
        lv[d, :, :, slot] = np.asarray(v_hist[:, p])
        lpos[d, slot] = p
        fill[d] += 1

    mesh = _sp_mesh(sp)
    fn = shard_map(
        functools.partial(cp_decode_attend, axis_name=AXIS_SP),
        mesh=mesh,
        in_specs=(P(), P(AXIS_SP), P(AXIS_SP), P(AXIS_SP), P()),
        out_specs=P(),
    )
    # Stack shards on a leading sp axis and shard it away.
    got = jax.jit(fn)(
        q,
        jnp.asarray(lk).reshape(sp * B, KV, Sc, Dh),
        jnp.asarray(lv).reshape(sp * B, KV, Sc, Dh),
        jnp.asarray(lpos).reshape(sp * Sc),
        jnp.int32(hist),
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=2e-5)


def test_cp_cache_append_round_robin():
    """Appends land on owner = pos % sp at the next free slot; replicated
    outputs stay consistent."""
    sp, B, KV, Sc, Dh = 4, 1, 2, 4, 8
    mesh = _sp_mesh(sp)

    def body(ck, cv, pids, fill, k_new, v_new, pos):
        return cp_cache_append(ck, cv, pids, k_new, v_new, pos, fill)

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(AXIS_SP), P(AXIS_SP), P(AXIS_SP), P(AXIS_SP), P(), P(), P()),
        out_specs=(P(AXIS_SP), P(AXIS_SP), P(AXIS_SP), P(AXIS_SP), P()),
    )
    ck = jnp.zeros((sp * B, KV, Sc, Dh))
    cv = jnp.zeros((sp * B, KV, Sc, Dh))
    pids = jnp.full((sp * Sc,), -1, jnp.int32)
    fill = jnp.zeros((sp,), jnp.int32)
    for p in range(6):
        k_new = jnp.full((B, 1, KV, Dh), float(p + 1))
        ck, cv, pids, fill, overflow = jax.jit(fn)(
            ck, cv, pids, fill, k_new, k_new * 2, jnp.int32(p)
        )
        assert not bool(overflow[0])
    pids = np.asarray(pids).reshape(sp, Sc)
    fill = np.asarray(fill)
    # positions 0..5 round-robin: dev0 got {0,4}, dev1 {1,5}, dev2 {2}, dev3 {3}
    assert fill.tolist() == [2, 2, 1, 1]
    assert pids[0, :2].tolist() == [0, 4] and pids[1, :2].tolist() == [1, 5]
    assert pids[2, 0] == 2 and pids[3, 0] == 3
    ck = np.asarray(ck).reshape(sp, B, KV, Sc, Dh)
    assert ck[0, 0, 0, 0, 0] == 1.0 and ck[0, 0, 0, 1, 0] == 5.0
    assert ck[1, 0, 0, 1, 0] == 6.0


def test_cp_cache_append_overflow_flag():
    """A full owner shard sets overflow on every device and stores nothing."""
    sp, B, KV, Sc, Dh = 2, 1, 1, 1, 8
    mesh = _sp_mesh(sp)
    fn = shard_map(
        lambda ck, cv, pids, fill, k_new, v_new, pos: cp_cache_append(
            ck, cv, pids, k_new, v_new, pos, fill
        ),
        mesh=mesh,
        in_specs=(P(AXIS_SP), P(AXIS_SP), P(AXIS_SP), P(AXIS_SP), P(), P(), P()),
        out_specs=(P(AXIS_SP), P(AXIS_SP), P(AXIS_SP), P(AXIS_SP), P()),
    )
    ck = jnp.zeros((sp * B, KV, Sc, Dh))
    cv = jnp.zeros((sp * B, KV, Sc, Dh))
    pids = jnp.full((sp * Sc,), -1, jnp.int32)
    fill = jnp.zeros((sp,), jnp.int32)
    for p in range(2):  # fills both single-slot shards
        k_new = jnp.full((B, 1, KV, Dh), float(p + 1))
        ck, cv, pids, fill, overflow = jax.jit(fn)(
            ck, cv, pids, fill, k_new, k_new, jnp.int32(p)
        )
        assert not bool(overflow[0])
    k_new = jnp.full((B, 1, KV, Dh), 99.0)
    ck2, cv2, pids2, fill2, overflow = jax.jit(fn)(
        ck, cv, pids, fill, k_new, k_new, jnp.int32(2)
    )
    assert bool(overflow[0])
    np.testing.assert_array_equal(np.asarray(ck2), np.asarray(ck))  # nothing stored
    np.testing.assert_array_equal(np.asarray(pids2), np.asarray(pids))
    np.testing.assert_array_equal(np.asarray(fill2), np.asarray(fill))
