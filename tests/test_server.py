"""End-to-end serving tests (SURVEY.md §4 item 4): the reference client's
flow — health → workers → generate — against a locally served engine, over
real HTTP."""

import json
import urllib.error
import urllib.request

import pytest

from distributed_llm_inference_tpu import EngineConfig, MeshConfig, create_engine
from distributed_llm_inference_tpu.client import DistributedLLMClient
from distributed_llm_inference_tpu.serving.server import InferenceServer


@pytest.fixture(scope="module")
def served():
    engine = create_engine(
        "test-llama-tiny",
        mesh_cfg=MeshConfig(pp=2),
        engine_cfg=EngineConfig(prefill_buckets=(64, 128)),
    )
    server = InferenceServer(engine, host="127.0.0.1", port=0)  # ephemeral port
    server.start()
    yield server
    server.shutdown()


@pytest.fixture()
def client(served):
    return DistributedLLMClient(f"http://127.0.0.1:{served.port}")


def test_health(client):
    h = client.check_health()
    assert h["status"] == "healthy"
    assert h["role"] == "orchestrator"
    assert h["n_stages"] == 2


def test_workers_sweep(client):
    w = client.check_workers()
    # reference shape: worker_N -> online (orchestration.py:306-329)
    assert w["worker_1"] == "online"
    assert w["worker_2"] == "online"
    assert len(w["detail"]) == 2


def test_generate_over_http(client):
    r = client.generate("Hello over HTTP", max_tokens=6, verbose=False, seed=0)
    assert r["status"] == "success"
    for k in ("response", "time_taken", "tokens_generated", "tokens_per_sec"):
        assert k in r
    assert r["tokens_generated"] <= 6


def test_generate_missing_prompt_is_400(served):
    req = urllib.request.Request(
        f"http://127.0.0.1:{served.port}/generate",
        data=json.dumps({"max_tokens": 5}).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 400
    assert json.loads(ei.value.read())["error"] == "No prompt provided"


def test_generate_invalid_json_is_400(served):
    req = urllib.request.Request(
        f"http://127.0.0.1:{served.port}/generate",
        data=b"{not json",
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 400


def test_max_tokens_capped_at_30(client):
    # reference clamps to 30 (orchestration.py:347)
    r = client.generate("cap", max_tokens=500, verbose=False, chat=False)
    assert r["status"] == "success"
    assert r["tokens_generated"] <= 30


def test_unknown_route_404(served):
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(f"http://127.0.0.1:{served.port}/nope", timeout=10)
    assert ei.value.code == 404


def test_status_page_html(served):
    with urllib.request.urlopen(f"http://127.0.0.1:{served.port}/", timeout=10) as r:
        body = r.read().decode()
    assert "orchestrator" in body and "stage 1" in body


def test_bad_seed_and_bool_are_400(served):
    for payload in (
        {"prompt": "x", "seed": "lots"},
        {"prompt": "x", "greedy": "maybe"},
        {"prompt": "x", "chat": 3.5},
    ):
        req = urllib.request.Request(
            f"http://127.0.0.1:{served.port}/generate",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400, payload


def test_stringy_bools_accepted(client):
    r = client.generate("x", max_tokens=3, verbose=False, greedy="true", chat="false")
    assert r["status"] == "success"


def test_client_connection_refused_envelope():
    from distributed_llm_inference_tpu.client import DistributedLLMClient

    c = DistributedLLMClient("http://127.0.0.1:1", timeout=2)
    r = c.generate("x", verbose=False)
    assert r["status"] == "failed" and "connection failed" in r["error"]


def test_bad_param_type_is_400(served):
    req = urllib.request.Request(
        f"http://127.0.0.1:{served.port}/generate",
        data=json.dumps({"prompt": "x", "max_tokens": "many"}).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 400


def test_stats_route_and_percentiles(served, client):
    client.generate("warm stats", max_tokens=3, verbose=False)
    with urllib.request.urlopen(
        f"http://127.0.0.1:{served.port}/stats", timeout=10
    ) as r:
        s = json.loads(r.read())
    assert s["window"] >= 1
    assert s["ttft_p50_s"] is not None and s["ttft_p50_s"] >= 0
    assert s["tokens_per_sec_p50"] is not None
    # /health embeds the same rolling stats
    h = client.check_health()
    assert h["stats"]["window"] >= 1


def test_profiler_start_stop(served, tmp_path):
    def post(path, payload=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{served.port}{path}",
            data=json.dumps(payload or {}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())

    # trace_dir is a SUBDIR NAME under the server's base, never a raw path
    res = post("/profiler/start", {"trace_dir": "unit-test-trace"})
    assert res["status"] == "tracing"
    assert res["trace_dir"].endswith("/unit-test-trace")
    res = post("/profiler/stop")
    assert res["status"] == "stopped"
    # absolute / escaping paths are rejected (filesystem-write primitive)
    for bad in ("/etc/cron.d", "../escape"):
        req = urllib.request.Request(
            f"http://127.0.0.1:{served.port}/profiler/start",
            data=json.dumps({"trace_dir": bad}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400, bad
    # double-stop is a clean 400
    req = urllib.request.Request(
        f"http://127.0.0.1:{served.port}/profiler/stop", data=b"{}", method="POST"
    )
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 400


# -- streaming over HTTP (engine/continuous.py + NDJSON serving) ------------
@pytest.fixture(scope="module")
def served_continuous():
    from distributed_llm_inference_tpu.engine.continuous import ContinuousEngine

    engine = create_engine(
        "test-llama-tiny",
        engine_cfg=EngineConfig(prefill_buckets=(64, 128)),
    )
    cont = ContinuousEngine(engine, n_slots=2, chunk_steps=4)
    server = InferenceServer(
        engine, host="127.0.0.1", port=0, continuous=cont
    )
    server.start()
    yield server
    server.shutdown()


def test_stream_over_http_ndjson(served_continuous):
    req = urllib.request.Request(
        f"http://127.0.0.1:{served_continuous.port}/generate",
        data=json.dumps(
            {"prompt": "stream http", "max_tokens": 12, "greedy": True,
             "stream": True}
        ).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    events = []
    with urllib.request.urlopen(req, timeout=120) as r:
        assert r.headers.get("Content-Type") == "application/x-ndjson"
        for line in r:
            events.append(json.loads(line))
    final = events[-1]
    assert final["done"] is True and final["status"] == "success"
    assert "".join(e["delta"] for e in events[:-1]) == final["response"]
    assert len(events) >= 2


def test_stream_requires_continuous(served):
    req = urllib.request.Request(
        f"http://127.0.0.1:{served.port}/generate",
        data=json.dumps({"prompt": "x", "stream": True}).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        urllib.request.urlopen(req, timeout=30)
        assert False, "expected HTTP 400"
    except urllib.error.HTTPError as e:
        assert e.code == 400
        assert "continuous" in json.loads(e.read())["error"]


def test_nonstream_generate_on_continuous_server(served_continuous):
    c = DistributedLLMClient(f"http://127.0.0.1:{served_continuous.port}")
    r = c.generate("plain request", max_tokens=6, verbose=False, greedy=True)
    assert r["status"] == "success"
    assert r.get("continuous") is True
