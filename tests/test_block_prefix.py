"""Block-level prefix sharing (engine/block_prefix.py + refcounted
BlockAllocator) tests.

The bar: sharing is a MEMORY/ADMISSION strategy, not a semantics change —
a prefix-hit admission that maps shared physical blocks must decode the
exact token stream the cold path decodes; a block mapped by any live
table must never be reclaimed; eviction touches only chains whose every
holder is the index itself; and block accounting must conserve the pool
(free + cached + in-flight == total, shared blocks counted once).
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_inference_tpu import EngineConfig, get_model_config
from distributed_llm_inference_tpu.engine import paged as P
from distributed_llm_inference_tpu.engine.block_prefix import (
    BlockPrefixIndex, chunk_digests,
)
from distributed_llm_inference_tpu.engine.continuous import (
    ContinuousEngine, _Request,
)
from distributed_llm_inference_tpu.engine.engine import InferenceEngine

BS = 16  # block size used throughout


# ---------------------------------------------------------------------------
# Allocator refcounts (host-side units, no device work)


def test_allocator_refcounts_and_shared_accounting():
    a = P.BlockAllocator(8)  # 7 usable
    ids = a.alloc(3)
    assert all(a.refcount(b) == 1 for b in ids)
    assert a.shared_blocks == 0
    a.incref(ids[:2])  # a second holder maps two of them
    assert a.refcount(ids[0]) == 2 and a.shared_blocks == 2
    a.decref(ids)  # first holder lets go: only the sole-held block frees
    assert a.free_blocks == 4 + 1
    assert a.refcount(ids[2]) == 0 and a.refcount(ids[0]) == 1
    assert a.shared_blocks == 0
    a.decref(ids[:2])  # last holder: everything back
    assert a.free_blocks == 7
    # free() stays the single-holder spelling (decref)
    ids = a.alloc(7)
    a.free(ids)
    assert a.free_blocks == 7


def test_allocator_alloc_refuses_then_recovers():
    a = P.BlockAllocator(4)
    ids = a.alloc(3)
    assert a.alloc(1) is None
    a.decref(ids)
    assert len(a.alloc(3)) == 3


# ---------------------------------------------------------------------------
# Index units (allocator + index, no device work)


def _ids(n, seed=0):
    rng = np.random.RandomState(seed)
    return [int(t) for t in rng.randint(0, 1000, size=n)]


def test_chunk_digests_chain_structure():
    """The affinity-key export (router tier): digests are CHAINED — two
    sequences share digest[i] iff their first (i+1)*chunk items match —
    and only full chunks digest, mirroring lookup()'s partial-tail rule."""
    a = chunk_digests(list(range(40)), 16)
    b = chunk_digests(list(range(16)) + list(range(100, 124)), 16)
    assert len(a) == 2  # 40 // 16 full chunks, partial tail ignored
    assert a[0] == b[0]  # shared first chunk
    assert a[1] != b[1]  # chains diverge at the second chunk
    # chained, not a bag: same chunks in a different order differ at [1]
    c = chunk_digests(list(range(16, 32)) + list(range(16)), 16)
    assert c[0] != a[0] and c[1] != a[1]
    # progressive: a longer head extends, never rewrites, the chain
    assert chunk_digests(list(range(48)), 16)[:2] == a


def test_chunk_digests_bytes_and_str_forms():
    # the router hashes raw prompt text; str and its utf-8 bytes agree
    assert chunk_digests("x" * 130, 64) == chunk_digests(b"x" * 130, 64)
    assert len(chunk_digests("x" * 130, 64)) == 2
    assert chunk_digests("short", 64) == []  # no full chunk, no digest
    assert chunk_digests("", 64) == []
    # max_chunks bounds the walk (router-side cost cap)
    assert len(chunk_digests(b"y" * 1000, 8, max_chunks=4)) == 4
    # token-id and byte forms are distinct key spaces (no cross-collision
    # by construction worth asserting, but both must be stable hex)
    d = chunk_digests([1, 2, 3, 4], 4)
    assert d == chunk_digests([1, 2, 3, 4], 4)
    assert all(isinstance(s, str) and len(s) == 20 for s in d)
    with pytest.raises(ValueError):
        chunk_digests("abc", 0)


def test_index_register_lookup_roundtrip():
    a = P.BlockAllocator(32)
    idx = BlockPrefixIndex(a, BS)
    ids = _ids(3 * BS + 5)
    blocks = a.alloc(4)  # 3 full prompt blocks + decode tail
    idx.register(ids, len(ids), blocks)
    assert idx.stats()["cached_blocks"] == 3  # the partial block never caches

    # identical full prompt: depth capped to leave >= 1 tail token
    p0, shared, key = idx.lookup(ids)
    assert p0 == 3 * BS and shared == blocks[:3]

    # prompt diverging mid-block 2: only the intact full blocks map
    div = list(ids)
    div[BS + 3] += 1
    p0, shared, _ = idx.lookup(div)
    assert p0 == BS and shared == blocks[:1]

    # prompt that IS exactly the cached chain: the last block is
    # recomputed, not mapped (at least one sampling token must prefill)
    p0, shared, _ = idx.lookup(ids[: 3 * BS])
    assert p0 == 2 * BS and shared == blocks[:2]

    assert idx.lookup(_ids(2 * BS, seed=9)) == (0, None, None)


def test_index_register_dedups_existing_chain():
    a = P.BlockAllocator(32)
    idx = BlockPrefixIndex(a, BS)
    ids = _ids(2 * BS + 1)
    b1 = a.alloc(3)
    assert idx.register(ids, len(ids), b1) == 2
    # a second tenant with the same prompt registers its own row whose
    # head MAPS the cached blocks — no new entries, no extra index refs
    b2 = b1[:2] + a.alloc(1)
    assert idx.register(ids, len(ids), b2) == 0
    assert idx.stats()["cached_blocks"] == 2
    assert a.refcount(b1[0]) == 2  # alloc holder + ONE index ref


def test_eviction_only_reclaims_unreferenced_chains():
    a = P.BlockAllocator(32)
    idx = BlockPrefixIndex(a, BS)
    ids = _ids(3 * BS + 1)
    blocks = a.alloc(4)
    idx.register(ids, len(ids), blocks)
    # a live table maps the chain: incref == mapping, as admission does
    a.incref(blocks[:3])
    a.decref(blocks)  # original tenant completes
    assert idx.evict(99) == 0  # every chain block is live-mapped: pinned
    assert idx.stats()["cached_blocks"] == 3
    a.decref(blocks[:3])  # the mapper completes too
    assert idx.evict(99) == 3  # now refcount-1 (index-only): reclaimed
    assert idx.stats()["cached_blocks"] == 0
    assert a.free_blocks == 31


def test_eviction_cascades_root_to_descendants():
    """Evicting an LRU root entry must cascade through its whole subtree:
    a stale child keyed on a recycled parent block id must never revive
    an old chain under new content."""
    a = P.BlockAllocator(32)
    idx = BlockPrefixIndex(a, BS)
    ids = _ids(3 * BS + 1)
    row = a.alloc(4)
    idx.register(ids, len(ids), row)
    a.decref(row)  # tenant completes; chain is index-only
    # the LRU-first entry is the chain's ROOT (registration order):
    # reclaiming one block must take the descendants with it
    assert idx.evict(1) == 3
    assert idx.lookup(ids) == (0, None, None)
    assert idx.stats()["cached_blocks"] == 0
    assert a.free_blocks == 31


def test_divergent_chains_share_root_once():
    """Two chains forking off one shared root block: the root is cached
    once, and draining the cache reclaims every branch exactly once."""
    a = P.BlockAllocator(32)
    idx = BlockPrefixIndex(a, BS)
    head = _ids(BS)
    ids_a = head + _ids(BS, seed=1) + [1]
    ids_b = head + _ids(BS, seed=2) + [2]
    row_a = a.alloc(3)
    idx.register(ids_a, len(ids_a), row_a)
    row_b = [row_a[0]] + a.alloc(2)
    a.incref([row_a[0]])  # chain B maps the shared root
    idx.register(ids_b, len(ids_b), row_b)
    assert idx.stats()["cached_blocks"] == 3  # shared root counted once
    p0, shared, _ = idx.lookup(ids_b)
    assert p0 == 2 * BS and shared == row_b[:2]
    a.decref(row_a)
    a.decref(row_b)
    assert idx.evictable_blocks() == 3
    assert idx.evict(99) == 3
    assert idx.lookup(ids_a) == (0, None, None)
    assert idx.lookup(ids_b) == (0, None, None)
    assert a.free_blocks == 31


# ---------------------------------------------------------------------------
# Engine-level: sharing on the paged fleet


PROMPTS = [
    "the quick brown fox",
    "jumps over",
    "a lazy dog while the band plays on",
    "hello",
]
SHARED = "shared system prefix " * 4  # ~85 byte-fallback tokens


@pytest.fixture(scope="module")
def base_engine():
    cfg = get_model_config("test-llama-tiny")
    return InferenceEngine(
        cfg, engine_cfg=EngineConfig(prefill_buckets=(32, 64))
    )


def _sharing_engine(base, **kw):
    eng = InferenceEngine(
        base.cfg, params=base.backend.params,
        engine_cfg=EngineConfig(
            prefill_buckets=(32, 64), prefix_cache_entries=4
        ),
    )
    args = dict(
        n_slots=2, chunk_steps=4, slot_max_seq=192,
        kv_pool_blocks=40, kv_block_size=BS,
    )
    args.update(kw)
    return ContinuousEngine(eng, **args)


def _submit_all(cont, prompts, **kw):
    out = [None] * len(prompts)

    def run(i):
        out[i] = cont.submit(prompts[i], greedy=True, chat=False, **kw)

    threads = [
        threading.Thread(target=run, args=(i,)) for i in range(len(prompts))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return out


@pytest.mark.slow
def test_hit_vs_cold_bit_exact(base_engine):
    """A prefix-hit admission (mapped shared head + tail prefill) decodes
    the exact greedy text a sharing-free paged fleet decodes — including
    a request whose prompt diverges mid-block."""
    # tails sized so the hit plans INSIDE the 128-token window (a tail
    # past every bucket at the hit offset falls back cold by design)
    mix = [
        SHARED + "first question",
        SHARED + "second question!",
        SHARED[: len(SHARED) // 2] + "diverges mid-stream from the rest",
        "no shared prefix at all",
    ]
    cold = ContinuousEngine(
        base_engine, n_slots=2, chunk_steps=4, slot_max_seq=192,
        kv_pool_blocks=40, kv_block_size=BS,
    )
    try:
        want = [
            cold.submit(p, greedy=True, chat=False, max_tokens=12)
            for p in mix
        ]
    finally:
        cold.close()
    warm = _sharing_engine(base_engine)
    try:
        got = [
            warm.submit(p, greedy=True, chat=False, max_tokens=12)
            for p in mix
        ]
        st = warm.stats()
    finally:
        warm.close()
    for w, g in zip(want, got):
        assert w["status"] == g["status"] == "success"
        assert g["response"] == w["response"]
        assert g["tokens_generated"] == w["tokens_generated"]
    # the full-prefix repeats actually mapped blocks
    assert got[1]["prefix_cached_tokens"] >= BS
    assert got[1]["prefix_cached_tokens"] % BS == 0
    assert got[2]["prefix_cached_tokens"] >= BS  # shared head of SHARED
    assert st["prefix_cache"]["hits"] >= 2
    assert st["prefix_cache"]["dedup_saved_tokens"] >= 2 * BS
    # conservation at idle: every block is free or cached, none leaked
    pg = st["paged"]
    assert pg["free_blocks"] + pg["cached_blocks"] == pg["pool_blocks"] - 1


@pytest.mark.slow
def test_concurrent_sharing_matches_solo(base_engine):
    """Churn: concurrent tenants mapping the same chain (refcount > 1 on
    the head while multiple tables decode off it) still produce the solo
    engine's exact greedy text — a dropped or corrupted shared block
    would diverge some stream."""
    prompts = [SHARED + f"question number {i}" for i in range(6)]
    solo = [
        base_engine.generate(p, greedy=True, chat=False, max_tokens=10)
        for p in prompts
    ]
    warm = _sharing_engine(base_engine, n_slots=3)
    try:
        got = _submit_all(warm, prompts, max_tokens=10)
        st = warm.stats()
    finally:
        warm.close()
    for w, g in zip(solo, got):
        assert g["status"] == "success"
        assert g["response"] == w["response"]
    assert st["prefix_cache"]["hits"] >= 1
    pg = st["paged"]
    assert pg["free_blocks"] + pg["cached_blocks"] == pg["pool_blocks"] - 1


@pytest.mark.slow
def test_pool_exhaustion_with_shared_blocks_resident(base_engine):
    """A pool too small to hold a new worst-case tenant PLUS the resident
    cached chains still serves everything: admission evicts unreferenced
    chains (never live-mapped ones) instead of deadlocking on a free list
    the cache has eaten."""
    # slot class 96 -> 6 blocks worst case; 9 usable blocks. Each ~57-token
    # prompt caches 3 full blocks on completion, so by the third DISTINCT
    # prompt the cache holds 6 of the 9 blocks and admission must reclaim.
    longs = [f"p{i} " * 18 + "end" for i in range(3)]
    warm = _sharing_engine(
        base_engine, n_slots=2, slot_max_seq=96, kv_pool_blocks=10,
    )
    try:
        solo = [
            base_engine.generate(p, greedy=True, chat=False, max_tokens=30)
            for p in longs
        ]
        got = [
            warm.submit(p, greedy=True, chat=False, max_tokens=30)
            for p in longs
        ]
        st = warm.stats()
    finally:
        warm.close()
    for w, g in zip(solo, got):
        assert g["status"] == "success"
        assert g["response"] == w["response"]
    pg = st["paged"]
    assert pg["free_blocks"] + pg["cached_blocks"] == pg["pool_blocks"] - 1
    # the cache had to give blocks back at least once
    assert st["prefix_cache"]["evictions"] >= 1
    # concurrency on top: live-mapped chains stay pinned while the pool
    # churns, and every stream still matches solo
    solo2 = [
        base_engine.generate(p, greedy=True, chat=False, max_tokens=40)
        for p in PROMPTS
    ]
    warm2 = _sharing_engine(
        base_engine, n_slots=4, slot_max_seq=96, kv_pool_blocks=10,
    )
    try:
        got2 = _submit_all(warm2, PROMPTS, max_tokens=40)
        st2 = warm2.stats()
    finally:
        warm2.close()
    for w, g in zip(solo2, got2):
        assert g["status"] == "success"
        assert g["response"] == w["response"]
    pg2 = st2["paged"]
    assert pg2["free_blocks"] + pg2["cached_blocks"] == pg2["pool_blocks"] - 1


@pytest.mark.slow
def test_blocked_release_frees_granted_blocks(base_engine):
    """Regression for the admission pool-block leak: blocks granted, then
    `_BLOCKED` on constraint-table backpressure must decref the grant —
    a retry re-allocates, and the first grant would otherwise be orphaned
    (refcount 1, no holder, never freed)."""
    warm = _sharing_engine(base_engine)
    total = warm._alloc.n_blocks - 1
    real_acquire = warm._ctable.acquire
    calls = []

    def acquire_once_blocked(art):
        calls.append(warm._alloc.free_blocks)
        if len(calls) == 1:
            return None  # simulate a full constraint table
        return real_acquire(art)

    warm._ctable.acquire = acquire_once_blocked
    try:
        req = _Request(
            "hello there",
            dict(max_tokens=6, greedy=True, chat=False,
                 constraint={"choices": ["aa", "bb"]}),
        )
        assert warm._enqueue(req) is None
        assert req.done.wait(timeout=120)
        assert req.result["status"] == "success"
        # free at the SECOND acquire (post-retry re-grant) must equal free
        # at the first — a leak would show the retry eating a second grant
        assert len(calls) >= 2
        assert calls[1] == calls[0]
        # drain: nothing in flight keeps blocks; only the cache may hold
        deadline = time.time() + 10
        while time.time() < deadline:
            pg = warm.stats()["paged"]
            if pg["free_blocks"] + pg["cached_blocks"] == total:
                break
            time.sleep(0.05)
        pg = warm.stats()["paged"]
        assert pg["free_blocks"] + pg["cached_blocks"] == total
    finally:
        warm._ctable.acquire = real_acquire
        warm.close()


@pytest.mark.slow
def test_sharing_disabled_without_prefix_entries(base_engine):
    """prefix_cache_entries=0 keeps the paged fleet sharing-free: no
    index, full free list after completion (the pre-sharing contract)."""
    cont = ContinuousEngine(
        base_engine, n_slots=2, chunk_steps=4, slot_max_seq=96,
        kv_pool_blocks=16, kv_block_size=BS,
    )
    try:
        out = cont.submit(SHARED + "q", greedy=True, chat=False,
                          max_tokens=8)
        assert out["status"] == "success"
        assert "prefix_cached_tokens" not in out
        st = cont.stats()
    finally:
        cont.close()
    assert cont._bpx is None
    assert st["paged"]["free_blocks"] == 15
    assert "prefix_cache" not in st


@pytest.mark.slow
def test_hit_depth_degrades_to_fit_buckets(base_engine):
    """A hit whose deepest offset leaves a tail no prefill bucket fits
    inside the slot window must degrade to a shallower block-aligned
    depth instead of falling all the way back to cold — found driving
    the HTTP surface with the default bucket ladder (smallest bucket 64,
    window 128: a 96-token-deep hit can never plan, a 64-token one can).
    BUCKETED FALLBACK ONLY (ragged_prefill=False): the ragged ingest has
    no bucket ladder and reuses at exact depth — that contract is pinned
    in tests/test_ragged_attention.py's exact-depth regression.
    """
    eng = InferenceEngine(
        base_engine.cfg, params=base_engine.backend.params,
        engine_cfg=EngineConfig(
            prefill_buckets=(64,), prefix_cache_entries=4,
            ragged_prefill=False,
        ),
    )
    p = SHARED + "first question"  # ~98 tokens; full-depth reuse = 96
    cold = ContinuousEngine(
        base_engine, n_slots=2, chunk_steps=4, slot_max_seq=128,
        kv_pool_blocks=40, kv_block_size=BS,
    )
    try:
        want = cold.submit(p, greedy=True, chat=False, max_tokens=10)
    finally:
        cold.close()
    warm = ContinuousEngine(
        eng, n_slots=2, chunk_steps=4, slot_max_seq=128,
        kv_pool_blocks=40, kv_block_size=BS,
    )
    try:
        first = warm.submit(p, greedy=True, chat=False, max_tokens=10)
        again = warm.submit(p, greedy=True, chat=False, max_tokens=10)
        st = warm.stats()
    finally:
        warm.close()
    assert first["status"] == again["status"] == "success"
    assert "prefix_cached_tokens" not in first
    # 96 and 80 cannot plan (offset + 64-bucket > 128); 64 can
    assert again["prefix_cached_tokens"] == 4 * BS
    assert again["response"] == want["response"] == first["response"]
    assert st["prefix_cache"]["dedup_saved_tokens"] == 4 * BS


needs_shard_map = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="this jax build has no jax.shard_map (pp backends unavailable)",
)


@needs_shard_map
@pytest.mark.slow
def test_pp_block_sharing_matches_dense(eight_devices):
    """Block sharing on the pp=2 mesh: the layer-local fill gather + the
    trash-head insert compose with the gated ring — hit streams match a
    sharing-free pp paged fleet exactly."""
    from distributed_llm_inference_tpu import MeshConfig
    from distributed_llm_inference_tpu.runtime import create_engine

    mix = [SHARED + "first question", SHARED + "second question!"]
    eng = create_engine(
        "test-llama-tiny", mesh_cfg=MeshConfig(pp=2),
        engine_cfg=EngineConfig(
            prefill_buckets=(32, 64), prefix_cache_entries=4
        ),
    )
    # solo pp path as the reference stream (solo-vs-fleet greedy parity
    # is the structural contract every fleet test leans on)
    want = [
        eng.generate(p, greedy=True, chat=False, max_tokens=10)
        for p in mix
    ]
    warm = ContinuousEngine(
        eng, n_slots=2, chunk_steps=4, slot_max_seq=128,
        kv_pool_blocks=24, kv_block_size=BS,
    )
    try:
        got = [
            warm.submit(p, greedy=True, chat=False, max_tokens=10)
            for p in mix
        ]
    finally:
        warm.close()
    for w, g in zip(want, got):
        assert w["status"] == g["status"] == "success"
        assert g["response"] == w["response"]
    assert got[1]["prefix_cached_tokens"] >= BS


@pytest.mark.slow
def test_gather_scratch_blocks_inverts_scatter(base_engine):
    """Device-level: gather_scratch_blocks(scatter_scratch(x)) == x on an
    out-of-order block row — the contiguous view a tail prefill attends
    is byte-identical to the scratch the blocks came from."""
    be = base_engine.backend
    scratch = be.init_cache(1, 4 * BS)
    # fill with distinguishable content
    scratch = {
        k: jnp.asarray(
            np.random.RandomState(i).standard_normal(v.shape), v.dtype
        )
        for i, (k, v) in enumerate(scratch.items())
    }
    pool = be.init_paged_pool(9, BS)
    row = jnp.asarray([5, 2, 7, 3], jnp.int32)
    pool = P.scatter_scratch(pool, scratch, row)
    back = P.gather_scratch_blocks(pool, row)
    for k in ("k", "v"):
        np.testing.assert_array_equal(
            np.asarray(back[k]), np.asarray(scratch[k])
        )
