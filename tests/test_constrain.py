"""constrain/ compiler unit tests: regex -> DFA, schema -> regex -> DFA,
token tables over the vocab trie, spec parsing/400 surface, and the fleet
table registry. Pure host-side — no jit, fast tier.

The property bar: for ANY compiled constraint, a masked sampler (greedy or
categorical over random logits) must (a) never pick a masked-out token,
(b) terminate (the bounded grammars are acyclic, accept-with-no-
continuation forces EOS), and (c) produce text the ORIGINAL constraint
accepts (Python re.fullmatch / json.loads + field checks — an independent
oracle, not our own DFA).
"""

import json
import re

import numpy as np
import pytest

from distributed_llm_inference_tpu.constrain import (
    CompiledConstraint,
    ConstraintError,
    FleetConstraintTable,
    RegexError,
    SchemaError,
    TokenVocab,
    compile_constraint,
    compile_regex,
    constraint_key,
    constraint_to_regex,
    parse_constraint_spec,
)
from distributed_llm_inference_tpu.constrain.schema import schema_to_regex
from distributed_llm_inference_tpu.utils.tokenizer import ByteTokenizer


def dfa_match(dfa, s: str) -> bool:
    st = dfa.start
    for b in s.encode():
        st = int(dfa.trans[st, b])
        if st < 0:
            return False
    return bool(dfa.accept[st])


# -- regex -> DFA ------------------------------------------------------------

REGEX_CORPUS = [
    # (pattern, matches, non-matches) — pattern valid for Python re too,
    # so re.fullmatch is the independent oracle
    (r"(red|green|blue)", ["red", "green", "blue"], ["", "re", "redx"]),
    (r"[0-9]{2,4}", ["12", "1234"], ["1", "12345", "ab"]),
    (r"-?(0|[1-9][0-9]{0,3})(\.[0-9]{1,2})?",
     ["0", "-0", "42", "9999.25"], ["01", "1.", ".5", "1.234"]),
    (r"a+b*c?", ["a", "aab", "abc", "aaac"], ["", "b", "ca"]),
    (r"\w+@\w+\.(com|org)", ["a_1@b.com", "x@y.org"], ["a@b.net", "@b.com"]),
    (r"[^x-z]{1,3}", ["abc", "w"], ["", "xa", "abcd"]),
    (r"yes|no|maybe( not)?", ["yes", "maybe", "maybe not"], ["may", "not"]),
    (r"\s*\d\s*", ["5", " 5 ", "\t7\n"], ["55", "x"]),
]


@pytest.mark.parametrize("pattern,good,bad", REGEX_CORPUS)
def test_regex_dfa_agrees_with_python_re(pattern, good, bad):
    dfa = compile_regex(pattern)
    for s in good:
        assert re.fullmatch(pattern, s), f"corpus bug: {s!r}"
        assert dfa_match(dfa, s), f"{pattern!r} should accept {s!r}"
    for s in bad:
        assert not re.fullmatch(pattern, s), f"corpus bug: {s!r}"
        assert not dfa_match(dfa, s), f"{pattern!r} should reject {s!r}"


def test_regex_utf8_literals_walk_bytes():
    dfa = compile_regex("héllo")
    assert dfa_match(dfa, "héllo") and not dfa_match(dfa, "hello")


def test_regex_rejects_unsupported():
    for pat in ("^abc", "a$", r"(a)\1", "a**"):
        with pytest.raises(RegexError):
            compile_regex(pat)
    with pytest.raises(RegexError):
        compile_regex("[z-a]")  # reversed range
    with pytest.raises(RegexError):
        compile_regex("a{1000}")  # repeat bound cap


def test_regex_state_cap():
    # the classic subset-construction blowup: .*a.{n} needs 2^n states
    with pytest.raises(RegexError):
        compile_regex(r"[ab]*a[ab]{15}")


# -- schema -> regex ---------------------------------------------------------

SCHEMA_CORPUS = [
    {"type": "object",
     "properties": {"name": {"type": "string"}, "age": {"type": "integer"}},
     "required": ["name", "age"]},
    {"type": "object",
     "properties": {"color": {"enum": ["red", "green", "blue"]},
                    "score": {"type": "number"},
                    "tags": {"type": "array", "items": {"type": "string"}}},
     "required": ["color"]},
    {"type": "array", "items": {"type": "integer"}},
    {"enum": ["north", "south", 42, True, None]},
    {"type": "object",
     "properties": {"inner": {"type": "object",
                              "properties": {"ok": {"type": "boolean"}},
                              "required": ["ok"]}},
     "required": ["inner"]},
]


@pytest.mark.parametrize("schema", SCHEMA_CORPUS)
def test_schema_regex_accepts_valid_instances(schema):
    dfa = compile_regex(schema_to_regex(schema))
    # hand-built valid instances per corpus entry
    samples = {
        0: ['{"name":"bob","age":42}', '{"name":"","age":-7}'],
        1: ['{"color":"red","score":1.5,"tags":["a"]}',
            '{"color":"blue","score":-2e4,"tags":[]}'],
        2: ["[]", "[1,2,3]"],
        3: ['"north"', "42", "true", "null"],
        4: ['{"inner":{"ok":true}}'],
    }[SCHEMA_CORPUS.index(schema)]
    for s in samples:
        json.loads(s)  # corpus sanity
        assert dfa_match(dfa, s), f"schema should accept {s}"


def test_schema_rejects_invalid_instances():
    dfa = compile_regex(schema_to_regex(SCHEMA_CORPUS[0]))
    for s in ['{"name":"bob"}', '{"age":42,"name":"b"}', "{}", "[1]",
              '{"name":"bob","age":"x"}']:
        assert not dfa_match(dfa, s)


def test_schema_errors():
    with pytest.raises(SchemaError):
        schema_to_regex({"type": "tuple"})
    with pytest.raises(SchemaError):
        schema_to_regex({"type": "object", "properties": {"a": {"type": "string"}},
                         "required": ["b"]})
    with pytest.raises(SchemaError):
        schema_to_regex({"enum": []})
    with pytest.raises(SchemaError):
        schema_to_regex({"enum": [{"nested": 1}]})


# -- spec parsing (the serving 400 surface) ----------------------------------

def test_parse_constraint_spec():
    assert parse_constraint_spec({"regex": "a+"})["kind"] == "regex"
    assert parse_constraint_spec({"choices": ["a"]})["kind"] == "choices"
    assert parse_constraint_spec({"json_object": True})["kind"] == "json_object"
    s = parse_constraint_spec({"json_schema": {"type": "string"}})
    assert s == {"kind": "json_schema", "schema": {"type": "string"}}
    for bad in (
        "regex", {}, {"regex": "a", "choices": ["b"]}, {"regex": ""},
        {"choices": []}, {"choices": ["a", 3]}, {"json_object": "yes"},
        {"json_schema": "x"}, {"bogus": 1},
    ):
        with pytest.raises(ConstraintError):
            parse_constraint_spec(bad)


def test_constraint_key_canonical():
    a = constraint_key(parse_constraint_spec({"json_schema": {"type": "object", "properties": {"a": {"type": "string"}}}}))
    b = constraint_key(parse_constraint_spec({"json_schema": {"properties": {"a": {"type": "string"}}, "type": "object"}}))
    assert a == b  # key order canonicalized
    c = constraint_key(parse_constraint_spec({"regex": "a+"}))
    assert c != a


# -- token tables ------------------------------------------------------------

def _byte_vocab(vocab_size=256):
    return TokenVocab.from_tokenizer(
        ByteTokenizer(), vocab_size, eos_ids=(2,), special_ids=(0, 1, 2)
    )


def _simulate(art: CompiledConstraint, tok, rng, greedy: bool,
              max_steps=600):
    """Host replica of the constrained sampler: masked draw + table
    advance. Returns decoded text; asserts termination."""
    st = art.start
    out = []
    for _ in range(max_steps):
        logits = rng.normal(size=art.mask.shape[1])
        masked = np.where(art.mask[st], logits, -1e30)
        if greedy:
            tid = int(np.argmax(masked))
        else:
            p = np.exp(masked - masked.max())
            p /= p.sum()
            tid = int(rng.choice(len(p), p=p))
        assert art.mask[st, tid], "sampler picked a masked token"
        if tid == 2:  # eos
            return tok.decode(out)
        out.append(tid)
        st = art.advance(st, tid)
    raise AssertionError("constrained generation did not terminate")


@pytest.mark.parametrize("spec,check", [
    ({"regex": r"(red|green|blue)"},
     lambda t: re.fullmatch(r"(red|green|blue)", t)),
    ({"regex": r"[0-9]{2,4}(\.[0-9])?"},
     lambda t: re.fullmatch(r"[0-9]{2,4}(\.[0-9])?", t)),
    ({"choices": ["alpha", "beta", "alphabet"]},
     lambda t: t in ("alpha", "beta", "alphabet")),
    ({"json_object": True}, lambda t: isinstance(json.loads(t), dict)),
    ({"json_schema": SCHEMA_CORPUS[0]},
     lambda t: isinstance(json.loads(t)["age"], int)),
    ({"json_schema": SCHEMA_CORPUS[1]},
     lambda t: json.loads(t)["color"] in ("red", "green", "blue")),
    ({"json_schema": SCHEMA_CORPUS[2]},
     lambda t: isinstance(json.loads(t), list)),
])
def test_masked_sampling_property(spec, check):
    """Greedy AND categorical draws over random logits always produce
    output the original constraint accepts (independent oracle)."""
    tok = ByteTokenizer()
    art = compile_constraint(spec, _byte_vocab())
    rng = np.random.default_rng(0)
    for trial in range(8):
        for greedy in (True, False):
            text = _simulate(art, tok, rng, greedy)
            assert check(text), f"{spec} produced {text!r}"


def test_eos_only_in_accept_states():
    art = compile_constraint({"choices": ["ab"]}, _byte_vocab())
    a = ord("a") + ByteTokenizer.OFFSET
    b = ord("b") + ByteTokenizer.OFFSET
    assert not art.mask[art.start, 2]  # can't end before "ab"
    st = art.advance(art.start, a)
    assert not art.mask[st, 2]
    st = art.advance(st, b)
    # accept with no live continuation: ONLY eos remains (forced)
    assert art.mask[st, 2]
    assert art.mask[st].sum() == 1


def test_special_tokens_never_allowed():
    art = compile_constraint({"regex": ".*"}, _byte_vocab())
    assert not art.mask[:, 0].any() and not art.mask[:, 1].any()  # pad/bos


def test_start_bias_matches_start_mask():
    art = compile_constraint({"choices": ["no", "yes"]}, _byte_vocab())
    bias = art.start_bias()
    assert (bias[art.mask[art.start]] == 0).all()
    assert (bias[~art.mask[art.start]] == -1e9).all()


# -- fleet table registry ----------------------------------------------------

def test_fleet_table_acquire_release_compact():
    v = _byte_vocab()
    a = compile_constraint({"choices": ["aa"]}, v)
    b = compile_constraint({"choices": ["bbb"]}, v)
    ft = FleetConstraintTable(256, max_states=32)
    off_a = ft.acquire(a)
    assert off_a == 1  # row 0 is the free state
    assert ft.acquire(a) == off_a  # resident reuse
    off_b = ft.acquire(b)
    assert off_b == off_a + a.num_states
    mask, trans = ft.numpy_tables()
    assert mask.shape[0] == 32  # bucket-padded
    assert mask[0].all() and (trans[0] == 0).all()  # free row
    # rebased rows equal the artifact rows
    assert (mask[off_b: off_b + b.num_states] == b.mask).all()
    assert (trans[off_b: off_b + b.num_states] == b.next_state + off_b).all()
    # full table backpressures (None), releases allow compaction
    big = compile_constraint({"regex": "[a-z]{28}"}, v)
    assert ft.fits(big)
    assert ft.acquire(big) is None  # no room while a+b resident
    for _ in range(3):
        ft.release(a.key)
    ft.release(b.key)
    ft.release(b.key)
    assert not ft.any_active
    assert ft.acquire(big) == 1  # compacted: registry reset, rows reused
    never = compile_constraint({"regex": "[a-z]{40}"}, v)
    assert not ft.fits(never)  # can never fit max_states=32 -> solo route


def test_fleet_free_row_is_inert():
    """Unconstrained slots sit at state 0: every token allowed, state
    pinned — the constrained program is a no-op for them."""
    ft = FleetConstraintTable(256, max_states=32)
    ft.acquire(compile_constraint({"choices": ["x"]}, _byte_vocab()))
    mask, trans = ft.numpy_tables()
    assert mask[0].all()
    assert (trans[0] == 0).all()
