"""Chaos suite: deterministic fault injection (utils/faults.py) driving
every failure-containment path in the continuous scheduler and the
serving drain/readiness surface.

The bar, per recovery path:
  * transient faults at each injection point: every non-poison greedy
    request completes with output IDENTICAL to a fault-free run, and the
    restart counter matches the injection count;
  * resource accounting returns to zero leaks (paged pool free == total,
    constraint rows free) after crashes — including on the permanent
    loop-death path;
  * a poison request is isolated within poison_strikes restarts WITHOUT
    failing its fleet-mates;
  * restart-budget exhaustion fails the whole fleet with clean
    envelopes, never hangs a client;
  * SIGTERM flips readiness (503 + Retry-After at the edge), in-flight
    work drains, and the server exits cleanly.

Everything here is tier-1 (marker `chaos`, never `slow`): the triggers
are call counters, not wall clock, so the suite replays identically.
"""

import json
import os
import signal
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from distributed_llm_inference_tpu import EngineConfig, get_model_config
from distributed_llm_inference_tpu.client import DistributedLLMClient
from distributed_llm_inference_tpu.engine.continuous import ContinuousEngine
from distributed_llm_inference_tpu.engine.engine import InferenceEngine
from distributed_llm_inference_tpu.serving.server import InferenceServer
from distributed_llm_inference_tpu.utils import faults

pytestmark = pytest.mark.chaos

PROMPTS = [
    "the quick brown fox",
    "jumps over",
    "a lazy dog while the band plays on",
]
POISON = "POISONPILL do not serve this"


@pytest.fixture(autouse=True)
def _always_disarm():
    """No armed plan may leak between tests (or into other suites)."""
    faults.disarm()
    yield
    faults.disarm()


@pytest.fixture(scope="module")
def engine():
    cfg = get_model_config("test-llama-tiny")
    return InferenceEngine(
        cfg, engine_cfg=EngineConfig(prefill_buckets=(32, 64))
    )


@pytest.fixture(scope="module")
def solo(engine):
    """Fault-free greedy references (the bit-exactness bar)."""
    return {
        p: engine.generate(p, max_tokens=10, greedy=True, chat=False)
        for p in PROMPTS
    }


def _cont(engine, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("chunk_steps", 4)
    kw.setdefault("restart_backoff_s", 0.01)
    return ContinuousEngine(engine, **kw)


def _submit_all(cont, prompts, max_tokens=10, stagger=0.05):
    out = {}
    lock = threading.Lock()

    def run(p, delay):
        time.sleep(delay)
        r = cont.submit(p, max_tokens=max_tokens, greedy=True, chat=False)
        with lock:
            out[p] = r

    threads = [
        threading.Thread(target=run, args=(p, stagger * i))
        for i, p in enumerate(prompts)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    return out


# -- harness units (no engine) ----------------------------------------------

def test_rule_triggers_nth_every_times():
    r = faults.FaultRule("fetch", "transient", on_call=2, every=3, times=2)
    fired = [r.should_fire("") for _ in range(9)]
    # calls:      1      2     3      4      5     6      7  (times cap)
    assert fired == [
        False, True, False, False, True, False, False, False, False
    ]
    assert r.fired == 2


def test_rule_match_restricts_and_counts_matching_calls_only():
    r = faults.FaultRule("prefill", "fatal", match="BAD", every=1, times=0)
    assert not r.should_fire("good prompt")
    assert r.should_fire("a BAD prompt")
    assert not r.should_fire("still good")
    assert r.should_fire("BAD again")


def test_check_is_noop_when_disarmed():
    faults.disarm()
    faults.check("decode_launch", tag="anything")  # must not raise


def test_armed_check_raises_typed_errors():
    faults.arm([faults.FaultRule("fetch", "transient")])
    with pytest.raises(faults.TransientFault, match="RESOURCE_EXHAUSTED"):
        faults.check("fetch")
    faults.arm([faults.FaultRule("fetch", "fatal")])
    with pytest.raises(faults.FatalFault):
        faults.check("fetch")
    # other points untouched by the plan stay silent
    faults.check("prefill")


def test_spec_parsing_round_trip():
    plan = faults.arm(
        "decode_launch:transient:on=3,every=2,times=4;"
        "prefill:fatal:match=XYZ,wedge=0.001"
    )
    kinds = {(r.point, r.kind) for r in plan.rules}
    assert kinds == {("decode_launch", "transient"), ("prefill", "fatal")}
    assert plan.rules[0].on_call == 3 and plan.rules[0].every == 2
    assert plan.rules[1].match == "XYZ" and plan.rules[1].wedge_s == 0.001
    for bad in ("nonsense", "fetch", "fetch:weird", "fetch:fatal:zz=1", ""):
        with pytest.raises(ValueError):
            faults.parse_spec(bad)


def test_arm_from_env():
    assert faults.arm_from_env({}) is None
    plan = faults.arm_from_env({"DLI_FAULTS": "alloc:transient:on=5"})
    assert plan is not None and plan.rules[0].point == "alloc"


def test_seeded_probabilistic_rule_is_deterministic():
    def draws():
        r = faults.FaultRule(
            "fetch", "transient", on_call=1, every=1, times=0, p=0.5, seed=7
        )
        return [r.should_fire("") for _ in range(32)]

    a, b = draws(), draws()
    assert a == b  # same seed => same firing sequence
    assert any(a) and not all(a)


def test_wedge_sleeps_before_raising():
    faults.arm([faults.FaultRule("fetch", "transient", wedge_s=0.15)])
    t0 = time.time()
    with pytest.raises(faults.TransientFault):
        faults.check("fetch")
    assert time.time() - t0 >= 0.14


# -- scheduler recovery: transient/fatal × injection point -------------------

@pytest.mark.parametrize("kind", ["transient", "fatal"])
@pytest.mark.parametrize("point", ["admission", "prefill", "decode_launch",
                                   "fetch"])
def test_one_shot_fault_recovers_bit_exact(engine, solo, point, kind):
    """One injected crash at each host-loop point: the supervisor
    restarts once, salvages the in-flight request as a continuation
    prefill, and the greedy output matches the fault-free run exactly."""
    cont = _cont(engine)
    try:
        faults.arm([faults.FaultRule(point, kind, on_call=1)])
        r = cont.submit(
            PROMPTS[0], max_tokens=10, greedy=True, chat=False
        )
        faults.disarm()
        assert r["status"] == "success", r
        assert r["response"] == solo[PROMPTS[0]]["response"]
        assert r["tokens_generated"] == solo[PROMPTS[0]]["tokens_generated"]
        assert cont.restarts_total == 1
        assert cont.stats()["supervisor"]["ready"] is True
        # the fleet keeps serving afterwards
        r2 = cont.submit(
            PROMPTS[1], max_tokens=10, greedy=True, chat=False
        )
        assert r2["status"] == "success"
        assert r2["response"] == solo[PROMPTS[1]]["response"]
    finally:
        cont.close()


def test_mid_decode_crash_recovers_fleet_bit_exact(engine, solo):
    """A fetch fault while SEVERAL requests are in flight: every one is
    salvaged, re-admitted serially, and finishes identical to solo; the
    restart counter matches the injection count (1)."""
    cont = _cont(engine)
    try:
        faults.arm([faults.FaultRule("fetch", "transient", on_call=3)])
        out = _submit_all(cont, PROMPTS)
        faults.disarm()
        for p in PROMPTS:
            assert out[p]["status"] == "success", out[p]
            assert out[p]["response"] == solo[p]["response"], p
        assert cont.restarts_total == 1
        s = cont.stats()
        assert s["occupied"] == 0
        assert s["supervisor"]["recovered"] >= 1
        # recovered envelopes are flagged
        assert any(out[p].get("recovered") for p in PROMPTS)
    finally:
        cont.close()


def test_repeated_transient_faults_within_budget(engine, solo):
    """Two separate crashes separated by healthy work: the consecutive-
    crash window resets, so the default budget absorbs both."""
    cont = _cont(engine)
    try:
        # fetch call 1 is restart #1's recovery chunk (healthy — resets
        # the consecutive window); fetch call 2 crashes MID-REQUEST, so
        # both restarts complete before submit() returns
        faults.arm([
            faults.FaultRule("decode_launch", "transient", on_call=2),
            faults.FaultRule("fetch", "transient", on_call=2),
        ])
        r = cont.submit(PROMPTS[2], max_tokens=10, greedy=True, chat=False)
        faults.disarm()
        assert r["status"] == "success", r
        assert r["response"] == solo[PROMPTS[2]]["response"]
        assert cont.restarts_total == 2
    finally:
        cont.close()


def test_streaming_across_crash_reassembles_exactly(engine, solo):
    """A crash mid-stream: deltas already emitted are never re-emitted,
    and the joined deltas still equal the fault-free response."""
    cont = _cont(engine, chunk_steps=2)
    try:
        faults.arm([faults.FaultRule("fetch", "transient", on_call=3)])
        events = list(cont.stream(
            PROMPTS[0], max_tokens=10, greedy=True, chat=False
        ))
        faults.disarm()
        final = events[-1]
        assert final["status"] == "success", final
        assert final["response"] == solo[PROMPTS[0]]["response"]
        deltas = [e["delta"] for e in events[:-1]]
        assert "".join(deltas) == solo[PROMPTS[0]]["response"]
        assert cont.restarts_total == 1
    finally:
        cont.close()


def test_restart_metrics_exposed(engine):
    cont = _cont(engine)
    try:
        faults.arm([faults.FaultRule("fetch", "transient", on_call=1)])
        r = cont.submit(PROMPTS[1], max_tokens=6, greedy=True, chat=False)
        faults.disarm()
        assert r["status"] == "success"
        m = engine.metrics
        assert m.get("dli_scheduler_restarts_total").labels(
            engine="continuous"
        ).value >= 1
        assert m.get("dli_requests_recovered_total").labels(
            engine="continuous"
        ).value >= 1
        render = m.render()
        assert "dli_scheduler_restarts_total" in render
        assert "dli_poison_requests_total" in render
        assert "dli_drain_duration_seconds" in render
    finally:
        cont.close()


# -- poison quarantine --------------------------------------------------------

def test_poison_quarantined_within_strikes_fleet_survives(engine, solo):
    """A request that deterministically crashes the scheduler on every
    admission is failed ALONE (error_type "poison") within
    poison_strikes restarts; its innocent fleet-mate completes
    bit-exact and the fleet keeps serving."""
    cont = _cont(engine, poison_strikes=2)
    try:
        faults.arm([
            faults.FaultRule("prefill", "fatal", match="POISONPILL",
                             every=1, times=0),
        ])
        out = {}

        def bg(name, prompt):
            out[name] = cont.submit(
                prompt, max_tokens=12, greedy=True, chat=False
            )

        t1 = threading.Thread(target=bg, args=("good", PROMPTS[2]))
        t1.start()
        time.sleep(0.3)  # the innocent tenant is decoding when P arrives
        t2 = threading.Thread(target=bg, args=("bad", POISON))
        t2.start()
        t1.join(timeout=300)
        t2.join(timeout=300)
        faults.disarm()
        assert out["bad"]["status"] == "failed"
        assert out["bad"]["error_type"] == "poison", out["bad"]
        assert out["good"]["status"] == "success", out["good"]
        solo_good = engine.generate(
            PROMPTS[2], max_tokens=12, greedy=True, chat=False
        )
        assert out["good"]["response"] == solo_good["response"]
        assert cont.poisoned_total == 1
        # isolated within poison_strikes restarts
        assert cont.restarts_total <= cont.poison_strikes
        # fleet survives the quarantine
        r = cont.submit("hello", max_tokens=5, greedy=True, chat=False)
        assert r["status"] == "success"
        assert cont.stats()["supervisor"]["ready"] is True
    finally:
        cont.close()


# -- restart-budget exhaustion ------------------------------------------------

def test_budget_exhaustion_fails_fleet_cleanly(engine):
    """Unbounded crashes: after restart_budget consecutive failures the
    scheduler declares itself dead — every waiter gets a clean
    `unavailable` envelope (no hangs), readiness goes false, and later
    submissions fail fast."""
    cont = _cont(engine, restart_budget=2, poison_strikes=99)
    try:
        faults.arm([
            faults.FaultRule("decode_launch", "fatal", every=1, times=0)
        ])
        r = cont.submit("doomed", max_tokens=6, greedy=True, chat=False)
        faults.disarm()
        assert r["status"] == "failed"
        assert r["error_type"] == "unavailable", r
        s = cont.stats()["supervisor"]
        assert s["dead"] is True and s["ready"] is False
        assert cont.restarts_total == 2  # budget worth of restarts
        r2 = cont.submit("after death", max_tokens=3, chat=False)
        assert r2["status"] == "failed"  # fails fast, never hangs
    finally:
        cont.close()


# -- resource accounting (the loop-death leak regression) ---------------------

def test_paged_pool_zero_leak_after_fatal_loop_death(engine):
    """Satellite regression: the loop-death path must release paged
    blocks — pool free == total after an injected fatal crash with no
    restart budget."""
    cont = _cont(engine, restart_budget=0, kv_pool_blocks=24,
                 kv_block_size=8)
    try:
        faults.arm([faults.FaultRule("decode_launch", "fatal")])
        r = cont.submit("leak check", max_tokens=8, greedy=True, chat=False)
        faults.disarm()
        assert r["error_type"] == "unavailable"
        assert cont._alloc.free_blocks == cont._alloc.n_blocks - 1
        assert cont._alloc.outstanding == 0
    finally:
        cont.close()


def test_paged_recovery_bit_exact_and_pool_clean(engine, solo):
    """Paged fleet: a transient crash mid-decode recovers bit-exact and
    the allocator books return to zero outstanding blocks once requests
    complete (prefix sharing disabled: engine cfg has no prefix cache)."""
    cont = _cont(engine, kv_pool_blocks=24, kv_block_size=8)
    try:
        faults.arm([faults.FaultRule("fetch", "transient", on_call=2)])
        r = cont.submit(
            PROMPTS[0], max_tokens=10, greedy=True, chat=False
        )
        faults.disarm()
        assert r["status"] == "success", r
        assert r["response"] == solo[PROMPTS[0]]["response"]
        assert cont.restarts_total == 1
        deadline = time.time() + 10
        while time.time() < deadline and cont._alloc.outstanding:
            time.sleep(0.05)
        assert cont._alloc.outstanding == 0
        assert cont._alloc.free_blocks == cont._alloc.n_blocks - 1
    finally:
        cont.close()


def test_alloc_fault_on_paged_admission_recovers(engine, solo):
    cont = _cont(engine, kv_pool_blocks=24, kv_block_size=8)
    try:
        faults.arm([faults.FaultRule("alloc", "transient", on_call=1)])
        r = cont.submit(PROMPTS[1], max_tokens=8, greedy=True, chat=False)
        faults.disarm()
        assert r["status"] == "success", r
        solo_ref = engine.generate(
            PROMPTS[1], max_tokens=8, greedy=True, chat=False
        )
        assert r["response"] == solo_ref["response"]
        assert cont.restarts_total == 1
        assert cont._alloc.outstanding == 0 or r["tokens_generated"] >= 0
    finally:
        cont.close()


# -- graceful drain + readiness ----------------------------------------------

def test_continuous_drain_completes_inflight_rejects_new(engine):
    cont = _cont(engine)
    try:
        out = {}

        def bg():
            out["r"] = cont.submit(
                PROMPTS[2], max_tokens=16, greedy=True, chat=False
            )

        t = threading.Thread(target=bg)
        t.start()
        time.sleep(0.2)
        assert cont.ready
        drained = cont.drain(deadline_s=120)
        assert drained is True
        assert not cont.ready
        t.join(timeout=60)
        assert out["r"]["status"] == "success"
        # new work is rejected with the draining envelope
        r = cont.submit("late arrival", max_tokens=3, chat=False)
        assert r["status"] == "failed" and r["error_type"] == "draining"
        # drain duration was recorded
        fam = engine.metrics.get("dli_drain_duration_seconds")
        assert fam.labels(component="continuous").count >= 1
    finally:
        cont.close()


def test_queue_drain(engine):
    from distributed_llm_inference_tpu.serving.queue import BatchingQueue

    q = BatchingQueue(engine, max_queue=8, max_batch=2, max_wait_ms=1.0)
    try:
        out = {}

        def bg():
            out["r"] = q.submit(
                PROMPTS[0], max_tokens=8, greedy=True, chat=False
            )

        t = threading.Thread(target=bg)
        t.start()
        time.sleep(0.1)
        assert q.drain(deadline_s=120) is True
        t.join(timeout=60)
        assert out["r"]["status"] == "success"
        r = q.submit("late", max_tokens=3, chat=False)
        assert r["status"] == "failed" and r["error_type"] == "draining"
    finally:
        q.close()


def _get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=15) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def test_ready_endpoint_and_health_ready_field(engine):
    server = InferenceServer(engine, host="127.0.0.1", port=0)
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        code, body, _ = _get(base, "/ready")
        assert code == 200 and body["ready"] is True
        code, body, _ = _get(base, "/health")
        assert code == 200 and body["ready"] is True
        # liveness/readiness split: draining keeps /health 200 while
        # /ready goes 503 (LB-friendly) and POSTs bounce with Retry-After
        server.state.draining = True
        code, body, hdrs = _get(base, "/ready")
        assert code == 503 and body["reason"] == "draining"
        assert hdrs.get("Retry-After")
        code, body, _ = _get(base, "/health")
        assert code == 200 and body["ready"] is False
        assert body["ready_reason"] == "draining"
        req = urllib.request.Request(
            base + "/generate",
            data=json.dumps({"prompt": "x"}).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(req, timeout=15)
        assert exc_info.value.code == 503
        assert exc_info.value.headers.get("Retry-After")
        assert json.loads(exc_info.value.read())["error_type"] == "draining"
    finally:
        server.state.draining = False
        server.shutdown()


def test_ready_false_while_scheduler_dead(engine):
    cont = _cont(engine, restart_budget=0, poison_strikes=99)
    server = InferenceServer(engine, host="127.0.0.1", port=0,
                             continuous=cont)
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        faults.arm([faults.FaultRule("decode_launch", "fatal")])
        r = cont.submit("kill it", max_tokens=4, greedy=True, chat=False)
        faults.disarm()
        assert r["error_type"] == "unavailable"
        code, body, _ = _get(base, "/ready")
        assert code == 503 and body["reason"] == "scheduler_dead"
    finally:
        server.shutdown()


def test_sigterm_drains_inflight_then_exits(engine):
    """The SIGTERM handler: readiness flips immediately, the in-flight
    request finishes, then the HTTP listener closes (clean exit path)."""
    cont = _cont(engine, chunk_steps=2)
    server = InferenceServer(engine, host="127.0.0.1", port=0,
                             continuous=cont, drain_deadline_s=120)
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    old_handler = signal.getsignal(signal.SIGTERM)
    out = {}
    try:
        server.install_signal_handlers()

        def bg():
            out["r"] = DistributedLLMClient(base, max_retries=0).generate(
                PROMPTS[2], max_tokens=16, greedy=True, chat=False,
                verbose=False,
            )

        t = threading.Thread(target=bg)
        t.start()
        time.sleep(0.3)
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.time() + 10
        while time.time() < deadline and not server.state.draining:
            time.sleep(0.05)
        assert server.state.draining
        # a 503 while draining — unless the drain already finished and
        # closed the listener (warm fleets finish 16 tokens fast), which
        # the connection error below proves just as well
        try:
            code, _body, _ = _get(base, "/ready")
            assert code == 503
        except (urllib.error.URLError, ConnectionError):
            pass
        t.join(timeout=120)
        assert out["r"]["status"] == "success", out["r"]
        # listener eventually closes: new connections fail
        deadline = time.time() + 60
        down = False
        while time.time() < deadline:
            try:
                _get(base, "/health")
                time.sleep(0.1)
            except Exception:
                down = True
                break
        assert down, "server never closed after drain"
    finally:
        signal.signal(signal.SIGTERM, old_handler)
        try:
            server.shutdown()
        except Exception:
            pass


# -- client retry discipline --------------------------------------------------

class _FlakyHandler(BaseHTTPRequestHandler):
    """Stub server: N rejections (with Retry-After) before success."""

    rejections = 2
    reject_code = 503
    seen = 0
    lock = threading.Lock()

    def log_message(self, fmt, *args):
        pass

    def do_POST(self):
        cls = type(self)
        with cls.lock:
            cls.seen += 1
            n = cls.seen
        self.rfile.read(int(self.headers.get("Content-Length", 0)))
        if n <= cls.rejections:
            body = json.dumps({
                "error": "Error: draining", "status": "failed",
                "error_type": "draining",
            }).encode()
            self.send_response(cls.reject_code)
            self.send_header("Retry-After", "0")
        else:
            body = json.dumps({
                "status": "success", "response": "ok", "attempts": n,
            }).encode()
            self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def _stub_server(handler):
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


@pytest.mark.parametrize("code", [429, 503])
def test_client_retries_on_retryable_codes(code):
    class H(_FlakyHandler):
        rejections = 2
        reject_code = code
        seen = 0
        lock = threading.Lock()

    httpd, base = _stub_server(H)
    try:
        c = DistributedLLMClient(base, max_retries=3, retry_backoff_s=0.01)
        r = c.generate("hi", verbose=False)
        assert r["status"] == "success"
        assert r["attempts"] == 3  # 2 rejections + the success
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_client_retry_bounded_then_returns_envelope():
    class H(_FlakyHandler):
        rejections = 99
        seen = 0
        lock = threading.Lock()

    httpd, base = _stub_server(H)
    try:
        c = DistributedLLMClient(base, max_retries=2, retry_backoff_s=0.01)
        r = c.generate("hi", verbose=False)
        assert r["status"] == "failed"
        assert r["error_type"] == "draining"
        assert H.seen == 3  # initial + 2 retries, bounded
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_client_never_retries_non_retryable():
    class H(_FlakyHandler):
        rejections = 99
        reject_code = 400
        seen = 0
        lock = threading.Lock()

    httpd, base = _stub_server(H)
    try:
        c = DistributedLLMClient(base, max_retries=3, retry_backoff_s=0.01)
        r = c.generate("hi", verbose=False)
        assert r["status"] == "failed"
        assert H.seen == 1  # a 400 is the caller's bug; retrying is spam
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_client_honors_retry_after():
    class H(_FlakyHandler):
        rejections = 1
        seen = 0
        lock = threading.Lock()

        def do_POST(self):
            cls = type(self)
            with cls.lock:
                cls.seen += 1
                n = cls.seen
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            if n == 1:
                body = b'{"status": "failed", "error_type": "draining"}'
                self.send_response(503)
                self.send_header("Retry-After", "0.4")
            else:
                body = b'{"status": "success", "response": "ok"}'
                self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd, base = _stub_server(H)
    try:
        c = DistributedLLMClient(base, max_retries=2, retry_backoff_s=0.001)
        t0 = time.time()
        r = c.generate("hi", verbose=False)
        elapsed = time.time() - t0
        assert r["status"] == "success"
        assert elapsed >= 0.4  # waited the server-directed delay, not 1 ms
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_client_stream_never_retries_after_partial_output(capsys):
    """The no-retry-after-partial-output contract: a stream that emits a
    delta and then fails mid-stream is returned as-is — exactly one
    request reaches the server."""

    class H(BaseHTTPRequestHandler):
        seen = 0
        lock = threading.Lock()

        def log_message(self, fmt, *args):
            pass

        def do_POST(self):
            cls = type(self)
            with cls.lock:
                cls.seen += 1
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.end_headers()
            self.wfile.write(b'{"delta": "partial", "tokens_so_far": 1}\n')
            self.wfile.flush()
            self.wfile.write(
                b'{"status": "failed", "error": "Error: boom", "done": true}\n'
            )

    httpd, base = _stub_server(H)
    try:
        c = DistributedLLMClient(base, max_retries=5, retry_backoff_s=0.01)
        r = c.generate_stream("hi")
        assert r["status"] == "failed"
        assert H.seen == 1  # partial output happened: NEVER replayed
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_client_stream_retries_pre_stream_rejection():
    """A 503 BEFORE the stream opens produced zero output — that one is
    retryable."""

    class H(BaseHTTPRequestHandler):
        seen = 0
        lock = threading.Lock()

        def log_message(self, fmt, *args):
            pass

        def do_POST(self):
            cls = type(self)
            with cls.lock:
                cls.seen += 1
                n = cls.seen
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            if n == 1:
                body = b'{"status": "failed", "error_type": "draining"}'
                self.send_response(503)
                self.send_header("Retry-After", "0")
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.end_headers()
            self.wfile.write(b'{"delta": "ok", "tokens_so_far": 1}\n')
            self.wfile.write(
                b'{"status": "success", "response": "ok", "done": true}\n'
            )

    httpd, base = _stub_server(H)
    try:
        c = DistributedLLMClient(base, max_retries=2, retry_backoff_s=0.01)
        r = c.generate_stream("hi")
        assert r["status"] == "success"
        assert H.seen == 2
    finally:
        httpd.shutdown()
        httpd.server_close()
