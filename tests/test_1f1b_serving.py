"""1F1B through the front door (round-2 review #4): BASELINE config 5's
microbatched backend served by the ENGINE and the HTTP surface, not just
the bench harness. Greedy fleets must match the plain pipeline backend
token-for-token (the zero-bubble schedule changes the compute order, not
the math — equivalence-tested in tests/test_schedule.py at the backend
level; here through the serving stack).
"""

import json
import urllib.request

import pytest

import jax

from distributed_llm_inference_tpu import (
    EngineConfig, MeshConfig, create_engine, get_model_config,
)
from distributed_llm_inference_tpu.models import api as M
from distributed_llm_inference_tpu.serving.server import InferenceServer

# fast-tier exclusion: 1F1B mesh compiles; run the full suite (plain
# `pytest`) to include it
pytestmark = pytest.mark.slow


class _NumTok:
    def encode(self, text):
        return [int(t) % 250 + 3 for t in text.split()] or [3]

    def decode(self, toks, skip_special_tokens=True):
        return " ".join(str(int(t)) for t in toks)


@pytest.fixture(scope="module")
def engines():
    cfg = get_model_config("test-llama-tiny", eos_token_id=-1)
    params = M.init_params(cfg, jax.random.PRNGKey(9))
    ecfg = EngineConfig(prefill_buckets=(32,))
    plain = create_engine(
        cfg, mesh_cfg=MeshConfig(pp=2), params=params, tokenizer=_NumTok(),
        engine_cfg=ecfg,
    )
    f1b = create_engine(
        cfg, mesh_cfg=MeshConfig(pp=2), microbatches=2, params=params,
        tokenizer=_NumTok(), engine_cfg=ecfg,
    )
    return plain, f1b


PROMPTS = [f"{3 * i + 1} {7 * i + 2} {5 * i + 4}" for i in range(8)]


def test_backend_selected(engines):
    _, f1b = engines
    assert f1b.backend.name == "pipeline-1f1b"
    assert f1b.backend.batch_granularity == 2


def test_batch8_matches_plain_pipeline_greedy(engines):
    plain, f1b = engines
    a = plain.generate_batch(PROMPTS, max_tokens=6, greedy=True, chat=False)
    b = f1b.generate_batch(PROMPTS, max_tokens=6, greedy=True, chat=False)
    assert a["status"] == b["status"] == "success"
    for ra, rb in zip(a["results"], b["results"]):
        assert ra["response"] == rb["response"]
        assert ra["tokens_generated"] == rb["tokens_generated"]


def test_solo_serves_on_plain_ring(engines):
    """Solo requests dispatch to the inherited plain-ring batch-1
    programs (round-3 review #3) — bit-identical to the plain pipeline,
    full solo envelope."""
    plain, f1b = engines
    a = plain.generate("11 22 33", max_tokens=5, greedy=True, chat=False)
    b = f1b.generate("11 22 33", max_tokens=5, greedy=True, chat=False)
    assert b["status"] == "success"
    assert b["response"] == a["response"]
    assert b["backend"] == "pipeline-1f1b"
    for k in ("time_taken", "tokens_generated", "tokens_per_sec",
              "prompt_tokens"):
        assert k in b


def test_solo_full_surface_on_1f1b(engines):
    """Round-3 review #3's acceptance: logprobs / logit_bias / penalties
    SERVE on the 1F1B backend now (plain-ring dispatch), identical to
    the plain pipeline."""
    plain, f1b = engines
    kw = dict(max_tokens=4, greedy=True, chat=False)
    a = plain.generate("1 2", logprobs=True, **kw)
    b = f1b.generate("1 2", logprobs=True, **kw)
    assert b["status"] == "success"
    assert b["response"] == a["response"]
    assert b["token_logprobs"] == a["token_logprobs"]
    a = plain.generate("1 2", logit_bias={"17": 100.0}, **kw)
    b = f1b.generate("1 2", logit_bias={"17": 100.0}, **kw)
    assert b["response"] == a["response"]
    assert set(b["response"].split()) == {"17"}
    a = plain.generate("5 5 5", frequency_penalty=1.5, **kw)
    b = f1b.generate("5 5 5", frequency_penalty=1.5, **kw)
    assert b["status"] == "success"
    assert b["response"] == a["response"]


def test_odd_batch_pads_to_granularity(engines):
    """B=3 on M=2 pads the fleet to 4 rows; 3 results come back."""
    _, f1b = engines
    r = f1b.generate_batch(PROMPTS[:3], max_tokens=4, greedy=True, chat=False)
    assert r["status"] == "success"
    assert len(r["results"]) == 3


def test_http_batch8_on_1f1b(engines):
    """The VERDICT's acceptance check: an HTTP {"prompts": [8]} request
    served by pipeline-1f1b, identical to the plain pipeline."""
    plain, f1b = engines
    expected = plain.generate_batch(PROMPTS, max_tokens=5, greedy=True,
                                    chat=False)
    server = InferenceServer(f1b, host="127.0.0.1", port=0)
    server.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/generate",
            data=json.dumps({
                "prompts": PROMPTS, "max_tokens": 5, "greedy": True,
                "chat": False,
            }).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=300) as resp:
            r = json.loads(resp.read())
        assert r["status"] == "success"
        assert r["backend"] == "pipeline-1f1b"
        got = [row["response"] for row in r["results"]]
        want = [row["response"] for row in expected["results"]]
        assert got == want
    finally:
        server.shutdown()


def test_1f1b_warmup(engines):
    """--warmup on a 1F1B engine compiles BOTH the batch-1 plain-ring solo
    programs (solo requests dispatch there now) and the granularity-
    multiple fleet programs."""
    _, f1b = engines
    stats = f1b.warmup(decode_buckets=(16,), batch_buckets=(2,))
    assert stats["programs"] > 0
