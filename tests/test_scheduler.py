"""SLO-aware chunked-prefill scheduler (engine/scheduler.py) tests.

The bar: chunked scheduling is a LAUNCH strategy, not a semantics change —
greedy output must be bit-identical to the whole-prefill admission flow,
decode must keep advancing while a long prompt lands chunk by chunk (the
TPOT guarantee the subsystem exists for), the per-step token budget must
be sliced deterministically (decode rows first, class-apportioned prefill,
starvation-free), SLO admission control must shed with class-local
Retry-After hints, and a crash mid-chunked-prefill must salvage with
bit-identical greedy output (PR-5 discipline, chunk-aligned progress).
"""

import threading
import time

import jax
import numpy as np
import pytest

from distributed_llm_inference_tpu import EngineConfig, get_model_config
from distributed_llm_inference_tpu.engine.continuous import (
    ContinuousEngine,
    _Request,
)
from distributed_llm_inference_tpu.engine.engine import InferenceEngine
from distributed_llm_inference_tpu.engine.scheduler import (
    SHED_GRACE,
    PrefillJob,
    SLOClass,
    TokenBudgetScheduler,
    parse_slo_classes,
)
from distributed_llm_inference_tpu.models import api as M
from distributed_llm_inference_tpu.utils import faults

TILE = 8


# -- planner units (no engine, no device) ------------------------------------

class _FakeReq:
    def __init__(self, enqueued):
        self.enqueued = enqueued


def _job(cls, tail, enqueued=0.0, slot=0):
    job = PrefillJob(
        _FakeReq(enqueued), ids=list(range(tail)), p0=0, prompt_len=tail,
        max_tokens=4, slot=slot, sampling=(0.7, 50, 0.9, True, 0.0, 1.0,
                                           0.0, 0.0),
        presence_row=None, table_row=None, cls=cls,
    )
    return job


def _sched(width=64, n_slots=4, classes=None, default="standard"):
    if classes is None:
        classes = {
            "interactive": SLOClass("interactive", 0.5, 0.1, 4.0, True),
            "standard": SLOClass("standard", 2.0, 0.5, 2.0, True),
            "batch": SLOClass("batch", 30.0, 2.0, 1.0, False),
        }
    return TokenBudgetScheduler(classes, default, width, TILE, n_slots)


def test_width_clamps_to_fleet_plus_one_tile():
    s = _sched(width=8, n_slots=4)
    # 4 decode tiles + >= 1 prefill tile: 40 tokens minimum at tile 8
    assert s.width == (4 + 1) * TILE
    # and always whole tiles
    assert _sched(width=70, n_slots=2).width == 72


def test_budget_slicing_reserves_decode_rows():
    s = _sched(width=64, n_slots=4)  # 8 tiles
    cls = s.classes["standard"]
    jobs = [_job(cls, tail=200, enqueued=1.0)]
    # 3 decoding slots -> 5 tiles = 40 tokens of prefill budget
    plan = s.plan(3, jobs, now=1.0)
    assert plan == [(jobs[0], 40)]
    # full fleet decoding is impossible WITH a pending job (a job holds a
    # slot), but the planner still never over-fills the launch
    plan = s.plan(7, jobs, now=1.0)
    assert plan == [(jobs[0], 8)]


def test_final_chunk_is_partial_not_padded():
    s = _sched(width=64, n_slots=4)
    cls = s.classes["standard"]
    jobs = [_job(cls, tail=13, enqueued=1.0)]
    plan = s.plan(0, jobs, now=1.0)
    assert plan == [(jobs[0], 13)]  # the tail itself, not a tile multiple


def test_class_apportionment_follows_weight_and_urgency():
    s = _sched(width=272, n_slots=4)  # 34 tiles
    inter, batch = s.classes["interactive"], s.classes["batch"]
    ji = _job(inter, tail=400, enqueued=100.0, slot=0)
    jb = _job(batch, tail=400, enqueued=100.0, slot=1)
    plan = dict(
        (id(j), n) for j, n in s.plan(0, [jb, ji], now=100.2)
    )
    # same wait: interactive's weight 4 (and tighter TTFT target ->
    # higher urgency) must out-apportion batch's weight 1
    assert plan[id(ji)] > plan[id(jb)]
    # a batch job that has waited far past ITS OWN 30s target gains
    # urgency and claws budget back
    jb_old = _job(batch, tail=400, enqueued=0.0, slot=1)
    plan2 = dict(
        (id(j), n) for j, n in s.plan(0, [jb_old, ji], now=100.2)
    )
    assert plan2[id(jb_old)] > plan[id(jb)]


def test_starvation_freedom_all_jobs_complete():
    """Many jobs, tiny budget: every job finishes within a bounded number
    of planned steps — the oldest job always progresses."""
    s = _sched(width=48, n_slots=4)  # 6 tiles; 4 decoding -> 2 prefill
    inter, batch = s.classes["interactive"], s.classes["batch"]
    jobs = [
        _job(batch, tail=64, enqueued=0.0, slot=0),
        _job(inter, tail=64, enqueued=0.1, slot=1),
        _job(inter, tail=64, enqueued=0.2, slot=2),
    ]
    pending = list(jobs)
    steps = 0
    while pending and steps < 100:
        for job, n in s.plan(4 - len(pending), pending, now=1.0 + steps):
            job.done += n
        pending = [j for j in pending if j.remaining > 0]
        steps += 1
    assert not pending, [(j.cls.name, j.remaining) for j in pending]
    assert steps <= 30  # 192 tokens at >= 16/step, with slack


def test_decode_pressure_halves_prefill_budget():
    s = _sched(width=96, n_slots=4)  # 12 tiles
    cls = s.classes["standard"]
    jobs = [_job(cls, tail=400, enqueued=1.0)]
    full = s.plan(2, jobs, now=1.0)[0][1]
    # report TPOT over the standard class's target, with standard decoding
    s.observe("standard", ttft_s=0.1, tpot_s=cls.tpot_target_s * 3)
    throttled = s.plan(2, jobs, active_classes={"standard"}, now=1.0)[0][1]
    assert throttled == full // 2
    # pressure on a class with NO active decode rows must not throttle
    unrelated = s.plan(2, jobs, active_classes=set(), now=1.0)[0][1]
    assert unrelated == full


def test_admission_control_shed_and_class_retry_after():
    s = _sched()
    inter, batch = s.classes["interactive"], s.classes["batch"]
    # no observed data: never shed on a guess
    assert not s.should_shed(inter, class_depth=50)
    # feedback: ~0.4s per interactive request -> depth 10 drains in ~4s,
    # past SHED_GRACE x 0.5s target
    for _ in range(4):
        s.observe("interactive", ttft_s=0.4, tpot_s=0.05)
    assert s.should_shed(inter, class_depth=10)
    assert not s.should_shed(inter, class_depth=2)  # tiny backlog: noise
    assert s.drain_estimate_s(inter, 10) > SHED_GRACE * inter.ttft_target_s
    # non-sheddable classes only queue, however deep
    for _ in range(4):
        s.observe("batch", ttft_s=5.0, tpot_s=1.0)
    assert not s.should_shed(batch, class_depth=50)
    # Retry-After is CLASS-local: same global state, different hints
    assert s.retry_after_s(inter, 10) == 4  # 10 x 0.4s
    assert s.retry_after_s(batch, 2) == 10  # 2 x 5.0s
    assert s.retry_after_s(inter, 0) == 1  # floor


def test_parse_slo_classes_validation():
    classes = parse_slo_classes(EngineConfig())
    assert EngineConfig().slo_default_class in classes
    with pytest.raises(ValueError):
        parse_slo_classes(EngineConfig(slo_default_class="nope"))
    with pytest.raises(ValueError):
        parse_slo_classes(
            EngineConfig(slo_classes=(("bad", -1.0, 0.1, 1.0, True),))
        )


# -- engine level -------------------------------------------------------------

SERVE_CFG = dict(dtype="float32", eos_token_id=-1, max_seq_len=512)


@pytest.fixture(scope="module")
def setup():
    cfg = get_model_config("test-llama-tiny", **SERVE_CFG)
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _cont(cfg, params, chunked, **kw):
    ecfg = dict(
        prefix_cache_entries=4, chunked_prefill=chunked,
        step_token_budget=64, prefill_buckets=(64, 128, 256),
    )
    ecfg.update(kw.pop("engine_cfg", {}))
    eng = InferenceEngine(cfg, params=params, engine_cfg=EngineConfig(**ecfg))
    args = dict(n_slots=4, chunk_steps=8, slot_max_seq=512,
                kv_pool_blocks=120, kv_block_size=16,
                restart_backoff_s=0.01)
    args.update(kw)
    return ContinuousEngine(eng, **args)


def test_chunked_greedy_identical_to_whole_prefill(setup):
    """The acceptance bar: mixed-launch chunked prefill serves the exact
    greedy token streams the whole-prefill admission flow serves — warm
    prefix reuse and a threaded mixed fleet included."""
    cfg, params = setup
    shared = " ".join(f"ctx{j}" for j in range(24))
    prompts = [
        "the quick brown fox jumps over the lazy dog",
        shared + " question one",
        shared + " question two",
        "short",
        "y " * 150,
    ]
    outs = {}
    for chunked in (False, True):
        cont = _cont(cfg, params, chunked)
        try:
            warm = [
                cont.submit(p, max_tokens=10, greedy=True, chat=False)
                for p in prompts
            ]
            wave = [None] * len(prompts)

            def run(i, c=cont, w=wave):
                w[i] = c.submit(prompts[i], max_tokens=10, greedy=True,
                                chat=False)

            ts = [
                threading.Thread(target=run, args=(i,))
                for i in range(len(prompts))
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            st = cont.stats()
        finally:
            cont.close()
        assert all(
            r["status"] == "success" for r in warm + wave
        ), (chunked, warm, wave)
        assert st.get("scheduler", {}).get("chunked_prefill", False) is chunked
        outs[chunked] = [r["response"] for r in warm + wave]
    assert outs[True] == outs[False]


def test_long_prompt_interleaves_with_decode(setup):
    """The tentpole behavior: a long prompt admitted while the fleet
    decodes lands as PREFILL CHUNKS interleaved with decode rows in the
    same launches — decode never stalls for the whole prefill."""
    cfg, params = setup
    cont = _cont(cfg, params, True, engine_cfg={"prefix_cache_entries": 0})
    eng = cont.engine
    try:
        cont.submit("warm", max_tokens=4, greedy=True, chat=False)
        outs = [None] * 3

        def decoder(i):
            outs[i] = cont.submit(
                f"short prompt {i}", max_tokens=250, greedy=True, chat=False
            )

        def longp():
            time.sleep(0.1)
            outs[2] = cont.submit(
                "y " * 150, max_tokens=6, greedy=True, chat=False
            )

        ts = [
            threading.Thread(target=decoder, args=(i,)) for i in range(2)
        ] + [threading.Thread(target=longp)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        snap = eng.metrics.snapshot()
    finally:
        cont.close()
    assert all(r and r["status"] == "success" for r in outs), outs

    def series(name):
        return {
            tuple(sorted(s["labels"].items())): s["value"]
            for s in snap.get(name, {}).get("series", [])
        }

    toks = series("dli_sched_step_tokens_total")
    # BOTH kinds rode scheduler launches: decode advanced during prefill
    assert toks.get((("kind", "decode"),), 0) > 0
    assert toks.get((("kind", "prefill"),), 0) > 0
    assert series("dli_sched_prefill_chunks_total").get((), 0) >= 4
    assert series("dli_sched_decode_rows_total").get((), 0) > 0
    launches = series("dli_ragged_launches_total")
    assert launches.get((("phase", "mixed"),), 0) > 0
    # the pool frees fully once the fleet drains (chunked scatter leaks
    # no blocks)
    assert cont._alloc.outstanding == 0
    assert cont._alloc.free_blocks == cont._alloc.n_blocks - 1


def test_streaming_through_chunked_path(setup):
    """stream() rides the chunked scheduler unchanged: deltas as chunks
    land, final envelope concatenates exactly."""
    cfg, params = setup
    cont = _cont(cfg, params, True)
    try:
        events = list(cont.stream(
            "stream me please", max_tokens=12, greedy=True, chat=False
        ))
    finally:
        cont.close()
    final = events[-1]
    assert final.get("done") and final["status"] == "success"
    joined = "".join(e.get("delta", "") for e in events[:-1])
    assert joined == final["response"]


def test_slo_class_envelope_and_shed(setup):
    """slo_class flows end to end (resolved, echoed) and queue-full 429s
    carry a CLASS-derived Retry-After, not a global-depth one."""
    cfg, params = setup
    cont = _cont(cfg, params, True, max_queue=3, n_slots=2,
                 kv_pool_blocks=70)
    try:
        r = cont.submit("hello", max_tokens=4, greedy=True, chat=False,
                        slo_class="interactive")
        assert r["status"] == "success" and r["slo_class"] == "interactive"
        # unknown classes fall back to the default (the serving edge
        # 400s unknown names before they reach the engine)
        r = cont.submit("hello again", max_tokens=4, greedy=True,
                        chat=False, slo_class="not-a-class")
        assert r["slo_class"] == cont._sched.default_name
        # wedge the worker so the queue fills deterministically: pause by
        # holding the queue full of batch-class requests
        with cont._cv:
            for i in range(3):
                q = _Request(f"fill {i}", dict(max_tokens=4, greedy=True,
                                               chat=False))
                q.slo = "batch"
                cont._queue.append(q)
            cont._note_queue_locked()
        shed = cont._enqueue(_mk_req("shed me", slo="interactive"))
        assert shed is not None and shed["error_type"] == "overloaded"
        assert shed["slo_class"] == "interactive"
        # class-local estimate: 0 interactive requests queued ahead ->
        # floor hint, NOT the batch backlog's
        assert shed["retry_after_s"] == 1
        shed_b = cont._enqueue(_mk_req("shed batch", slo="batch"))
        assert shed_b is not None
        assert shed_b["retry_after_s"] >= shed["retry_after_s"]
        with cont._cv:
            cont._queue.clear()
            cont._note_queue_locked()
    finally:
        cont.close()


def _mk_req(prompt, slo=None):
    req = _Request(prompt, dict(max_tokens=4, greedy=True, chat=False))
    req.slo = slo
    return req


def test_slo_over_target_shed(setup):
    """A sheddable class whose drain estimate overruns its TTFT target is
    refused at enqueue with the class drain estimate as Retry-After."""
    cfg, params = setup
    cont = _cont(cfg, params, True, max_queue=64)
    try:
        # feedback: interactive requests observed at ~1s TTFT
        for _ in range(4):
            cont._sched.observe("interactive", ttft_s=1.0, tpot_s=0.05)
        with cont._cv:
            for i in range(6):
                q = _Request(f"fill {i}", dict(max_tokens=4, greedy=True,
                                               chat=False))
                q.slo = "interactive"
                cont._queue.append(q)
            cont._note_queue_locked()
        shed = cont._enqueue(_mk_req("over target", slo="interactive"))
        assert shed is not None and shed["error_type"] == "overloaded"
        assert "TTFT target" in shed["error"]
        assert shed["retry_after_s"] == 6  # 6 queued x 1.0s EWMA
        # batch is non-sheddable: same depth, still queues
        for _ in range(4):
            cont._sched.observe("batch", ttft_s=1.0, tpot_s=0.5)
        with cont._cv:
            for q in cont._queue:
                q.slo = "batch"
            cont._note_queue_locked()
        ok = cont._enqueue(_mk_req("bulk", slo="batch"))
        assert ok is None
        with cont._cv:
            cont._queue.clear()
            cont._note_queue_locked()
    finally:
        cont.close()


def test_slo_queue_depth_gauge(setup):
    cfg, params = setup
    cont = _cont(cfg, params, True)
    eng = cont.engine
    try:
        cont.submit("hello", max_tokens=4, greedy=True, chat=False,
                    slo_class="batch")
        snap = eng.metrics.snapshot()
    finally:
        cont.close()
    series = {
        tuple(sorted(s["labels"].items())): s["value"]
        for s in snap.get("dli_slo_queue_depth", {}).get("series", [])
    }
    # every configured class exposes a series (schema-stable scrape);
    # the anonymous tenant "" carries untagged traffic
    for name in ("interactive", "standard", "batch"):
        assert (("slo_class", name), ("tenant", "")) in series, series


# -- serving surface ----------------------------------------------------------

def _post(port, path, payload):
    import json
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=120) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_slo_class_http_surface():
    """slo_class rides /generate and the OpenAI routes: accepted + echoed
    for configured classes, 400 for unknown names on both surfaces."""
    from distributed_llm_inference_tpu.serving.server import InferenceServer

    cfg = get_model_config("test-llama-tiny")
    eng = InferenceEngine(
        cfg, engine_cfg=EngineConfig(prefill_buckets=(32, 64))
    )
    server = InferenceServer(eng, host="127.0.0.1", port=0)
    server.start()
    try:
        code, r = _post(server.port, "/generate", {
            "prompt": "hi there", "max_tokens": 4,
            "slo_class": "interactive",
        })
        assert code == 200 and r["slo_class"] == "interactive"
        code, r = _post(server.port, "/generate", {
            "prompt": "hi there", "max_tokens": 4, "slo_class": "nope",
        })
        assert code == 400 and "slo_class" in r["error"]
        code, r = _post(server.port, "/v1/completions", {
            "model": cfg.name, "prompt": "hi", "max_tokens": 4,
            "slo_class": "batch",
        })
        assert code == 200, r
        code, r = _post(server.port, "/v1/chat/completions", {
            "model": cfg.name, "max_tokens": 4, "slo_class": "nope",
            "messages": [{"role": "user", "content": "hi"}],
        })
        assert code == 400
        assert r["error"]["param"] == "slo_class"
    finally:
        server.shutdown()


# -- chaos leg: crash mid-chunked-prefill ------------------------------------

@pytest.fixture(autouse=True)
def _always_disarm():
    faults.disarm()
    yield
    faults.disarm()


@pytest.mark.chaos
def test_crash_mid_chunked_prefill_salvages_bit_identical(setup):
    """A scheduler crash while a long prompt is mid-chunked-prefill (some
    chunks already in the pool) salvages every in-flight request: the
    long prompt re-admits from its chunk-aligned progress record (zero —
    the rebuilt pool holds none of its chunks) and every greedy stream is
    bit-identical to a fault-free run."""
    cfg, params = setup
    long_prompt = "y " * 150
    prompts = ["the quick brown fox", long_prompt, "a lazy dog"]

    def serve(spec):
        faults.disarm()
        cont = _cont(cfg, params, True,
                     engine_cfg={"prefix_cache_entries": 0})
        try:
            if spec:
                faults.arm(spec)
            out = {}
            lock = threading.Lock()

            def run(i, p):
                time.sleep(0.05 * i)
                r = cont.submit(p, max_tokens=12, greedy=True, chat=False)
                with lock:
                    out[p] = r

            ts = [
                threading.Thread(target=run, args=(i, p))
                for i, p in enumerate(prompts)
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=300)
            restarts = cont.restarts_total
        finally:
            faults.disarm()
            cont.close()
        return out, restarts

    clean, _ = serve(None)
    assert all(r["status"] == "success" for r in clean.values()), clean
    # crash the SECOND prefill-chunk launch that carries the long prompt:
    # its first chunk already landed in the pool — a mid-prefill crash
    crashed, restarts = serve(
        [faults.FaultRule("prefill", "transient", on_call=2, match="y y y")]
    )
    assert restarts >= 1
    for p in prompts:
        assert crashed[p]["status"] == "success", crashed[p]
        assert crashed[p]["response"] == clean[p]["response"], p
    # NOTE: the long prompt re-admits with NO continuation tokens (its
    # chunk-aligned progress record resets with the rebuilt pool), so
    # the PR-5 `recovered` continuation flag deliberately stays off —
    # bit-identical output is the contract, asserted above


@pytest.mark.chaos
def test_crash_at_mixed_decode_launch_salvages(setup):
    """Same bar for a crash at the mixed launch itself (decode rows in
    flight): salvage + continuation prefill, greedy bit-identical."""
    cfg, params = setup
    prompts = ["the quick brown fox", "jumps over the moon"]

    def serve(spec):
        faults.disarm()
        cont = _cont(cfg, params, True,
                     engine_cfg={"prefix_cache_entries": 0})
        try:
            if spec:
                faults.arm(spec)
            return {
                p: cont.submit(p, max_tokens=10, greedy=True, chat=False)
                for p in prompts
            }, cont.restarts_total
        finally:
            faults.disarm()
            cont.close()

    clean, _ = serve(None)
    crashed, restarts = serve("decode_launch:transient:on=3")
    assert restarts >= 1
    for p in prompts:
        assert crashed[p]["status"] == "success", crashed[p]
        assert crashed[p]["response"] == clean[p]["response"], p
