"""Checkpoint save / full restore / per-stage slice restore."""

import numpy as np
import jax
import jax.numpy as jnp

from distributed_llm_inference_tpu.models import api as M
from distributed_llm_inference_tpu.models import checkpoint as ckpt
from distributed_llm_inference_tpu.models.registry import get_model_config


def _tree_equal(a, b):
    fa, fb = ckpt._flatten(a), ckpt._flatten(b)
    assert set(fa) == set(fb)
    for k in fa:
        assert fa[k].dtype == fb[k].dtype, k
        np.testing.assert_array_equal(
            np.asarray(fa[k]).view(np.uint8), np.asarray(fb[k]).view(np.uint8), err_msg=k
        )


def test_round_trip_fp32_and_bf16(tmp_path):
    for dtype in ("float32", "bfloat16"):
        cfg = get_model_config("test-llama-tiny", dtype=dtype)
        params = M.init_params(cfg, jax.random.PRNGKey(0))
        d = str(tmp_path / dtype)
        ckpt.save_params(d, cfg, params)
        cfg2, params2 = ckpt.load_params(d)
        assert cfg2 == cfg
        _tree_equal(params, params2)


def test_stage_slice_matches_full(tmp_path):
    cfg = get_model_config("test-llama-tiny")  # 4 layers, untied, lm_head
    params = M.init_params(cfg, jax.random.PRNGKey(1))
    d = str(tmp_path / "ck")
    ckpt.save_params(d, cfg, params)

    pp = 2
    cfg0, st0 = ckpt.load_stage_params(d, pp, 0)
    cfg1, st1 = ckpt.load_stage_params(d, pp, 1)
    assert cfg0 == cfg and cfg1 == cfg

    # layer slices
    for k in params["layers"]:
        np.testing.assert_array_equal(
            np.asarray(st0["layers"][k]), np.asarray(params["layers"][k][:2])
        )
        np.testing.assert_array_equal(
            np.asarray(st1["layers"][k]), np.asarray(params["layers"][k][2:])
        )
    # role-filtered shared leaves: embed only on first, head only on last
    assert "embed" in st0 and "lm_head" not in st0 and "final_norm" not in st0
    assert "lm_head" in st1 and "final_norm" in st1 and "embed" not in st1


def test_stage_slice_tied_embeddings(tmp_path):
    cfg = get_model_config("test-gpt2-tiny")  # tied: last stage needs embed
    params = M.init_params(cfg, jax.random.PRNGKey(2))
    d = str(tmp_path / "ck")
    ckpt.save_params(d, cfg, params)
    _, st0 = ckpt.load_stage_params(d, 2, 0)
    _, st1 = ckpt.load_stage_params(d, 2, 1)
    assert "embed" in st0 and "pos_embed" in st0
    assert "embed" in st1  # tied LM head
    assert "pos_embed" not in st1  # position table feeds stage 0 only
    assert "final_norm_w" in st1 and "final_norm_w" not in st0


def test_loaded_params_forward_equal(tmp_path):
    """Logits from reloaded params match the originals bit-for-bit."""
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(3))
    d = str(tmp_path / "ck")
    ckpt.save_params(d, cfg, params)
    _, params2 = ckpt.load_params(d)
    tokens = jnp.asarray([[1, 5, 9, 2]], jnp.int32)
    cache = M.init_kv_cache(cfg, 1, max_seq=8)
    l1, _ = M.forward(cfg, params, tokens, cache, jnp.int32(0))
    l2, _ = M.forward(cfg, params2, tokens, M.init_kv_cache(cfg, 1, max_seq=8), jnp.int32(0))
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))


def _sharded_matches_reference(model_name, mesh_cfg, key):
    """load_params_sharded == the pad/device_put path, leaf by leaf."""
    import tempfile

    from distributed_llm_inference_tpu.parallel.mesh import build_mesh
    from distributed_llm_inference_tpu.parallel import partition as part

    cfg = get_model_config(model_name)
    params = M.init_params(cfg, jax.random.PRNGKey(key))
    with tempfile.TemporaryDirectory() as d:
        ckpt.save_params(d, cfg, params)
        mesh = build_mesh(mesh_cfg)
        cfg2, loaded = ckpt.load_params_sharded(d, mesh)
    assert cfg2 == cfg
    assert part.params_already_placed(loaded, mesh)
    ref_shared, ref_layers = part.shard_params(cfg, params, mesh)
    got_shared, got_layers = part.split_params(loaded)
    _tree_equal(ref_layers, got_layers)
    _tree_equal(ref_shared, got_shared)
    # feeding placed params back through shard_params is a no-op pass-through
    again_shared, again_layers = part.shard_params(cfg, loaded, mesh)
    assert again_layers["wq"] is got_layers["wq"] if "wq" in got_layers else True


def test_sharded_load_pp2():
    from distributed_llm_inference_tpu.config import MeshConfig

    _sharded_matches_reference("test-llama-tiny", MeshConfig(pp=2), 11)


def test_sharded_load_uneven_pp_and_tp():
    # 4 layers over pp=3 pads to 6 slots; tp=2 shards heads/ffn
    from distributed_llm_inference_tpu.config import MeshConfig

    _sharded_matches_reference("test-llama-tiny", MeshConfig(pp=3, tp=2), 12)


def test_sharded_load_gpt2_tied():
    from distributed_llm_inference_tpu.config import MeshConfig

    _sharded_matches_reference("test-gpt2-tiny", MeshConfig(pp=2), 13)
