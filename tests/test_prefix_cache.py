"""Prefix KV cache (engine/prefix.py): shared-prompt reuse.

Correctness bar: a request served off a cached prefix must produce
EXACTLY the tokens the cold path produces (KV at slot i depends only on
tokens[:i+1], so a spliced chunk-aligned snapshot is byte-valid), and the
store must stay LRU-bounded.
"""

import numpy as np
import pytest
import jax

from distributed_llm_inference_tpu import EngineConfig, MeshConfig, create_engine
from distributed_llm_inference_tpu.models.registry import get_model_config


def _engine(prefix_entries=4, chunk=16, mesh_cfg=None, max_seq=256, **cfg_over):
    return create_engine(
        get_model_config("test-llama-tiny", max_seq_len=max_seq, **cfg_over),
        mesh_cfg=mesh_cfg or MeshConfig(),
        engine_cfg=EngineConfig(
            prefill_buckets=(32, 64), max_seq_len=max_seq,
            prefix_cache_entries=prefix_entries, prefix_chunk=chunk,
        ),
    )


SHARED = "shared system prefix " * 4  # ~85 byte-fallback tokens > chunk


def test_hit_reproduces_cold_output_exactly():
    warm = _engine()
    cold = _engine(prefix_entries=0)

    p1 = SHARED + "first question"
    p2 = SHARED + "second, different question"
    r1 = warm.generate(p1, max_tokens=6, greedy=True, chat=False, seed=1)
    assert r1["status"] == "success" and "prefix_cached_tokens" not in r1
    r2 = warm.generate(p2, max_tokens=6, greedy=True, chat=False, seed=1)
    assert r2["status"] == "success"
    assert r2.get("prefix_cached_tokens", 0) > 0

    c2 = cold.generate(p2, max_tokens=6, greedy=True, chat=False, seed=1)
    assert r2["response"] == c2["response"]

    stats = warm.stats()["prefix_cache"]
    assert stats["hits"] >= 1 and stats["entries"] >= 1


@pytest.mark.slow
def test_identical_prompt_rerun_hits():
    eng = _engine()
    p = SHARED + "same prompt"
    r1 = eng.generate(p, max_tokens=5, greedy=True, chat=False, seed=2)
    r2 = eng.generate(p, max_tokens=5, greedy=True, chat=False, seed=2)
    assert r2.get("prefix_cached_tokens", 0) > 0
    assert r1["response"] == r2["response"]


@pytest.mark.slow
def test_conversation_prefix_grows():
    """Multi-turn chat: each turn extends the stored prefix, so turn N+1
    reuses turn N's longer snapshot (chained growth)."""
    eng = _engine()
    history = SHARED
    reused = []
    for turn in range(3):
        history += f" user turn {turn} says things; assistant replies. "
        r = eng.generate(history, max_tokens=4, greedy=True, chat=False, seed=3)
        assert r["status"] == "success"
        reused.append(r.get("prefix_cached_tokens", 0))
    assert reused[1] > 0 and reused[2] >= reused[1]


def test_lru_bound_holds():
    eng = _engine(prefix_entries=2)
    for i in range(5):
        r = eng.generate(
            f"prompt variant {i} " * 8, max_tokens=3, greedy=True,
            chat=False, seed=4,
        )
        assert r["status"] == "success"
    assert eng.stats()["prefix_cache"]["entries"] <= 2


@pytest.mark.slow
def test_prefix_plus_chunked_tail():
    """A cached prefix plus a tail longer than the largest bucket routes
    through extend() chunks from the cached offset."""
    eng = _engine()
    cold = _engine(prefix_entries=0)
    long_tail = "tail words " * 14  # ~150 tokens > 64 bucket
    p1 = SHARED + "x"
    p2 = SHARED + long_tail
    eng.generate(p1, max_tokens=3, greedy=True, chat=False, seed=5)
    r = eng.generate(p2, max_tokens=5, greedy=True, chat=False, seed=5)
    assert r["status"] == "success"
    assert r.get("prefix_cached_tokens", 0) > 0
    c = cold.generate(p2, max_tokens=5, greedy=True, chat=False, seed=5)
    assert r["response"] == c["response"]


@pytest.mark.slow
def test_prefix_cache_on_pipeline_mesh(eight_devices):
    warm = _engine(mesh_cfg=MeshConfig(dp=1, pp=2, tp=1))
    cold = _engine(prefix_entries=0)
    p1 = SHARED + "alpha"
    p2 = SHARED + "beta gamma"
    warm.generate(p1, max_tokens=4, greedy=True, chat=False, seed=6)
    r = warm.generate(p2, max_tokens=4, greedy=True, chat=False, seed=6)
    assert r["status"] == "success"
    assert r.get("prefix_cached_tokens", 0) > 0
    c = cold.generate(p2, max_tokens=4, greedy=True, chat=False, seed=6)
    assert r["response"] == c["response"]


@pytest.mark.slow
def test_auto_disable_on_incompatible_cache(eight_devices):
    """The context-parallel backend's slot-tagged cache cannot snapshot/
    splice: the prefix cache must disable itself (checked against the live
    buffer, so a warmup()-initialized cache is covered) instead of pinning
    unusable snapshots in HBM."""
    eng = create_engine(
        get_model_config("test-llama-tiny", max_seq_len=256),
        mesh_cfg=MeshConfig(sp=2),
        engine_cfg=EngineConfig(
            prefill_buckets=(32, 64), max_seq_len=256,
            prefix_cache_entries=4, prefix_chunk=16,
        ),
    )
    eng.warmup(decode_buckets=(16,), batch_buckets=())  # sets _cache first
    r = eng.generate("short cp prompt", max_tokens=3, greedy=True, chat=False)
    assert r["status"] == "success", r
    assert eng._prefix is None  # auto-disabled, not silently hoarding
    assert "prefix_cache" not in eng.stats()


@pytest.mark.slow
def test_ttft_improves_on_hit():
    """The point of the feature: a hit's TTFT beats the cold TTFT for the
    same prompt (prefill covers only the tail). Generous margin — CI runs
    on one CPU core."""
    eng = _engine(chunk=64, max_seq=1024)
    p = ("long shared context " * 30) + "question"  # ~600 tokens, chunked
    r1 = eng.generate(p, max_tokens=2, greedy=True, chat=False, seed=7)
    r2 = eng.generate(p, max_tokens=2, greedy=True, chat=False, seed=7)
    assert r2.get("prefix_cached_tokens", 0) >= 512
    # warm-vs-warm comparison is unfair on compile-heavy first calls;
    # just require the hit path not to be slower than 1.5x the miss
    assert r2["ttft_s"] <= r1["ttft_s"] * 1.5


def test_store_double_snapshot_race_drops_loser():
    """Two threads racing store() for the same key both pass the first
    key-exists check and both snapshot (the device _extract runs outside
    the lock on purpose) — the insert must re-check under the lock and
    DROP the loser: exactly one entry, exactly one winner return value,
    no eviction charged for the duplicate."""
    import threading

    import jax.numpy as jnp

    from distributed_llm_inference_tpu.engine import prefix as PX

    cache = {
        "k": jnp.zeros((2, 1, 2, 16, 4)),
        "v": jnp.zeros((2, 1, 2, 16, 4)),
    }
    pc = PX.PrefixCache(max_entries=4, chunk=8)
    barrier = threading.Barrier(2)
    real_extract = PX._extract

    def racy_extract(c, p):
        # both threads must be PAST the first key check before either
        # inserts — the widest possible race window
        barrier.wait(timeout=10)
        return real_extract(c, p)

    ids = list(range(16))
    out = [None, None]

    def run(i):
        out[i] = pc.store(ids, 16, cache)

    PX._extract = racy_extract
    try:
        threads = [threading.Thread(target=run, args=(i,)) for i in (0, 1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        PX._extract = real_extract
    assert sorted(out) == [0, 16]  # one winner, one dropped loser
    st = pc.stats()
    assert st["entries"] == 1
    assert st["evictions"] == 0
