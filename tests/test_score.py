"""Teacher-forced scoring (engine.score / OpenAI echo+logprobs+max_tokens=0
— the lm-eval loglikelihood pattern). Parity target: HF log_softmax over
the same forward."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from distributed_llm_inference_tpu import EngineConfig, get_model_config
from distributed_llm_inference_tpu.engine.engine import InferenceEngine
from distributed_llm_inference_tpu.models.convert import params_from_hf_model
from distributed_llm_inference_tpu.serving.server import InferenceServer


def _tiny_hf():
    cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=10000.0,
        pad_token_id=0, eos_token_id=2, bos_token_id=1,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    return model


@pytest.fixture(scope="module")
def served():
    hf = _tiny_hf()
    cfg, params = params_from_hf_model(hf, dtype="float32")
    engine = InferenceEngine(
        cfg, params=params, engine_cfg=EngineConfig(prefill_buckets=(32, 64))
    )
    server = InferenceServer(engine, host="127.0.0.1", port=0)
    server.start()
    yield hf, server
    server.shutdown()


@pytest.mark.slow
def test_score_matches_hf_teacher_forcing(served):
    hf, server = served
    eng = server.engine
    prompt = "score this exact text"
    r = eng.score(prompt)
    assert r["status"] == "success", r
    ids = eng.tokenizer.encode(prompt)
    assert r["prompt_tokens"] == len(ids)
    assert r["token_logprobs"][0] is None
    assert len(r["token_logprobs"]) == len(ids)
    assert len(r["token_strings"]) == len(ids)

    with torch.no_grad():
        logits = hf(torch.tensor([ids])).logits[0]
    lp = torch.log_softmax(logits.float(), dim=-1)
    want = [float(lp[t, ids[t + 1]]) for t in range(len(ids) - 1)]
    got = r["token_logprobs"][1:]
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(r["logprob_sum"], sum(want), rtol=2e-4,
                               atol=2e-3)


@pytest.mark.slow
def test_openai_echo_scoring_route(served):
    _, server = served
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}/v1/completions",
        data=json.dumps({
            "prompt": "echo me", "echo": True, "logprobs": 0,
            "max_tokens": 0,
        }).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=120) as r:
        out = json.loads(r.read())
    c = out["choices"][0]
    assert c["text"] == "echo me"
    assert c["logprobs"]["token_logprobs"][0] is None
    assert all(x <= 0.0 for x in c["logprobs"]["token_logprobs"][1:])
    assert out["usage"]["completion_tokens"] == 0
    assert out["usage"]["prompt_tokens"] == len(
        server.engine.tokenizer.encode("echo me")
    )
    # the scored ids match an engine-level score call
    ref = server.engine.score("echo me")
    assert c["logprobs"]["token_logprobs"][1:] == ref["token_logprobs"][1:]


@pytest.mark.slow
def test_openai_echo_without_scoring_form_rejected(served):
    _, server = served
    for body in [
        {"prompt": "x", "echo": True, "max_tokens": 5},           # generates
        {"prompt": "x", "echo": True, "max_tokens": 0},           # no logprobs
        {"prompt": "x", "echo": True, "logprobs": 0, "max_tokens": 0,
         "stream": True},
    ]:
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/completions",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 400


def test_score_chunked_matches_single_forward(served):
    """A prompt longer than the largest bucket chunk-scores through the
    KV cache: the stitched logprobs (incl. the chunk-boundary tokens)
    must equal HF's single-forward teacher forcing."""
    hf, server = served
    eng = server.engine
    # buckets are (32, 64): >64 tokens forces 1 full chunk + padded tail
    # (max_seq_len of the tiny config is 128)
    prompt = "chunked scoring wants " * 4
    r = eng.score(prompt)
    assert r["status"] == "success", r
    ids = eng.tokenizer.encode(prompt)
    assert len(ids) > 64  # actually chunked
    with torch.no_grad():
        logits = hf(torch.tensor([ids])).logits[0]
    lp = torch.log_softmax(logits.float(), dim=-1)
    want = [float(lp[t, ids[t + 1]]) for t in range(len(ids) - 1)]
    np.testing.assert_allclose(r["token_logprobs"][1:], want,
                               rtol=3e-4, atol=3e-4)


@pytest.mark.slow
def test_score_top_n_alternatives(served):
    hf, server = served
    eng = server.engine
    r = eng.score("top n check", top_n=3)
    assert r["status"] == "success", r
    tops = r["top_logprobs"]
    assert tops[0] is None
    assert len(tops) == r["prompt_tokens"]
    ids = eng.tokenizer.encode("top n check")
    with torch.no_grad():
        logits = hf(torch.tensor([ids])).logits[0]
    lp = torch.log_softmax(logits.float(), dim=-1)
    for t, alt in enumerate(tops[1:]):
        # distinct ids may decode to the same string and collapse (byte
        # tokenizer) — never more than N, best logprob kept per string
        assert 1 <= len(alt) <= 3
        # the top-1 alternative's logprob is the distribution's max
        want_max = float(lp[t].max())
        got_max = max(alt.values())
        np.testing.assert_allclose(got_max, want_max, rtol=3e-4, atol=3e-4)
        # and every listed logprob >= the scored token's logprob floor
        assert all(v <= 0.0 for v in alt.values())


@pytest.mark.slow
def test_openai_echo_top_logprobs(served):
    _, server = served
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}/v1/completions",
        data=json.dumps({
            "prompt": "echo tops", "echo": True, "logprobs": 2,
            "max_tokens": 0,
        }).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=120) as r:
        out = json.loads(r.read())
    tl = out["choices"][0]["logprobs"]["top_logprobs"]
    assert tl[0] is None
    assert all(isinstance(d, dict) and 1 <= len(d) <= 2 for d in tl[1:])


def test_score_rejects_too_short():
    cfg = get_model_config("test-llama-tiny")
    eng = InferenceEngine(cfg, engine_cfg=EngineConfig(prefill_buckets=(32,)))
    r = eng.score("")
    assert r["status"] == "failed"
    assert r["error_type"] == "invalid_request"
