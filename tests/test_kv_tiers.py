"""Tiered KV cache hierarchy suite (engine/shadow.py tiers 1+2, the
streamed /kv wire format, the proactive POST /kv push, and the router's
multi-holder residency — ISSUE r16).

Layers:
  * disk-tier units: LRU spill (demotion) instead of drop, promotion on
    hit, startup rescan, orphan hygiene, LRU bounds with subtree
    cascade, copier-backpressure spill;
  * corruption matrix (the PR-11 tamper matrix extended to tier 2):
    truncated / tampered / wrong-block-size chunk files REJECT into the
    next tier up — a miss and a cold re-prefill, never wrong KV;
  * stream wire units: frame round trip, mid-stream tamper and
    truncation aborting before the final digest, whole-blob fallback;
  * push units: decode_push self-naming validation, POST /kv over real
    HTTP, the pushed chain servable onward;
  * engine e2e: disk-warm admission bit-identical to cold, crash-shaped
    (new store over the same dir) restore with the disk tier populated;
  * router units: multi-holder residency spread, purge, bounded /health
    bootstrap.
"""

from __future__ import annotations

import glob
import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributed_llm_inference_tpu import create_engine
from distributed_llm_inference_tpu.engine.block_prefix import chunk_digests
from distributed_llm_inference_tpu.config import EngineConfig
from distributed_llm_inference_tpu.engine.continuous import ContinuousEngine
from distributed_llm_inference_tpu.engine.shadow import ShadowStore
from distributed_llm_inference_tpu.serving import kv_fabric as KF
from distributed_llm_inference_tpu.serving.router import Replica, Router
from distributed_llm_inference_tpu.serving.server import InferenceServer

BS = 16  # kv block size for engine-level tests; units use 4


class _E:
    def __init__(self, leaves):
        self.leaves = leaves


def _chain(n_blocks: int, bs: int = 4, base: int = 1):
    ids = [(base + i) % 250 + 1 for i in range(n_blocks * bs)]
    keys = [tuple(ids[: (i + 1) * bs]) for i in range(n_blocks)]
    entries = [
        _E([
            np.full((2, 3), i + base, np.float32),
            (np.arange(6, dtype=np.int8) + i).reshape(2, 3),
        ])
        for i in range(n_blocks)
    ]
    return ids, keys, entries


def _store(tmp_path, **kw):
    kw.setdefault("max_blocks", 4)
    kw.setdefault("disk_dir", str(tmp_path / "kvdisk"))
    return ShadowStore(4, **kw)


# -- disk-tier units ----------------------------------------------------------

def test_host_eviction_demotes_to_disk_and_promotes_back(tmp_path):
    st = _store(tmp_path)
    try:
        _, keys_a, entries_a = _chain(4, base=1)
        st.put_host(keys_a, [e.leaves for e in entries_a], seq=0)
        deep_a = st.digest_of(keys_a[-1])
        # a second chain LRU-evicts the first — which must DEMOTE, not
        # drop: still resident, now in tier 2
        _, keys_b, entries_b = _chain(4, base=101)
        st.put_host(keys_b, [e.leaves for e in entries_b], seq=1)
        s = st.stats()
        assert s["demoted"] == 4 and s["disk_blocks"] == 4
        assert st.digest_tier(deep_a) == "disk"
        assert st.digest_tier(st.digest_of(keys_b[-1])) == "host"
        assert all(st.has_resident(k) for k in keys_a)
        files = glob.glob(os.path.join(st.disk_dir, "chunk_*.npz"))
        assert len(files) == 4
        # a chain lookup through the digest surface promotes the whole
        # chain back into the host tier, bit-identical
        got = st.chain_for_digest(deep_a)
        assert got is not None
        got_keys, got_entries = got
        assert got_keys == keys_a
        for e, ref in zip(got_entries, entries_a):
            np.testing.assert_array_equal(e.leaves[0], ref.leaves[0])
            np.testing.assert_array_equal(e.leaves[1], ref.leaves[1])
            assert e.leaves[1].dtype == np.int8
        s = st.stats()
        assert s["disk_hits"] == 4 and s["promoted"] >= 4
        assert st.digest_tier(deep_a) == "host"
    finally:
        st.close()


def test_no_disk_dir_keeps_drop_semantics(tmp_path):
    st = ShadowStore(4, max_blocks=4)  # no tier 2
    try:
        _, keys_a, entries_a = _chain(4, base=1)
        st.put_host(keys_a, [e.leaves for e in entries_a], seq=0)
        _, keys_b, entries_b = _chain(4, base=101)
        st.put_host(keys_b, [e.leaves for e in entries_b], seq=1)
        assert st.chain_for_digest(st.digest_of(keys_a[-1])) is None
        assert st.stats()["demoted"] == 0
    finally:
        st.close()


def test_disk_scan_rebuilds_index_across_restart(tmp_path):
    """Crash-shaped persistence: a NEW store over the same dir (no
    save()/load() — the chunk files ARE the persisted form) serves the
    demoted chain back, bit-identical."""
    st = _store(tmp_path)
    _, keys, entries = _chain(3, base=7)
    st.put_host(keys, [e.leaves for e in entries], seq=3)
    deep = st.digest_of(keys[-1])
    _, keys_b, entries_b = _chain(4, base=201)
    st.put_host(keys_b, [e.leaves for e in entries_b], seq=4)  # demote a
    assert st.digest_tier(deep) == "disk"
    st.close()

    st2 = _store(tmp_path)
    try:
        assert st2.stats()["disk_blocks"] >= 3
        assert st2.digest_tier(deep) == "disk"
        got = st2.chain_for_digest(deep)
        assert got is not None
        got_keys, got_entries = got
        assert got_keys == keys
        np.testing.assert_array_equal(
            got_entries[1].leaves[0], entries[1].leaves[0]
        )
    finally:
        st2.close()


def test_disk_scan_deletes_orphans_and_junk(tmp_path):
    st = _store(tmp_path)
    _, keys, entries = _chain(3, base=7)
    st.put_host(keys, [e.leaves for e in entries], seq=0)
    _, keys_b, entries_b = _chain(4, base=201)
    st.put_host(keys_b, [e.leaves for e in entries_b], seq=1)
    d = st.disk_dir
    # delete the chain's ROOT chunk: its descendants become orphans
    root_digest = st.digest_of(keys[0])
    st.close()
    os.remove(os.path.join(d, f"chunk_{root_digest}.npz"))
    with open(os.path.join(d, "chunk_deadbeef00.npz"), "wb") as f:
        f.write(b"junk, not an npz")
    st2 = _store(tmp_path)
    try:
        # orphans + junk gone from index AND dir
        assert all(st2.digest_tier(st2.digest_of(k)) is None for k in keys)
        names = os.listdir(d)
        assert "chunk_deadbeef00.npz" not in names
        assert st2.stats()["disk_rejected"] >= 1
    finally:
        st2.close()


def test_disk_lru_bound_cascades_subtrees(tmp_path):
    st = _store(tmp_path, max_blocks=2, max_disk_blocks=4)
    try:
        # four 2-block chains through a 2-entry host tier: each insert
        # demotes the previous chain; the third demotion overflows the
        # 4-entry disk tier, which must evict the oldest WHOLE chain
        # (cascade), never leave an interior hole
        chains = []
        for base in (1, 61, 121, 181):
            _, keys, entries = _chain(2, base=base)
            st.put_host(keys, [e.leaves for e in entries], seq=base)
            chains.append(keys)
        s = st.stats()
        assert s["disk_blocks"] <= 4
        # the oldest chain is fully gone — evicted as a unit
        assert all(
            st.digest_tier(st.digest_of(k)) is None for k in chains[0]
        )
        for keys in chains:
            on_disk = [k for k in keys if st.digest_tier(st.digest_of(k))
                       == "disk"]
            # chains are on disk whole or not at all (no interior holes)
            assert len(on_disk) in (0, len(keys))
        files = glob.glob(os.path.join(st.disk_dir, "chunk_*.npz"))
        assert len(files) == s["disk_blocks"]
    finally:
        st.close()


def test_copier_backpressure_spills_to_disk_not_drop(tmp_path):
    """put_async past max_pending lands batches straight in tier 2 (a
    demotion); only a doubly-full queue drops. The copier only wakes on
    notify, so queue sentinels appended WITHOUT one hold the depth
    steady until put_async's own notify."""
    st = _store(tmp_path, max_blocks=64, max_pending=1)
    try:
        with st._lock:
            st._q.append(([], [], 0, False))  # full (>= max_pending)
        _, keys, entries = _chain(1, base=31)
        ok = st.put_async(
            keys, [np.stack([e.leaves[j] for e in entries])
                   for j in range(2)], seq=0,
        )
        assert ok  # accepted as a spill, not dropped
        assert st.flush(10.0)
        assert st.stats()["dropped"] == 0
        assert st.stats()["demoted"] == 1
        assert st.digest_tier(st.digest_of(keys[0])) == "disk"
        # doubly-full (no room even for spill): drop, counted
        with st._lock:
            st._q.append(([], [], 0, False))
            st._q.append(([], [], 0, False))
        ok2 = st.put_async(
            [(9, 9, 9, 9)], [np.zeros((1, 2, 3), np.float32)] * 2,
            seq=0,
        )
        assert not ok2
        assert st.stats()["dropped"] == 1
    finally:
        st.close()


def test_select_spans_disk_tier(tmp_path):
    st = _store(tmp_path, max_blocks=2)
    try:
        _, keys, entries = _chain(2, base=1)
        st.put_host(keys, [e.leaves for e in entries], seq=0)
        _, keys_b, entries_b = _chain(2, base=61)
        st.put_host(keys_b, [e.leaves for e in entries_b], seq=1)
        # budget 4: host chain (b) + disk chain (a), parents first
        sel, leaf_keys = st.select(4)
        got_keys = [k for k, _ in sel]
        assert set(got_keys) == set(keys) | set(keys_b)
        assert sorted(map(len, got_keys)) == [len(k) for k, _ in sel]
        assert set(leaf_keys) == {keys[-1], keys_b[-1]}
        # budget 2 prefers the MRU (host) chain only
        st2_sel, _ = st.select(2)
        assert {k for k, _ in st2_sel} == set(keys_b)
    finally:
        st.close()


def test_resident_digests_mru_and_bounded(tmp_path):
    st = _store(tmp_path, max_blocks=2)
    try:
        _, keys, entries = _chain(2, base=1)
        st.put_host(keys, [e.leaves for e in entries], seq=0)
        _, keys_b, entries_b = _chain(2, base=61)
        st.put_host(keys_b, [e.leaves for e in entries_b], seq=1)
        ds = st.resident_digests()
        assert len(ds) == 4  # host pair (MRU first) then disk pair
        assert ds[0] == st.digest_of(keys_b[-1])
        assert st.resident_digests(limit=3) == ds[:3]
        assert len(st.resident_digests(limit=1)) == 1
    finally:
        st.close()


# -- tier-2 corruption matrix -------------------------------------------------

def _demote_one(tmp_path):
    st = _store(tmp_path)
    _, keys, entries = _chain(2, base=1)
    st.put_host(keys, [e.leaves for e in entries], seq=0)
    _, keys_b, entries_b = _chain(4, base=101)
    st.put_host(keys_b, [e.leaves for e in entries_b], seq=1)
    deep = st.digest_of(keys[-1])
    assert st.digest_tier(deep) == "disk"
    path = os.path.join(st.disk_dir, f"chunk_{deep}.npz")
    assert os.path.exists(path)
    return st, deep, path


@pytest.mark.parametrize("tamper", ["truncate", "tokens", "block_size"])
def test_corrupt_chunk_file_rejects_into_miss(tmp_path, tamper):
    """The PR-11 tamper matrix at tier 2: a truncated, token-tampered,
    or wrong-block-size chunk file is rejected AND deleted on load — the
    lookup degrades to a miss (next tier up: cold re-prefill), never
    wrong KV."""
    st, deep, path = _demote_one(tmp_path)
    try:
        if tamper == "truncate":
            data = open(path, "rb").read()
            with open(path, "wb") as f:
                f.write(data[: len(data) // 2])
        else:
            with np.load(path, allow_pickle=False) as z:
                manifest = json.loads(str(z["manifest"]))
                arrays = {
                    k: np.array(z[k]) for k in z.files if k != "manifest"
                }
            if tamper == "tokens":
                manifest["t"][0] = (manifest["t"][0] % 250) + 1
            else:
                manifest["block_size"] = 8
            arrays["manifest"] = np.array(json.dumps(manifest))
            with open(path, "wb") as f:
                np.savez(f, **arrays)
        before = st.stats()["disk_rejected"]
        assert st.chain_for_digest(deep) is None  # miss, not an error
        assert st.stats()["disk_rejected"] == before + 1
        assert not os.path.exists(path)  # rejected file is deleted
        assert st.digest_tier(deep) is None
    finally:
        st.close()


# -- stream wire units --------------------------------------------------------

def _frames_bytes(bs, keys, entries):
    """A whole streamed /kv body (every frame + terminator) as bytes."""
    res = []
    ids = list(keys[-1])
    for i, (k, e) in enumerate(zip(keys, entries)):
        d = chunk_digests(ids, bs, max_chunks=i + 1)[-1]
        payload = KF.encode_frame(bs, k[-bs:], d, e.leaves)
        res.append(len(payload).to_bytes(8, "big") + payload)
    res.append((0).to_bytes(8, "big"))
    return b"".join(res)


class _Sock:
    """file-like over bytes for fetch_stream's reader contract."""

    def __init__(self, data):
        self._d = data
        self._i = 0

    def read(self, n):
        out = self._d[self._i:self._i + n]
        self._i += len(out)
        return out


def test_stream_frame_roundtrip():
    ids, keys, entries = _chain(3)
    data = _frames_bytes(4, keys, entries)
    sock = _Sock(data)
    # decode frame-at-a-time exactly as the client does
    got = []
    running = None
    while True:
        n = int.from_bytes(KF._read_exact(sock, 8), "big")
        if n == 0:
            break
        chunk, digest, leaves = KF.decode_frame(
            KF._read_exact(sock, n), 4
        )
        got.append((chunk, digest, leaves))
        running = digest
    assert len(got) == 3
    assert running == KF.chain_digest(ids, 4)
    for i, (chunk, _, leaves) in enumerate(got):
        assert tuple(chunk) == keys[i][-4:]
        np.testing.assert_array_equal(leaves[0], entries[i].leaves[0])


def test_stream_truncation_raises():
    ids, keys, entries = _chain(3)
    data = _frames_bytes(4, keys, entries)
    sock = _Sock(data[: len(data) - 12])  # cut inside the last frame
    with pytest.raises(KF.FabricPayloadError):
        while True:
            n = int.from_bytes(KF._read_exact(sock, 8), "big")
            if n == 0:
                break
            KF.decode_frame(KF._read_exact(sock, n), 4)


def test_serve_chain_stream_matches_whole_blob(tmp_path):
    """The streamed serve and the whole-blob serve describe the SAME
    chain: reassembling the frames yields blocks identical to
    decode_chain over serve_chain, and a disk-resident chain streams
    with tier='disk' (the pre-promotion label the wire accounting
    needs)."""
    st = _store(tmp_path)
    try:
        ids, keys, entries = _chain(3, base=11)
        st.put_host(keys, [e.leaves for e in entries], seq=0)
        deep = st.digest_of(keys[-1])
        res = KF.serve_chain_stream(st, deep)
        assert res is not None
        n_chunks, tier, frames = res
        assert (n_chunks, tier) == (3, "host")
        body = b"".join(frames)
        whole = KF.serve_chain(st, deep)
        keys_w, blocks_w = KF.decode_chain(whole, 4, deep)
        sock = _Sock(body)
        i = 0
        while True:
            n = int.from_bytes(KF._read_exact(sock, 8), "big")
            if n == 0:
                break
            chunk, _, leaves = KF.decode_frame(KF._read_exact(sock, n), 4)
            assert tuple(chunk) == tuple(keys_w[i][-4:])
            for a, b in zip(leaves, blocks_w[i]):
                np.testing.assert_array_equal(a, b)
            i += 1
        assert i == n_chunks
        # demote the chain, then stream again: tier must say "disk"
        _, keys_b, entries_b = _chain(4, base=201)
        st.put_host(keys_b, [e.leaves for e in entries_b], seq=1)
        assert st.digest_tier(deep) == "disk"
        res2 = KF.serve_chain_stream(st, deep)
        assert res2 is not None and res2[1] == "disk"
        assert KF.serve_chain_stream(st, "deadbeef00") is None
    finally:
        st.close()


# -- push units ---------------------------------------------------------------

def test_decode_push_self_naming_roundtrip():
    ids, keys, entries = _chain(3)
    data = KF.encode_chain(4, keys, entries)
    digest, keys2, per_block = KF.decode_push(data, 4)
    assert digest == KF.chain_digest(ids, 4)
    assert keys2 == keys
    np.testing.assert_array_equal(per_block[2][0], entries[2].leaves[0])
    # a tampered payload names a DIFFERENT chain — decode_push still
    # verifies structure, and block-size drift rejects outright
    with pytest.raises(KF.FabricPayloadError):
        KF.decode_push(data, 8)
    with pytest.raises(KF.FabricPayloadError):
        KF.decode_push(b"junk", 4)


# -- engine-level e2e ---------------------------------------------------------

# >= 6 full 16-token blocks under the byte tokenizer, inside the tiny
# model's 128-token window with max_tokens 10 (same budget as PROMPT_A
# in test_kv_fabric.py)
PROMPT = "tiered cache workload preamble " * 3 + "tail one!"
assert 96 <= len(PROMPT) <= 112
GEN = dict(max_tokens=10, greedy=True, chat=False)


def _mk_replica(cls, tmp_path=None, **cfg_kw):
    if tmp_path is not None:
        cfg_kw.setdefault("kv_disk_dir", str(tmp_path / "kvdisk"))
    eng = create_engine(
        "test-llama-tiny",
        engine_cfg=EngineConfig(
            prefix_cache_entries=8, replica_class=cls, **cfg_kw,
        ),
    )
    cont = ContinuousEngine(
        eng, n_slots=2, chunk_steps=4,
        kv_pool_blocks=48, kv_block_size=BS,
    )
    srv = InferenceServer(eng, "127.0.0.1", 0, max_tokens_cap=64,
                          continuous=cont)
    srv.start()
    return eng, cont, srv, f"http://127.0.0.1:{srv.port}"


@pytest.fixture(scope="module")
def ref_engine():
    return create_engine("test-llama-tiny")


def test_disk_warm_admission_bit_identical(tmp_path, ref_engine):
    """THE tier-2 acceptance property: a chain that has been demoted to
    DISK and dropped from the pool re-enters through promotion at
    admission — greedy output bit-identical to the cold run, with the
    prefix actually reused and a disk hit + promotions recorded."""
    ref = ref_engine.generate(PROMPT, **GEN)
    _, cont, srv, _ = _mk_replica("mixed", tmp_path)
    try:
        out = cont.submit(PROMPT, **GEN)
        assert out["status"] == "success"
        assert out["response"] == ref["response"]
        assert cont._shadow.flush(10.0)
        # force the chain out of the pool AND the host tier: clear the
        # block-prefix index, demote host entries to disk
        with cont._shadow._lock:
            for k in list(cont._shadow._entries):
                cont._shadow._evict_subtree_locked(k)
            cont._shadow._note_tiers_locked()
        assert cont._shadow.stats()["disk_blocks"] >= 2
        cont._bpx.evict(10**9)
        out2 = cont.submit(PROMPT, **GEN)
        assert out2["status"] == "success"
        assert out2["response"] == ref["response"]
        assert out2.get("kv_promoted_blocks", 0) >= 2
        assert out2.get("prefix_cached_tokens", 0) >= 2 * BS
        s = cont._shadow.stats()
        assert s["disk_hits"] >= 2 and s["promoted"] >= 2
    finally:
        srv.shutdown()


def test_crash_restart_restores_from_disk_tier(tmp_path, ref_engine):
    """Chaos-shaped: the first replica dies (no drain, no save()); a
    NEW replica over the same --kv-disk-dir rescans tier 2 at startup
    and serves the prompt warm — bit-identical, prefix reused."""
    ref = ref_engine.generate(PROMPT, **GEN)
    _, cont_a, srv_a, _ = _mk_replica("mixed", tmp_path)
    out = cont_a.submit(PROMPT, **GEN)
    assert out["status"] == "success"
    assert cont_a._shadow.flush(10.0)
    # demote everything to disk (the LRU would do this under pressure;
    # forcing it keeps the test deterministic), then crash: no save()
    with cont_a._shadow._lock:
        for k in list(cont_a._shadow._entries):
            cont_a._shadow._evict_subtree_locked(k)
    assert cont_a._shadow.stats()["disk_blocks"] >= 2
    srv_a.shutdown()

    _, cont_b, srv_b, _ = _mk_replica("mixed", tmp_path)
    try:
        assert cont_b._shadow.stats()["disk_blocks"] >= 2
        out2 = cont_b.submit(PROMPT, **GEN)
        assert out2["status"] == "success"
        assert out2["response"] == ref["response"]
        assert out2.get("kv_promoted_blocks", 0) >= 2
    finally:
        srv_b.shutdown()


def test_streamed_pull_bit_identical_and_accounted(tmp_path, ref_engine):
    """A streamed fabric pull (the default) is bit-identical to cold,
    imports the chain, and labels its bytes with the serving tier."""
    ref = ref_engine.generate(PROMPT, **GEN)
    _, cont_a, srv_a, url_a = _mk_replica("prefill", tmp_path)
    out = cont_a.submit(PROMPT, **GEN)
    assert out["status"] == "success" and out["kv_digests"]
    assert cont_a._shadow.flush(10.0)
    _, cont_b, srv_b, _ = _mk_replica("decode")
    try:
        got = cont_b.submit(
            PROMPT, **GEN,
            kv_hint={"peer": url_a, "digest": out["kv_digests"][-1]},
        )
        assert got["status"] == "success"
        assert got["response"] == ref["response"]
        assert got.get("kv_fabric_blocks", 0) >= 2
        st = cont_b.stats()["kv_fabric"]
        assert (st["hits"], st["misses"]) == (1, 0)
        assert st["bytes"] > 0
        # flight recorder: the fetch event carries tier + streamed
        ev = [
            e for e in cont_b.engine.flight.events()
            if e.get("kind") == "fabric_fetch"
        ]
        assert ev and ev[-1]["streamed"] is True
        assert ev[-1]["tier"] in ("host", "disk")
    finally:
        srv_a.shutdown()
        srv_b.shutdown()


def test_streamed_pull_from_disk_tier_bit_identical(tmp_path, ref_engine):
    """The deepest wire path: the HOLDER's chain lives on disk; the
    streamed serve promotes it, labels X-KV-Tier: disk, and the fetcher
    still lands a bit-identical warm admission."""
    ref = ref_engine.generate(PROMPT, **GEN)
    _, cont_a, srv_a, url_a = _mk_replica("prefill", tmp_path)
    out = cont_a.submit(PROMPT, **GEN)
    assert out["status"] == "success" and out["kv_digests"]
    assert cont_a._shadow.flush(10.0)
    with cont_a._shadow._lock:
        for k in list(cont_a._shadow._entries):
            cont_a._shadow._evict_subtree_locked(k)
    assert cont_a._shadow.digest_tier(out["kv_digests"][-1]) == "disk"
    _, cont_b, srv_b, _ = _mk_replica("decode")
    try:
        got = cont_b.submit(
            PROMPT, **GEN,
            kv_hint={"peer": url_a, "digest": out["kv_digests"][-1]},
        )
        assert got["status"] == "success"
        assert got["response"] == ref["response"]
        assert got.get("kv_fabric_blocks", 0) >= 2
        ev = [
            e for e in cont_b.engine.flight.events()
            if e.get("kind") == "fabric_fetch"
        ]
        assert ev and ev[-1]["tier"] == "disk"
    finally:
        srv_a.shutdown()
        srv_b.shutdown()


def test_push_roundtrip_over_http(tmp_path, ref_engine):
    """POST /kv (phase 1.5): the holder pushes its chain at the decode
    replica; the pushed chain is host-resident there, the decode
    admission PROMOTES it with no pull, and output is bit-identical."""
    ref = ref_engine.generate(PROMPT, **GEN)
    _, cont_a, srv_a, _ = _mk_replica("prefill", tmp_path)
    _, cont_b, srv_b, url_b = _mk_replica("decode")
    try:
        out = cont_a.submit(PROMPT, **GEN, prefill_only=True,
                            kv_push_to=url_b)
        assert out["status"] == "success"
        assert out.get("kv_pushed", 0) >= 2
        assert cont_a.stats()["kv_fabric"]["pushes"] == 1
        # the pushed chain is resident at B before any phase-2 traffic
        assert out["kv_digests"][-1] in cont_b.fabric_digests()
        got = cont_b.submit(PROMPT, **GEN)  # no hint needed: it's local
        assert got["status"] == "success"
        assert got["response"] == ref["response"]
        assert got.get("kv_promoted_blocks", 0) >= 2
        assert cont_b.stats()["kv_fabric"]["fetches"] == 0  # no pull
    finally:
        srv_a.shutdown()
        srv_b.shutdown()


def test_push_garbage_rejected_over_http(tmp_path):
    _, cont, srv, url = _mk_replica("decode")
    try:
        req = urllib.request.Request(
            url + "/kv", data=b"not a chain", method="POST",
            headers={"Content-Type": "application/octet-stream"},
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400
        assert cont._shadow.stats()["blocks"] == 0
    finally:
        srv.shutdown()


def test_health_residency_bounded(tmp_path):
    """Satellite: /health's resident_digests is capped
    (--kv-health-digests), MRU-first, however deep the tiers grow."""
    _, cont, srv, url = _mk_replica("mixed", tmp_path,
                                    kv_health_digests=3)
    try:
        out = cont.submit(PROMPT, **GEN)
        assert out["status"] == "success"
        assert cont._shadow.flush(10.0)
        assert len(cont._shadow.resident_digests()) > 3
        with urllib.request.urlopen(f"{url}/health", timeout=10) as r:
            h = json.loads(r.read())
        ds = h["kv"]["resident_digests"]
        assert len(ds) == 3
        # MRU-first: the cap keeps the NEWEST chain tip (which includes
        # generated tokens past the prompt) and its nearest ancestors —
        # the prompt chain's deepest digest makes the cut
        assert out["kv_digests"][-1] in ds
    finally:
        srv.shutdown()


# -- router units -------------------------------------------------------------

def _stub_router(n=2, **kw):
    kw.setdefault("probe_interval_s", 3600.0)
    reps = [
        Replica(f"r{i}", f"http://127.0.0.1:{9100 + i}") for i in range(n)
    ]
    return Router(reps, **kw), reps


def test_multi_holder_residency_spreads_by_load():
    router, (r0, r1) = _stub_router()
    router.record_residency(["d1"], "r0", token_digest="t0")
    router.record_residency(["d1"], "r1", token_digest="t0")
    with router._res_lock:
        holders, tok = router._residency["d1"]
    assert holders == ("r1", "r0") and tok == "t0"
    # seed a deep digest match via the real digest machinery
    digests = chunk_digests("y" * 256, router.affinity_chunk, 32)
    router.record_residency(digests, "r0")
    router.record_residency(digests, "r1")
    rep, _ = router.pick("y" * 256)
    assert rep.rid == "r1"  # MRU on equal load
    r1.outstanding = 5
    rep, _ = router.pick("y" * 256)
    assert rep.rid == "r0"  # load spreads the hot prefix
    # purge strips ONE holder, keeps the co-holder serving
    router.purge_residency("r1")
    rep, _ = router.pick("y" * 256)
    assert rep.rid == "r0"
    router.purge_residency("r0")
    with router._res_lock:
        assert not router._residency


def test_kv_hint_prefers_least_loaded_ready_holder():
    router, (r0, r1, r2) = _stub_router(3)
    digests = chunk_digests("z" * 256, router.affinity_chunk, 32)
    router.record_residency(digests, "r0", token_digest="feed01")
    router.record_residency(digests, "r1", token_digest="feed01")
    r1.outstanding = 7
    hint = router._kv_hint(digests, r2)
    assert hint == {
        "X-KV-Transfer-Peer": r0.url, "X-KV-Transfer-Digest": "feed01",
    }
    # a holder never hints at itself
    assert router._kv_hint(digests, r0) is None
    assert router._kv_hint(digests, r1) is None


def test_bootstrap_appends_behind_live_holders():
    router, _ = _stub_router()
    router.record_kv_residency(["t1"], "r0")
    router.record_kv_residency(["t1", "t2"], "r1", bootstrap=True)
    with router._res_lock:
        assert router._kv_residency["t1"] == ("r0", "r1")
        assert router._kv_residency["t2"] == ("r1",)
    # live traffic MRU-fronts; bootstrap never reorders
    router.record_kv_residency(["t1"], "r1")
    router.record_kv_residency(["t1"], "r0", bootstrap=True)
    with router._res_lock:
        assert router._kv_residency["t1"] == ("r1", "r0")


def test_holders_capped():
    router, _ = _stub_router(6)
    for i in range(6):
        router.record_residency(["d"], f"r{i}", token_digest="t")
        router.record_kv_residency(["t"], f"r{i}")
    with router._res_lock:
        assert router._residency["d"][0] == ("r5", "r4", "r3", "r2")
        assert router._kv_residency["t"] == ("r5", "r4", "r3", "r2")
