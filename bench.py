#!/usr/bin/env python
"""Headline benchmark: TinyLlama-1.1B autoregressive decode throughput.

Apples-to-apples with the reference's own observed number on the same
model (`TinyLlama/TinyLlama-1.1B-Chat-v1.0`): ~0.12-0.2 tokens/sec end to
end across 3 Colab CPU VMs with no KV cache and 4 JSON-over-WAN activation
transfers per token (/root/reference/Test.py:61, orchestration.py:202).
Baseline pinned at the midpoint, 0.16 tok/s.

Here the same architecture runs as one jit-compiled program on one TPU
chip: bf16 params in HBM, prefill in a single call, decode as an on-device
while-loop with a donated KV cache. Weights are random-init (zero network
egress; throughput is weight-value independent).

Robustness contract (this script must ALWAYS land one JSON line):
  * The TPU backend is probed in a SUBPROCESS with a hard timeout and
    bounded retries + backoff, so a wedged backend init (observed in round
    1: `UNAVAILABLE: TPU backend setup/compile error`, and a hang in the
    judge's env) can neither crash nor hang this process.
  * If the TPU never comes up, the benchmark re-executes itself on the CPU
    backend so a platform="cpu" number lands instead of a traceback.
  * If even that fails, a diagnostic JSON line with "error" and
    platform="none" is printed and the exit code is 0.
  * A watchdog thread hard-exits with a diagnostic line if the whole run
    exceeds its wall-clock budget.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "mfu": N,
   ...extras}
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

REFERENCE_TOK_S = 0.16  # midpoint of the reference's 0.12-0.2 tok/s
PROMPT_LEN = 128
DECODE_STEPS = 64
# skip the optional batch-8 leg when the single-stream part (compiles
# included) has already used this much wall clock
BATCH_LEG_DEADLINE_S = 420.0
# hard ceiling on the whole script; the watchdog prints a diagnostic JSON
# line and exits 0 when it trips
WATCHDOG_S = 1500.0
PROBE_TIMEOUT_S = 120.0
# keep re-probing the TPU while/after the CPU fallback runs: a tunnel that
# recovers mid-run still gets a TPU number (round-2 review #2 — the old
# flow gave up on TPU in the first ~8 minutes)
PROBE_INTERVAL_S = 60.0
MIN_TPU_LEG_S = 240.0  # smallest budget worth starting a TPU child with
T_START = time.perf_counter()

# Peak dense bf16 FLOP/s and HBM bandwidth (bytes/s) per chip, keyed by
# substring of device_kind. Used for the MFU / bandwidth-utilization
# estimates; unknown kinds report null. Batch-1 decode is HBM-bound (every
# step streams all params from HBM once), so `hbm_util` is the roofline
# that actually judges single-stream speed; MFU judges the batched leg.
_PEAK = [
    ("v5 lite", 197e12, 819e9),  # v5e
    ("v5e", 197e12, 819e9),
    ("v5p", 459e12, 2765e9),
    ("v6 lite", 918e12, 1640e9),  # trillium
    ("v6e", 918e12, 1640e9),
    ("v4", 275e12, 1228e9),
    ("v3", 123e12, 900e9),
    ("v2", 46e12, 700e9),
]


def _emit(obj):
    sys.stdout.write(json.dumps(obj) + "\n")
    sys.stdout.flush()


# Best result computed so far (the primary metric lands here before the
# optional legs run); the watchdog emits it instead of a failure line.
_PARTIAL = {"result": None}


def _write_sidecar(result):
    """Persist the current best result to the sidecar file (atomic rename)
    so the PARENT can still recover it when this child dies without
    flushing a line — SIGKILL from the parent's subprocess timeout, a
    tunnel wedge the watchdog can't preempt, an OOM. Never fatal."""
    path = os.environ.get("_BENCH_SIDECAR")
    if not path:
        return
    try:
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(result, f)
        os.replace(tmp, path)
    except Exception:  # noqa: BLE001 - sidecar is best-effort only
        pass


def _fail_line(error, platform="none", **extra):
    out = {
        "metric": "tinyllama_1.1b_decode_throughput",
        "value": 0.0,
        "unit": "tokens/sec",
        "vs_baseline": 0.0,
        "platform": platform,
        "error": str(error)[-2000:],
    }
    out.update(extra)
    _emit(out)


_PROBE_SRC = """
import json, os, sys
import jax
# the axon site package PINS jax_platforms at interpreter start, which
# overrides the JAX_PLATFORMS env var — a pre-backend-init config update
# is the only thing that wins (same workaround as tests/conftest.py);
# without it the CPU-fallback probe still touches the wedged tunnel
if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
    jax.config.update("jax_platforms", "cpu")
d = jax.devices()[0]
x = jax.numpy.ones((8, 8))
jax.block_until_ready(x @ x)
print(json.dumps({"platform": d.platform, "device_kind": d.device_kind}))
"""


# 1F1B microbatched-pipeline leg: runs in its own subprocess on a
# 2-virtual-CPU-device mesh (see the call site for why). Prints one JSON
# line with the aggregate decode throughput of a 4-row fleet riding the
# zero-bubble schedule (2 stages x 2 microbatches chasing each other
# around the ppermute ring — parallel/schedule.py).
_MB_LEG_SRC = """
import json, os, time
import jax
if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from distributed_llm_inference_tpu import MeshConfig, get_model_config
from distributed_llm_inference_tpu.engine import generate as G
from distributed_llm_inference_tpu.runtime import create_backend

cfg = get_model_config("test-llama-tiny", dtype="float32", eos_token_id=-1)
cfg, be = create_backend(cfg, mesh_cfg=MeshConfig(pp=2), microbatches=2)
B, PLEN, BUCKET, STEPS = 4, 24, 32, 16
row = [cfg.bos_token_id] + [7] * (PLEN - 1) + [cfg.pad_token_id] * (BUCKET - PLEN)
tokens = jnp.asarray([row] * B, jnp.int32)
plen = jnp.int32(PLEN)
sampling = G.default_sampling(greedy=True)
kp, kd = jax.random.split(jax.random.PRNGKey(0))
limit = jnp.int32(STEPS)

cache = be.init_cache(B, 128)
first, _, cache = be.prefill(tokens, plen, cache, kp, sampling)
out, n_gen, cache = be.decode(
    first, cache, plen, limit, kd, sampling, max_steps=STEPS
)
np.asarray(n_gen)  # warm/compile + drain

def rep():
    global cache
    t0 = time.perf_counter()
    _, n, cache = be.decode(
        first, cache, plen, limit, kd, sampling, max_steps=STEPS
    )
    np.asarray(n)
    return time.perf_counter() - t0

t = min(rep() for _ in range(3))
print(json.dumps({
    "tokens_per_sec": round(B * STEPS / t, 3), "batch": B, "steps": STEPS,
    "pp": 2, "microbatches": 2, "model": cfg.name,
}))
"""


# comms-contract cross-check leg (analysis/comms.py): run real pp=2
# prefill + decode launches with the wire knob off and on, read the
# dli_pp_wire_bytes_total per-path deltas a MetricsRegistry actually
# accumulated, and recompute the same launches through the symbolic link
# table. The two MUST agree to the byte — the runtime accounting routes
# through the table (parallel/pipeline.py _account_link), so a mismatch
# means the static model lies about what the wire carries.
_COMMS_LEG_SRC = """
import json, os
import jax
if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
    jax.config.update("jax_platforms", "cpu")
if not hasattr(jax, "shard_map"):
    print(json.dumps({"skipped": "no jax.shard_map in this jax"}))
    raise SystemExit(0)
import jax.numpy as jnp
import numpy as np
from distributed_llm_inference_tpu import MeshConfig, get_model_config
from distributed_llm_inference_tpu.analysis import comms
from distributed_llm_inference_tpu.engine import generate as G
from distributed_llm_inference_tpu.runtime import create_backend
from distributed_llm_inference_tpu.utils.metrics import MetricsRegistry

B, PLEN, BUCKET, STEPS = 2, 24, 32, 8
out = {"modes": {}, "exact_agreement": True, "pp": 2,
       "model": "test-llama-tiny"}
for mode, wq in (("off", None), ("on", "int8")):
    cfg = get_model_config(
        "test-llama-tiny", dtype="float32", eos_token_id=-1
    )
    cfg, be = create_backend(
        cfg, mesh_cfg=MeshConfig(pp=2), wire_quant=wq
    )
    reg = MetricsRegistry()
    be.attach_wire_metrics(reg)
    row = ([cfg.bos_token_id] + [7] * (PLEN - 1)
           + [cfg.pad_token_id] * (BUCKET - PLEN))
    tokens = jnp.asarray([row] * B, jnp.int32)
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(0))
    cache = be.init_cache(B, 128)
    first, _, cache = be.prefill(
        tokens, jnp.int32(PLEN), cache, kp, sampling
    )
    _, n_gen, cache = be.decode(
        first, cache, jnp.int32(PLEN), jnp.int32(STEPS), kd, sampling,
        max_steps=STEPS,
    )
    np.asarray(n_gen)
    fam = reg.get("dli_pp_wire_bytes_total")
    measured = {
        path: int(fam.labels(path=path).value)
        for path in ("microstep", "broadcast")
    }
    q = wq is not None
    p = comms.params_from_config(
        cfg, dp=1, pp=2, rows=B, t=BUCKET, steps=STEPS
    )
    derived = {
        "microstep":
            comms.link_bytes("pp-microstep-prefill", p, itemsize=4, quant=q)
            + comms.link_bytes("pp-microstep-decode", p, itemsize=4, quant=q),
        "broadcast":
            comms.link_bytes("pp-broadcast-prefill", p, itemsize=4, quant=q)
            + comms.link_bytes("pp-broadcast-decode", p, itemsize=4, quant=q),
    }
    agree = measured == derived
    out["modes"][mode] = {
        "measured": measured, "derived": derived, "agree": agree,
    }
    out["exact_agreement"] = out["exact_agreement"] and agree
assert out["exact_agreement"], out
print(json.dumps(out))
"""


# MPMD stage-pipeline leg (serving/stage_runtime.py): a REAL 2-process
# stage fleet — each stage a subprocess owning a contiguous layer slice,
# activations over the HTTP stage transport — driven against the
# single-process forward loop on the same seed-0 weights. Headlines:
# TTFT/TPOT p99 per topology (the cross-process hop tax on a CPU proxy;
# on TPU the transport is device-to-device and the tax is ICI-bound),
# bit-identity of the transcripts, and the fault-containment numbers the
# chaos suite asserts but never times: kill -9 the last stage mid-decode
# and measure time-to-recover (faulted wall minus clean wall) plus
# tokens recomputed, warm (block shadow restored) vs cold (shadow
# wiped). Runs in its own subprocess like the 1f1b leg so the stage
# fleet's env never perturbs this process's measurements.
_MPMD_LEG_SRC = """
import json, os, shutil, tempfile, time
import jax
if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from distributed_llm_inference_tpu.models import api as M
from distributed_llm_inference_tpu.models.registry import get_model_config
from distributed_llm_inference_tpu.serving.stage_runtime import (
    HttpStageTransport, MPMDPipeline, StageSupervisor, free_port,
)
from distributed_llm_inference_tpu.utils.tokenizer import ByteTokenizer

MODEL, BLOCK, STAGES, N_NEW, KILL_AFTER = "test-llama-tiny", 8, 2, 16, 6
PROMPTS = ["mpmd bench prompt %d!" % i for i in range(2)]
REC_PROMPT = "mpmd recovery probe"

def p99(xs):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(0.99 * len(xs)))]

stage_env = dict(os.environ, JAX_PLATFORMS="cpu")
stage_env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
stage_env.pop("DLI_FAULTS", None)
restore = tempfile.mkdtemp(prefix="bench_mpmd_")
sup = StageSupervisor(
    MODEL, STAGES, [free_port() for _ in range(STAGES)], seed=0,
    block_size=BLOCK, restore_dir=restore, restart_budget=100,
    env=stage_env,
)
pipe = MPMDPipeline(sup, transport=HttpStageTransport())
out = {"stages": STAGES, "model": MODEL, "block_size": BLOCK}
try:
    t0 = time.perf_counter()
    pipe.start_fleet(ready_timeout_s=180)
    out["fleet_spawn_s"] = round(time.perf_counter() - t0, 2)
    pipe.generate(PROMPTS[0], 4)  # compile every stage's programs

    ttfts, itls, pipe_texts = [], [], []
    for p in PROMPTS:
        t0 = time.perf_counter()
        rid = pipe.start(p)
        ttfts.append(time.perf_counter() - t0)
        for _ in range(N_NEW - 1):
            t1 = time.perf_counter()
            if pipe.step_once(rid) is None:
                break
            itls.append(time.perf_counter() - t1)
        pipe_texts.append(pipe.finish(rid)["tokens"])
    out["pipeline"] = {
        "ttft_p99_s": round(p99(ttfts), 4),
        "tpot_p99_s": round(p99(itls), 5),
        "tokens_per_sec": round(len(itls) / sum(itls), 2),
    }

    # single-process baseline: same model, same seed-0 weights, the plain
    # forward loop the chaos tests use as their bit-identity reference
    cfg = get_model_config(MODEL)
    tok = ByteTokenizer()
    params = M.init_params(cfg, jax.random.PRNGKey(0))

    def solo(prompt):
        ids = tok.encode(prompt)
        cache = M.init_kv_cache(cfg, 1, cfg.max_seq_len, cfg.n_layers)
        t0 = time.perf_counter()
        logits, cache = M.forward(
            cfg, params, jnp.asarray([ids], jnp.int32), cache, 0
        )
        t = int(jnp.argmax(logits[0, -1]))
        ttft = time.perf_counter() - t0
        toks, pos, itl = [t], len(ids), []
        for _ in range(N_NEW - 1):
            if t == tok.eos_token_id:
                break
            t1 = time.perf_counter()
            logits, cache = M.forward(
                cfg, params, jnp.asarray([[t]], jnp.int32), cache, pos
            )
            t = int(jnp.argmax(logits[0, -1]))
            itl.append(time.perf_counter() - t1)
            toks.append(t)
            pos += 1
        if toks and toks[-1] == tok.eos_token_id:
            toks = toks[:-1]
        return ttft, itl, toks

    solo(PROMPTS[0])  # compile
    s_ttfts, s_itls, solo_texts = [], [], []
    for p in PROMPTS:
        a, b, c = solo(p)
        s_ttfts.append(a)
        s_itls.extend(b)
        solo_texts.append(c)
    out["single_process"] = {
        "ttft_p99_s": round(p99(s_ttfts), 4),
        "tpot_p99_s": round(p99(s_itls), 5),
        "tokens_per_sec": round(len(s_itls) / sum(s_itls), 2),
    }
    out["bit_identical_vs_single_process"] = pipe_texts == solo_texts
    out["pipeline_tpot_overhead"] = round(
        out["pipeline"]["tpot_p99_s"] / out["single_process"]["tpot_p99_s"],
        2,
    )

    # fault containment, timed: kill -9 the last stage mid-decode.
    # time_to_recover = faulted wall minus the clean wall of the
    # IDENTICAL request, so the number isolates salvage (respawn +
    # restore + replay); tokens_recomputed comes off last_salvage().
    def request(kill=False, wipe=False):
        t0 = time.perf_counter()
        rid = pipe.start(REC_PROMPT)
        for step in range(N_NEW - 1):
            if kill and step == KILL_AFTER:
                victim = STAGES - 1
                sup.proc(victim).kill()
                sup.proc(victim).wait(timeout=10)
                if wipe:
                    shutil.rmtree(
                        os.path.join(restore, "stage%d" % victim),
                        ignore_errors=True,
                    )
            if pipe.step_once(rid) is None:
                break
        toks = pipe.finish(rid)["tokens"]
        return time.perf_counter() - t0, toks, rid

    clean_s, clean_toks, _ = request()
    rec = {"clean_request_s": round(clean_s, 3)}
    for mode, wipe in (("warm", False), ("cold", True)):
        wall, toks, rid = request(kill=True, wipe=wipe)
        sal = pipe.last_salvage()
        rec[mode] = {
            "ok": toks == clean_toks and sal["stage"] == STAGES - 1,
            "time_to_recover_s": round(max(0.0, wall - clean_s), 3),
            "tokens_recomputed": sal["tokens_recomputed"].get(rid),
            "salvage_s": round(sal["secs"], 3),
        }
    # the recovery CLAIM on the CPU proxy is tokens_recomputed (warm
    # replays only the partial tail block, cold the whole fed prefix):
    # per-step wall here is jit-dispatch + HTTP-hop bound (~1 s), so the
    # faulted-minus-clean wall delta is noise-bounded and the wall
    # speedup is only reported when both deltas actually resolved
    if rec["warm"]["tokens_recomputed"]:
        rec["cold_vs_warm_recompute"] = round(
            rec["cold"]["tokens_recomputed"]
            / rec["warm"]["tokens_recomputed"], 1,
        )
    if (rec["warm"]["time_to_recover_s"] > 0.3
            and rec["cold"]["time_to_recover_s"] > 0.3):
        rec["warm_recovery_speedup"] = round(
            rec["cold"]["time_to_recover_s"]
            / rec["warm"]["time_to_recover_s"], 2,
        )
    out["recovery"] = rec
finally:
    pipe.shutdown()
    shutil.rmtree(restore, ignore_errors=True)
print(json.dumps(out))
"""


def _prev_cpu_value():
    """Newest committed BENCH_r*.json CPU headline: the value itself on a
    platform=cpu round, or the recorded cpu_fallback field on a TPU round.
    Returns {"value", "source"} or None."""
    import glob

    here = os.path.dirname(os.path.abspath(__file__))
    for path in sorted(glob.glob(os.path.join(here, "BENCH_r*.json")),
                       reverse=True):
        try:
            with open(path, encoding="utf-8") as f:
                prev = json.load(f)
        except Exception:  # noqa: BLE001 - unreadable artifact: skip
            continue
        # the driver wraps the emitted line: {"n", "cmd", "rc", "tail",
        # "parsed"} — the metrics live under "parsed"
        if "parsed" in prev and isinstance(prev["parsed"], dict):
            prev = prev["parsed"]
        name = os.path.basename(path)
        if prev.get("platform") == "cpu" and prev.get("value"):
            return {"value": prev["value"], "source": name}
        if prev.get("cpu_fallback_tokens_per_sec"):
            return {
                "value": prev["cpu_fallback_tokens_per_sec"], "source": name
            }
    return None


def _probe_backend(env, timeout_s):
    """Touch the backend in a subprocess. Returns (ok, info_or_error)."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
        )
    except subprocess.TimeoutExpired:
        return False, f"backend probe timed out after {timeout_s:.0f}s"
    if proc.returncode != 0:
        return False, (proc.stderr or proc.stdout or "").strip()[-800:]
    try:
        return True, json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as e:  # noqa: BLE001 - diagnostic path
        return False, f"probe emitted unparseable output: {e}"


def _timed(fn):
    t0 = time.perf_counter()
    out = fn()
    return time.perf_counter() - t0, out


def run_benchmark():
    import jax

    if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
        # see _PROBE_SRC: the axon site pin overrides the env var
        jax.config.update("jax_platforms", "cpu")
    elif os.environ.get("BENCH_COMPILE_CACHE") != "off":
        # Persistent XLA compile cache, TPU leg only: a recovered-tunnel
        # run spends its budget measuring, not recompiling. NOT used for
        # the CPU fallback — XLA:CPU AOT entries bake in host machine
        # features and reload with SIGILL-risk warnings on a feature
        # mismatch. Failure to set it must never cost the run.
        try:
            cache_dir = os.environ.get(
                "BENCH_COMPILE_CACHE",
                os.path.join(
                    os.path.dirname(os.path.abspath(__file__)), ".xla_cache"
                ),
            )
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        except Exception:  # noqa: BLE001 - cache is an optimization only
            pass
    import jax.numpy as jnp
    import numpy as np

    from distributed_llm_inference_tpu.engine import generate as G
    from distributed_llm_inference_tpu.models import api as M
    from distributed_llm_inference_tpu.models.registry import get_model_config

    dev = jax.devices()[0]
    platform = dev.platform
    on_tpu = platform == "tpu"
    # CPU fallback (TPU unreachable): shrink the workload so a number
    # lands within the watchdog budget — a 1.1B fp32 model on one host
    # core decodes ~1 tok/s; the TPU-sized 12x64-step timing grid would
    # blow the budget and land a failure line instead of a measurement.
    decode_steps = DECODE_STEPS if on_tpu else 8
    n_chain = 4 if on_tpu else 1
    n_reps = 3 if on_tpu else 1
    # eos_token_id=-1: no token id can match, so the decode loop never
    # early-exits — every run measures exactly decode_steps steps.
    cfg = get_model_config(
        "tinyllama-1.1b",
        dtype="bfloat16" if on_tpu else "float32",
        eos_token_id=-1,
    )
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    n_params = int(
        sum(x.size for x in jax.tree_util.tree_leaves(params))
    )

    tokens = jnp.asarray(
        [[cfg.bos_token_id] + [7] * (PROMPT_LEN - 1)], jnp.int32
    )
    plen = jnp.int32(PROMPT_LEN)
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(1))
    limit = jnp.int32(decode_steps)

    # Under the axon TPU tunnel, jax.block_until_ready returns immediately;
    # only a device->host fetch waits for the compute queue. The fetch has a
    # fixed tunnel round-trip (~70 ms), so: time K back-to-back device calls
    # ending in one scalar fetch, subtract the separately-measured RTT, and
    # divide by K. (On a local backend RTT measures ~0 and this is exact.)
    def fetch(x):
        return np.asarray(x)

    trivial = jax.jit(lambda x: x + 1)
    fetch(trivial(jnp.float32(0)))  # warm
    rtt = min(
        _timed(lambda: fetch(trivial(jnp.float32(i))))[0] for i in range(5)
    )

    # warm-up: compile prefill + decode, drain the queue
    cache = M.init_kv_cache(cfg, 1, max_seq=512)
    first, _, cache = G.prefill(cfg, params, tokens, plen, cache, kp, sampling)
    out, n_gen, cache = G.decode(
        cfg, params, first, cache, plen, limit, kd, sampling,
        max_steps=decode_steps,
    )
    fetch(n_gen)

    # TTFT: K back-to-back prefills (each re-initing its cache) ending in
    # ONE scalar fetch, divided by K — chaining amortizes the tunnel RTT
    # to 1/K instead of subtracting it raw, which on a ~70 ms-RTT tunnel
    # swallowed the ~9 ms prefill entirely and reported ttft_s: 0.0.
    KP = 4 if on_tpu else 1

    def prefill_chain():
        f = None
        for _ in range(KP):
            c = M.init_kv_cache(cfg, 1, max_seq=512)
            f, _, c = G.prefill(cfg, params, tokens, plen, c, kp, sampling)
        fetch(f)

    prefill_chain()  # warm (compile already done above; drain queue)
    ttft = max(
        (min(_timed(prefill_chain)[0] for _ in range(3)) - rtt) / KP, 0.0
    )
    # prefill is the COMPUTE-bound phase (decode is HBM-bound): its MFU
    # judges how well the big batched matmuls land on the MXU
    prefill_tok_s = PROMPT_LEN / ttft if ttft > 0 else None

    # decode throughput: K chained decode calls (donated cache threaded
    # through), one scalar fetch at the end. One timing helper serves the
    # baseline, batch, and int8 legs so the discipline (rep count, RTT
    # subtraction) can never drift between them.
    K = n_chain

    def time_decode(p, first_tok, c):
        def run():
            nonlocal c
            for _ in range(K):
                _, n_gen, c = G.decode(
                    cfg, p, first_tok, c, plen, limit, kd, sampling,
                    max_steps=decode_steps,
                )
            fetch(n_gen)

        per_call = max(
            min(_timed(run)[0] for _ in range(n_reps)) - rtt, 1e-9
        ) / K
        return decode_steps / per_call, c

    tok_s, cache = time_decode(params, first, cache)

    # MFU: dense-decode FLOPs are ~2*params per token; judged against the
    # chip's peak bf16 FLOP/s. Decode is HBM-bandwidth-bound, so low single
    # digits is the expected healthy range for batch 1 — hbm_util (bytes
    # streamed per token ≈ 2*params bf16, vs peak HBM bandwidth) is the
    # roofline batch-1 decode is actually racing.
    peak = peak_bw = None
    kind = dev.device_kind.lower()
    if on_tpu:
        for sub, flops, bw in _PEAK:
            if sub in kind:
                peak, peak_bw = flops, bw
                break
    bytes_per_param = 2 if cfg.dtype == "bfloat16" else 4
    mfu = (2.0 * n_params * tok_s / peak) if peak else None
    hbm_util = (
        bytes_per_param * n_params * tok_s / peak_bw if peak_bw else None
    )

    # The PRIMARY result exists from this point on: _PARTIAL hands it to
    # the watchdog, so a later optional leg hanging (e.g. a pathological
    # remote kernel compile) degrades to a result without that leg
    # instead of a failure line.
    result = {
        "metric": "tinyllama_1.1b_decode_throughput",
        "value": round(tok_s, 3),
        "unit": "tokens/sec",
        "vs_baseline": round(tok_s / REFERENCE_TOK_S, 1),
        "ttft_s": round(ttft, 4),
        "prompt_len": PROMPT_LEN,
        "decode_steps": decode_steps,
        "platform": platform,
        "device_kind": dev.device_kind,
        "dtype": cfg.dtype,
        "n_params": n_params,
        "mfu": round(mfu, 5) if mfu is not None else None,
        "hbm_util": round(hbm_util, 4) if hbm_util is not None else None,
    }
    if peak and prefill_tok_s:
        result["prefill_mfu"] = round(2.0 * n_params * prefill_tok_s / peak, 4)
    _PARTIAL["result"] = result
    # Land the solo-greedy line THE MOMENT it exists (round-3 review #1):
    # the final emit below re-prints the enriched result and the consumer
    # takes the LAST parseable line, so an optional leg wedging the tunnel
    # afterward costs that leg, never the headline number.
    _emit(result)
    _write_sidecar(result)

    # wire-quant leg (quantized inter-stage transfers, ops/wire_quant.py
    # + EngineConfig.pp_wire_quant): the pp proxy — greedy decode with
    # the pp ring's wire numerics replayed on one device (one int8
    # round trip per stage hand-off + the final-stage broadcast), quant
    # on vs off. Headlines: wire bytes/token per ICI link (STATIC — the
    # quantity the knob shrinks, and what binds deep pipelines on a real
    # slice), the teacher-forced greedy match rate (the quality side of
    # the trade, same gate tests/test_wire_quant.py asserts), and proxy
    # tok/s on vs off. The CPU proxy PAYS the quantize FLOPs and
    # collects none of the ICI-byte win, so the tok/s ratio structurally
    # understates a TPU — the bytes/token reduction is the claim.
    if time.perf_counter() - T_START < BATCH_LEG_DEADLINE_S:
        try:
            from distributed_llm_inference_tpu.ops import wire_quant as _WQ

            w_cfg = get_model_config(
                "test-llama-tiny", dtype="float32", eos_token_id=-1,
                max_seq_len=512,
            )
            w_params = M.init_params(w_cfg, jax.random.PRNGKey(2))
            w_S, w_N = 4, 24
            w_rng = np.random.default_rng(7)
            w_prompts = [
                w_rng.integers(3, w_cfg.vocab_size, size=16).tolist()
                for _ in range(6)
            ]
            w_rates = [
                _WQ.proxy_stage_match(w_cfg, w_params, p, w_N, w_S)
                for p in w_prompts
            ]

            def _wire_tok_s(quant):
                _WQ.proxy_stage_generate(
                    w_cfg, w_params, w_prompts[0], w_N, w_S, quant=quant
                )  # compile
                t0 = time.perf_counter()
                n = 0
                for p in w_prompts[:4]:
                    n += len(_WQ.proxy_stage_generate(
                        w_cfg, w_params, p, w_N, w_S, quant=quant
                    ))
                return n / (time.perf_counter() - t0)

            tok_off = _wire_tok_s(False)
            tok_on = _wire_tok_s(True)
            act = (1, 1, w_cfg.dim)
            hops = w_S + 1  # S ring hops + the masked-psum broadcast
            bpt_off = _WQ.wire_bytes(act, 4, hops, quant=False)
            bpt_on = _WQ.wire_bytes(act, 4, hops, quant=True)
            result["wire_quant"] = {
                "proxy_stages": w_S,
                "model": w_cfg.name,
                "wire_bytes_per_token_off": bpt_off,
                "wire_bytes_per_token_on": bpt_on,
                "wire_bytes_reduction": round(bpt_off / bpt_on, 3),
                "greedy_match_rate_mean": round(
                    float(np.mean(w_rates)), 4
                ),
                "greedy_match_rate_min": round(min(w_rates), 4),
                "proxy_tok_s_off": round(tok_off, 2),
                "proxy_tok_s_on": round(tok_on, 2),
                "proxy_tok_s_ratio": round(tok_on / tok_off, 3),
                "note": (
                    "bytes/token per ICI link, static from shapes; the "
                    "CPU proxy pays the quantize FLOPs and none of the "
                    "ICI win, so tok_s_ratio understates a TPU slice"
                ),
            }
            _write_sidecar(result)
        except Exception:  # noqa: BLE001 - optional leg, never fatal
            import traceback

            traceback.print_exc(file=sys.stderr)


    # batched decode: 8 identical streams through the raw backend decode
    # loop (NOT the engine's generate_batch ragged path — this measures the
    # aggregate-throughput ceiling batching exposes, with no left-pad
    # masking in the program). Weights stream from HBM once per step
    # regardless of batch, so aggregate throughput scales ~linearly until
    # compute-bound. The prefilled B=1 cache is tiled instead of compiling
    # a batched prefill (identical rows; only the decode program costs a
    # compile), and the leg is skipped entirely if the single-stream part
    # already ate the time budget — the primary metric must always land.
    batch_tok_s = None
    if on_tpu and time.perf_counter() - T_START < BATCH_LEG_DEADLINE_S:
        BATCH = 8
        first_b = jnp.tile(first, (BATCH,))
        cache_b = jax.tree.map(
            lambda x: jnp.tile(x, (1, BATCH) + (1,) * (x.ndim - 2)), cache
        )
        out, n_gen_b, cache_b = G.decode(
            cfg, params, first_b, cache_b, plen, limit, kd, sampling,
            max_steps=decode_steps,
        )
        fetch(n_gen_b)  # warm/compile
        per_stream, cache_b = time_decode(params, first_b, cache_b)
        batch_tok_s = BATCH * per_stream
        result["batch8_tokens_per_sec"] = round(batch_tok_s, 3)
        if peak:
            result["batch8_mfu"] = round(2.0 * n_params * batch_tok_s / peak, 5)
        _write_sidecar(result)

    # int8 weight-only leg (ops/quant.py): same decode, half the HBM
    # bytes/token — the lever that moves the bandwidth roofline itself.
    # Skipped under the same wall-clock budget discipline as the batch leg.
    int8_tok_s = None
    if on_tpu and time.perf_counter() - T_START < BATCH_LEG_DEADLINE_S:
        from distributed_llm_inference_tpu.ops.quant import quantize_params

        qparams = quantize_params(cfg, params)
        cache_q = M.init_kv_cache(cfg, 1, max_seq=512)
        first_q, _, cache_q = G.prefill(
            cfg, qparams, tokens, plen, cache_q, kp, sampling
        )
        out, n_gen_q, cache_q = G.decode(
            cfg, qparams, first_q, cache_q, plen, limit, kd, sampling,
            max_steps=decode_steps,
        )
        fetch(n_gen_q)  # warm/compile
        int8_tok_s, cache_q = time_decode(qparams, first_q, cache_q)
        del qparams, cache_q
        result["int8_tokens_per_sec"] = round(int8_tok_s, 3)
        if peak_bw:
            # int8 streams ~1 byte/param (+0.2% scales)
            result["int8_hbm_util"] = round(
                1.0 * n_params * int8_tok_s / peak_bw, 4
            )
        _write_sidecar(result)

    # int4 leg (packed nibbles + Pallas VMEM-unpack kernel): halves the
    # weight bytes again. Fully fenced — compile/kernel failure must
    # never cost the primary metric.
    int4_tok_s = None
    if on_tpu and time.perf_counter() - T_START < BATCH_LEG_DEADLINE_S:
        try:
            from distributed_llm_inference_tpu.ops.quant import (
                quantize_params as _qp,
            )

            q4params = _qp(cfg, params, mode="int4")
            cache_q4 = M.init_kv_cache(cfg, 1, max_seq=512)
            first_q4, _, cache_q4 = G.prefill(
                cfg, q4params, tokens, plen, cache_q4, kp, sampling
            )
            out, n_gen_q4, cache_q4 = G.decode(
                cfg, q4params, first_q4, cache_q4, plen, limit, kd, sampling,
                max_steps=decode_steps,
            )
            fetch(n_gen_q4)  # warm/compile
            int4_tok_s, cache_q4 = time_decode(q4params, first_q4, cache_q4)
            result["int4_tokens_per_sec"] = round(int4_tok_s, 3)
            if peak_bw:
                # int4 streams ~0.5 byte/param (+ per-group scales)
                result["int4_hbm_util"] = round(
                    0.5 * n_params * int4_tok_s / peak_bw, 4
                )
            _write_sidecar(result)
        except Exception:  # noqa: BLE001 - optional leg, never fatal
            import traceback

            traceback.print_exc(file=sys.stderr)

    # flash-attention prefill leg: the Pallas kernel (ops/flash_attention)
    # vs the XLA einsum path at a 1k prompt — prefill is where attention
    # is quadratic, so this is the kernel's case to win (round-2 review
    # weak #3: the kernel existed but nothing measured it; the default
    # stays "xla" unless this leg shows a win). Fully fenced.
    flash_xla_tok_s = flash_pl_tok_s = None
    if on_tpu and time.perf_counter() - T_START < BATCH_LEG_DEADLINE_S:
        try:
            FLASH_LEN = 1024
            long_tokens = jnp.asarray(
                [[cfg.bos_token_id] + [7] * (FLASH_LEN - 1)], jnp.int32
            )
            fplen = jnp.int32(FLASH_LEN)

            def time_prefill(c):
                # K chained prefills, one fetch: RTT amortizes to 1/K
                # (raw subtraction let RTT jitter swallow the ~10 ms
                # prefill and report a physically-impossible tok/s).
                # This leg only runs on-TPU (the `on_tpu` fence above).
                KF = 4

                def run():
                    ff = None
                    for _ in range(KF):
                        cf = M.init_kv_cache(c, 1, max_seq=FLASH_LEN + 8)
                        ff, _, cf = G.prefill(
                            c, params, long_tokens, fplen, cf, kp, sampling
                        )
                    fetch(ff)

                run()  # warm/compile
                t = max(
                    (min(_timed(run)[0] for _ in range(3)) - rtt) / KF, 1e-9
                )
                return FLASH_LEN / t

            flash_xla_tok_s = time_prefill(cfg)
            result["prefill_xla_1k_tok_s"] = round(flash_xla_tok_s, 1)
            _write_sidecar(result)
            flash_pl_tok_s = time_prefill(cfg.replace(attn_impl="pallas"))
            result["prefill_flash_1k_tok_s"] = round(flash_pl_tok_s, 1)
            _write_sidecar(result)
        except Exception:  # noqa: BLE001 - optional leg, never fatal
            import traceback

            traceback.print_exc(file=sys.stderr)

    # fleet-attention leg: the per-row flash decode kernel
    # (ops/paged_attention.flash_attend_slots) vs the XLA einsum over an
    # 8-slot 8k-window fleet cache at position ~1k — the
    # over-provisioned-window case the kernel targets. Driven DIRECTLY
    # (the serving hook always takes the XLA path for T=1 decode, where
    # the einsum measured decisively faster); this leg is the regression
    # baseline future kernel work has to beat. Fully fenced.
    fleet_xla_ms = fleet_pl_ms = None
    if on_tpu and time.perf_counter() - T_START < BATCH_LEG_DEADLINE_S:
        try:
            from distributed_llm_inference_tpu.ops.attention import (
                attend, slot_causal_mask,
            )
            from distributed_llm_inference_tpu.ops.paged_attention import (
                flash_attend_slots,
            )

            FB, FS, FPOS = 8, 8192, 1024
            fk = jax.random.split(jax.random.PRNGKey(5), 3)
            fq = jax.random.normal(
                fk[0], (FB, 1, cfg.n_heads, cfg.head_dim), jnp.bfloat16
            )
            fck = jax.random.normal(
                fk[1], (FB, cfg.n_kv_heads, FS, cfg.head_dim), jnp.bfloat16
            )
            fcv = jax.random.normal(
                fk[2], (FB, cfg.n_kv_heads, FS, cfg.head_dim), jnp.bfloat16
            )
            fpos = jnp.full((FB,), FPOS, jnp.int32)
            fmask = slot_causal_mask(fpos, 1, FS)

            # operands are ARGUMENTS, not closure constants — a nullary
            # jit constant-folds the whole computation into the
            # executable and times nothing but the fetch
            att_x = jax.jit(attend)
            att_p = jax.jit(
                lambda q_, k_, v_, p_: flash_attend_slots(q_, k_, v_, p_)
            )

            def time_attn(fn, *args, n=20):
                fetch(fn(*args))  # warm/compile + drain
                t0 = time.perf_counter()
                for _ in range(n):
                    o = fn(*args)
                fetch(o)
                return max(time.perf_counter() - t0 - rtt, 1e-9) / n * 1e3

            fleet_xla_ms = time_attn(att_x, fq, fck, fcv, fmask)
            result["fleet_attn_xla_ms"] = round(fleet_xla_ms, 3)
            fleet_pl_ms = time_attn(att_p, fq, fck, fcv, fpos)
            result["fleet_attn_flash_ms"] = round(fleet_pl_ms, 3)
            _write_sidecar(result)
            del fck, fcv
        except Exception:  # noqa: BLE001 - optional leg, never fatal
            import traceback

            traceback.print_exc(file=sys.stderr)

    # continuous-batching legs (engine/continuous.py): closed-loop client
    # fleet against the real serving engine — slot recycling, mid-flight
    # admission, lag-1 chunk pipelining — measured THREE ways (round-3
    # review #7: the serving-level features get round-over-round driver
    # numbers): dense fleet, block-paged pool, paged+prefix-reuse.
    # Reported as a nested result["continuous"] block.
    #
    # Round-4 review #2: these legs run on EVERY platform now. On the CPU
    # fallback they ride a scaled-down workload on the CI-tiny model
    # (test-llama-tiny) with strict sub-budgets — absolute numbers are not
    # comparable to the TPU 1.1B legs (the block says which model ran),
    # but round-over-round they give the serving-level features a
    # driver-visible regression direction even with the tunnel dead.
    cont_block = {}
    if time.perf_counter() - T_START < BATCH_LEG_DEADLINE_S:
        try:
            from distributed_llm_inference_tpu.config import EngineConfig
            from distributed_llm_inference_tpu.engine.continuous import (
                ContinuousEngine,
            )
            from distributed_llm_inference_tpu.engine.engine import (
                InferenceEngine,
            )

            if on_tpu:
                c_cfg, c_params = cfg, params
                kw = dict(max_tokens=32, greedy=True, chat=False)
                n_req, n_words, n_clients, n_slots, chunk = 16, 96, 8, 8, 16
                slot_max_seq = 1024
            else:
                # max_seq_len raised over the CI preset's 128: slot
                # capacity clamps to the model window, and the churn
                # prompts byte-tokenize to ~180 tokens
                c_cfg = get_model_config(
                    "test-llama-tiny", dtype="float32", eos_token_id=-1,
                    max_seq_len=512,
                )
                c_params = M.init_params(c_cfg, jax.random.PRNGKey(2))
                kw = dict(max_tokens=16, greedy=True, chat=False)
                n_req, n_words, n_clients, n_slots, chunk = 8, 32, 4, 4, 8
                slot_max_seq = 512
            blocks_per_slot = slot_max_seq // 32
            pool_blocks = n_slots * blocks_per_slot + blocks_per_slot + 1
            cont_block["model"] = c_cfg.name
            cont_block["platform"] = platform
            prompts = [
                " ".join(f"w{i}_{j}" for j in range(n_words))
                for i in range(n_req)
            ]
            # prefix-reuse mix: requests sharing one long prefix, so a
            # warm prefix snapshot serves every admission's prefill tail
            shared = " ".join(f"ctx{j}" for j in range(n_words + 32))
            prefix_prompts = [f"{shared} q{i}" for i in range(n_req)]

            def churn(cont, plist):
                cont.submit(plist[0], **kw)  # warm slot programs
                # warm the prefix-REUSE path too: the second serve of the
                # same prompt compiles the hit-side programs (block-map
                # gather + tail prefill-at-offset) so the timed window
                # measures steady state, same discipline as every other
                # leg's warmup (a no-op extra request when reuse is off)
                cont.submit(plist[0], **kw)
                done_tokens = [0]
                lock = threading.Lock()
                it = iter(plist)

                def client():
                    while True:
                        with lock:
                            p = next(it, None)
                        if p is None:
                            return
                        r = cont.submit(p, **kw)
                        if r.get("status") == "success":
                            with lock:
                                done_tokens[0] += r["tokens_generated"]

                t0 = time.perf_counter()
                threads = [
                    threading.Thread(target=client) for _ in range(8)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                wall = time.perf_counter() - t0
                return (done_tokens[0] / wall) if done_tokens[0] else None

            from distributed_llm_inference_tpu.utils.metrics import (
                latency_summary,
            )

            eng = InferenceEngine(c_cfg, params=c_params)
            # slot_max_seq on every leg: the tiny engine's default slot
            # capacity (128) is smaller than a byte-tokenized 32-word
            # prompt, which made the whole CPU dense leg reject requests
            cont = ContinuousEngine(
                eng, n_slots=n_slots, chunk_steps=chunk,
                slot_max_seq=slot_max_seq,
            )
            try:
                v = churn(cont, prompts)
                if v:
                    cont_block["dense_tokens_per_sec"] = round(v, 3)
                    # registry snapshot of the dense leg: TTFT/TPOT/step
                    # percentiles + occupancy, so BENCH_*.json rounds
                    # carry the stage-level signal, not just tok/s
                    cont_block["metrics"] = latency_summary(eng.metrics)
            finally:
                cont.close()
            _write_sidecar(dict(result, continuous=cont_block))

            # paged pool: same churn, fleet HBM now a function of
            # in-flight tokens (pool), admission backpressure on blocks.
            # slot budget slot_max_seq tokens (byte-tokenized prompts run
            # well under it) in blocks of 32; pool sized one spare
            # slot-class above the fleet. Each leg re-checks the deadline
            # like every other optional leg — the one before it may have
            # eaten the budget, and the watchdog must never be what ends
            # this section.
            if time.perf_counter() - T_START < BATCH_LEG_DEADLINE_S:
                cont = ContinuousEngine(
                    eng, n_slots=n_slots, chunk_steps=chunk,
                    slot_max_seq=slot_max_seq,
                    kv_pool_blocks=pool_blocks, kv_block_size=32,
                )
                try:
                    v = churn(cont, prompts)
                    if v:
                        cont_block["paged_tokens_per_sec"] = round(v, 3)
                        cont_block["paged"] = cont.stats().get("paged")
                finally:
                    cont.close()
                _write_sidecar(dict(result, continuous=cont_block))

            # paged + prefix reuse over the BUCKETED fallback
            # (ragged_prefill=False): admissions after the first MAP the
            # shared-prefix blocks straight into their tables (refcounted
            # block sharing, engine/block_prefix.py) and prefill only the
            # tail through the scratch gather + bucket ladder + insert
            # scatter — the baseline the ragged leg below is measured
            # against
            if time.perf_counter() - T_START < BATCH_LEG_DEADLINE_S:
                eng_px = InferenceEngine(
                    c_cfg, params=c_params,
                    engine_cfg=EngineConfig(
                        prefix_cache_entries=4, ragged_prefill=False
                    ),
                )
                cont = ContinuousEngine(
                    eng_px, n_slots=n_slots, chunk_steps=chunk,
                    slot_max_seq=slot_max_seq,
                    kv_pool_blocks=pool_blocks, kv_block_size=32,
                )
                try:
                    v = churn(cont, prefix_prompts)
                    if v:
                        cont_block["paged_prefix_tokens_per_sec"] = round(v, 3)
                        # the round-over-round cliff tracker: shared-prompt
                        # churn relative to the plain paged leg (was ~0.13x
                        # under snapshot-splice-scatter in BENCH_r05)
                        base = cont_block.get("paged_tokens_per_sec")
                        if base:
                            cont_block["paged_prefix_speedup"] = round(
                                v / base, 3
                            )
                        st = cont.stats()
                        cont_block["prefix_cache"] = st.get("prefix_cache")
                        cont_block["paged_sharing"] = st.get("paged")
                finally:
                    cont.close()
                _write_sidecar(dict(result, continuous=cont_block))

            # ragged leg: the SAME mixed prefill+decode shared-prefix
            # churn through the ragged ingest (engine_cfg.ragged_prefill
            # default-on — admission prefills straight into the pool, one
            # compiled launch pair for any tail, exact-depth prefix
            # reuse). Reported side by side with the bucketed
            # paged_prefix leg so the BENCH trajectory captures the gap
            # closing (~50 tok/s in r05).
            if time.perf_counter() - T_START < BATCH_LEG_DEADLINE_S:
                eng_rg = InferenceEngine(
                    c_cfg, params=c_params,
                    # chunked_prefill=False: this leg tracks the
                    # PER-ADMISSION ragged ingest vs the bucketed
                    # fallback (round-over-round comparability with
                    # BENCH_r05); the chunked scheduler has its own
                    # sched_interleave leg below
                    engine_cfg=EngineConfig(
                        prefix_cache_entries=4, chunked_prefill=False
                    ),
                )
                cont = ContinuousEngine(
                    eng_rg, n_slots=n_slots, chunk_steps=chunk,
                    slot_max_seq=slot_max_seq,
                    kv_pool_blocks=pool_blocks, kv_block_size=32,
                )
                try:
                    v = churn(cont, prefix_prompts)
                    if v:
                        cont_block["ragged_tokens_per_sec"] = round(v, 3)
                        base = cont_block.get("paged_prefix_tokens_per_sec")
                        if base:
                            cont_block["ragged_vs_prefix_speedup"] = round(
                                v / base, 3
                            )
                        st = cont.stats()
                        cont_block["ragged_paged"] = st.get("paged")
                        snap = eng_rg.metrics.snapshot()

                        def _ctr(name):
                            return {
                                "|".join(
                                    f"{k}={v2}"
                                    for k, v2 in sorted(
                                        s["labels"].items()
                                    )
                                ) or "_": s["value"]
                                for s in snap.get(name, {}).get(
                                    "series", []
                                )
                            }

                        cont_block["ragged_metrics"] = {
                            "rows": _ctr("dli_ragged_rows_total"),
                            "tiles": _ctr("dli_ragged_tiles_total"),
                            "launches": _ctr("dli_ragged_launches_total"),
                            "exact_prefix_hits": _ctr(
                                "dli_ragged_exact_prefix_hits_total"
                            ),
                            "compiled_programs": _ctr(
                                "dli_ragged_compiled_programs"
                            ),
                        }
                finally:
                    cont.close()
                _write_sidecar(dict(result, continuous=cont_block))

            # SLO-aware chunked-prefill scheduler leg (engine/
            # scheduler.py): LONG prompts keep arriving while a request
            # streams steady decode. Whole-prefill admission stalls every
            # decoding request for each full prefill; the chunked
            # scheduler slices the prompt into budget-sized chunks
            # interleaved with the decode rows in ONE mixed launch per
            # step. Decode TPOT p99 is the standard inter-token-latency
            # percentile over the streamed token arrivals (a k-token
            # burst = one gap + k-1 zeros — tokens arriving together
            # cost the client one wait), so the whole-prefill stall
            # lands on the token that actually waited out the prefill.
            # Reported: sched_interleave_tpot_p99 vs
            # whole_prefill_tpot_p99 + ratio and the worst single stall.
            # (CPU proxy caveat: compute here is width-linear, so the
            # interleave win is structurally understated vs a TPU, where
            # small-batch launches are latency-bound and overlapping
            # prefill compute under decode is nearly free.)
            if time.perf_counter() - T_START < BATCH_LEG_DEADLINE_S:
                long_prompt = "d " * int(slot_max_seq * 0.43)
                sched_budget = n_slots * 8 + 8

                def interleave_leg(chunked):
                    eng_i = InferenceEngine(
                        c_cfg, params=c_params,
                        engine_cfg=EngineConfig(
                            prefix_cache_entries=0,
                            chunked_prefill=chunked,
                            step_token_budget=sched_budget,
                        ),
                    )
                    cont = ContinuousEngine(
                        eng_i, n_slots=n_slots, chunk_steps=chunk,
                        chunk_lag=1, slot_max_seq=slot_max_seq,
                        kv_pool_blocks=pool_blocks, kv_block_size=32,
                    )
                    itl, toks = [], [0]
                    lock = threading.Lock()
                    stop = threading.Event()
                    try:
                        cont.submit(prompts[0], **dict(kw, max_tokens=40))
                        cont.submit(long_prompt, **dict(kw, max_tokens=2))

                        def decoder():
                            last_t, last_n = None, 0
                            for ev in cont.stream(
                                "steady decoder",
                                **dict(kw, max_tokens=150),
                            ):
                                now = time.perf_counter()
                                if ev.get("done"):
                                    break
                                n = ev.get("tokens_so_far", last_n)
                                dn = n - last_n
                                if last_t is not None and dn > 0:
                                    with lock:
                                        itl.append(now - last_t)
                                        itl.extend([0.0] * (dn - 1))
                                last_t, last_n = now, n
                            stop.set()

                        def longs():
                            while not stop.is_set():
                                r = cont.submit(
                                    long_prompt, **dict(kw, max_tokens=2)
                                )
                                if r.get("status") == "success":
                                    with lock:
                                        toks[0] += (
                                            r["tokens_generated"]
                                            + r["prompt_tokens"]
                                        )
                                time.sleep(0.06)

                        t0 = time.perf_counter()
                        ts = [threading.Thread(target=decoder)] + [
                            threading.Thread(target=longs)
                            for _ in range(2)
                        ]
                        for t in ts:
                            t.start()
                        for t in ts:
                            t.join()
                        wall = time.perf_counter() - t0
                    finally:
                        cont.close()
                    if not itl:
                        return None
                    itl.sort()
                    return {
                        "tpot_p99_s": round(
                            itl[min(len(itl) - 1, int(0.99 * len(itl)))], 5
                        ),
                        "max_stall_s": round(itl[-1], 5),
                        "tokens_per_sec": round((toks[0] + 150) / wall, 3),
                        "itl_samples": len(itl),
                    }

                sched_leg = interleave_leg(True)
                whole_leg = interleave_leg(False)
                if sched_leg and whole_leg:
                    cont_block["sched_interleave_tpot_p99"] = sched_leg[
                        "tpot_p99_s"
                    ]
                    cont_block["whole_prefill_tpot_p99"] = whole_leg[
                        "tpot_p99_s"
                    ]
                    if sched_leg["tpot_p99_s"] > 0:
                        cont_block["sched_tpot_p99_improvement"] = round(
                            whole_leg["tpot_p99_s"]
                            / sched_leg["tpot_p99_s"], 3,
                        )
                    cont_block["sched_interleave"] = {
                        "chunked": sched_leg, "whole_prefill": whole_leg,
                        "step_token_budget": sched_budget,
                        "long_prompt_tokens_approx": int(
                            slot_max_seq * 0.86
                        ),
                    }
        except Exception:  # noqa: BLE001 - optional leg, never fatal
            import traceback

            traceback.print_exc(file=sys.stderr)

    # recovery leg (engine/shadow.py warm-state recovery): the same
    # long-prompt request served across a mid-decode scheduler crash,
    # shadow ON (warm: restore + partial-tail re-prefill) vs OFF (cold:
    # whole-prompt re-prefill). time_to_recover = faulted wall minus the
    # fault-free wall of the identical request, so the number isolates
    # the recovery cost; tokens_recomputed comes straight off
    # dli_recovery_tokens_recomputed_total. Headline:
    # warm_recovery_speedup = cold time-to-recover / warm.
    if cont_block and time.perf_counter() - T_START < BATCH_LEG_DEADLINE_S:
        try:
            from distributed_llm_inference_tpu.utils import faults as _faults

            long_p = "r " * int(slot_max_seq * 0.4)

            def _ctr_total(eng_x, name):
                snap = eng_x.metrics.snapshot()
                return sum(
                    s["value"]
                    for s in snap.get(name, {}).get("series", [])
                )

            def recovery_leg(warm):
                eng_v = InferenceEngine(
                    c_cfg, params=c_params,
                    engine_cfg=EngineConfig(prefix_cache_entries=4),
                )
                cont = ContinuousEngine(
                    eng_v, n_slots=n_slots, chunk_steps=chunk,
                    slot_max_seq=slot_max_seq,
                    kv_pool_blocks=pool_blocks, kv_block_size=32,
                    restart_backoff_s=0.01, kv_shadow=warm,
                )
                try:
                    cont.submit(long_p, **kw)  # compile + shadow warm
                    # warm the RECOVERY path too (the whole-prefill
                    # re-admission programs the chunked serving path
                    # never compiles, plus the restore scatter) with a
                    # throwaway crash — the timed window below measures
                    # steady-state recovery, not jit latency, same
                    # discipline as every other leg's warmup
                    _faults.arm([_faults.FaultRule(
                        "decode_launch", "transient", on_call=2
                    )])
                    cont.submit(long_p, **kw)
                    _faults.disarm()
                    t0 = time.perf_counter()
                    r_clean = cont.submit(long_p, **kw)
                    clean_s = time.perf_counter() - t0
                    if warm:
                        cont._shadow.flush(10.0)
                    base = _ctr_total(
                        eng_v, "dli_recovery_tokens_recomputed_total"
                    )
                    _faults.arm([_faults.FaultRule(
                        "decode_launch", "transient", on_call=3
                    )])
                    t0 = time.perf_counter()
                    r_fault = cont.submit(long_p, **kw)
                    fault_s = time.perf_counter() - t0
                    _faults.disarm()
                    ok = (
                        r_fault.get("status") == "success"
                        and r_fault.get("response")
                        == r_clean.get("response")
                        and cont.restarts_total == 2
                    )
                    return {
                        "ok": ok,
                        "clean_request_s": round(clean_s, 4),
                        "faulted_request_s": round(fault_s, 4),
                        "time_to_recover_s": round(
                            max(0.0, fault_s - clean_s), 4
                        ),
                        "tokens_recomputed": int(_ctr_total(
                            eng_v, "dli_recovery_tokens_recomputed_total"
                        ) - base),
                        "restored_blocks": cont.shadow_restored_total,
                    }
                finally:
                    _faults.disarm()
                    cont.close()

            warm_leg = recovery_leg(True)
            cold_leg = recovery_leg(False)
            cont_block["recovery"] = {
                "warm": warm_leg, "cold": cold_leg,
                "prompt_tokens_approx": len(long_p),
                "kv_block_size": 32,
            }
            if (
                warm_leg["ok"] and cold_leg["ok"]
                and warm_leg["time_to_recover_s"] > 0
            ):
                cont_block["warm_recovery_speedup"] = round(
                    cold_leg["time_to_recover_s"]
                    / warm_leg["time_to_recover_s"], 3,
                )
            _write_sidecar(dict(result, continuous=cont_block))
        except Exception:  # noqa: BLE001 - optional leg, never fatal
            import traceback

            traceback.print_exc(file=sys.stderr)

    # overload leg (SLO-aware KV preemption, engine/continuous.py
    # _preempt_for): a low-priority HOG decode holds most of a pool
    # sized to ~60% of the combined working set while deadline-carrying
    # interactive requests arrive. Shed-only ("off"): each interactive
    # admission waits for the hog's full decode and blows its
    # deadline_ms (504). Preemption ("swap"): the hog is evicted
    # (lowest weight), its KV swapped to the host shadow, and the
    # interactive stream completes inside its deadlines; the hog
    # resumes between arrivals. Headline: interactive completion rate
    # + p99 — "pool full" as a policy decision, not a tail-latency
    # cliff.
    if cont_block and time.perf_counter() - T_START < BATCH_LEG_DEADLINE_S:
        try:
            ov_bs = 32
            ov_slot_seq = 512  # 16 blocks
            hog_p = prompts[0]
            # the hog's budget fills its WHOLE slot class, so its blocks
            # span the entire usable pool and no short can be placed
            # beside it — the pool lands at ~60% of the combined
            # (hog + interactive stream) working set
            hog_mt = ov_slot_seq - (len(hog_p) + 8) - 1
            hog_kw = dict(max_tokens=hog_mt, greedy=True, chat=False,
                          slo_class="batch")
            short_p = "interactive q"
            short_kw = dict(max_tokens=8, greedy=True, chat=False,
                            slo_class="interactive")
            hog_need = -(-(len(hog_p) + 8 + hog_mt) // ov_bs)
            short_need = -(-(len(short_p) + 8 + 8) // ov_bs)
            ov_pool = ov_slot_seq // ov_bs + 1  # usable == one slot class
            n_short = 6

            def overload_leg(policy):
                eng_o = InferenceEngine(
                    c_cfg, params=c_params,
                    engine_cfg=EngineConfig(
                        prefix_cache_entries=4, preempt_policy=policy,
                        # the livelock cap exists for safety; the bench
                        # measures the policy ceiling, so let the hog be
                        # preempted once per interactive arrival
                        max_preemptions_per_req=64,
                    ),
                )
                cont = ContinuousEngine(
                    eng_o, n_slots=n_slots, chunk_steps=chunk,
                    slot_max_seq=ov_slot_seq,
                    kv_pool_blocks=ov_pool, kv_block_size=ov_bs,
                )
                try:
                    cont.submit(hog_p, **dict(hog_kw, max_tokens=8))
                    t0 = time.perf_counter()
                    clean = cont.submit(short_p, **short_kw)
                    clean_s = time.perf_counter() - t0
                    if clean.get("status") != "success":
                        return None
                    deadline_ms = max(200.0, 6 * clean_s * 1e3)
                    hog_out = {}

                    def run_hog():
                        hog_out["r"] = cont.submit(hog_p, **hog_kw)

                    th = threading.Thread(target=run_hog)
                    th.start()
                    while cont.stats()["occupied"] < 1:
                        time.sleep(0.002)
                    walls, ok = [], 0
                    t0 = time.perf_counter()
                    for _ in range(n_short):
                        t1 = time.perf_counter()
                        r = cont.submit(
                            short_p, deadline_ms=deadline_ms, **short_kw
                        )
                        w = time.perf_counter() - t1
                        if r.get("status") == "success":
                            ok += 1
                            walls.append(w)
                        time.sleep(0.01)
                    wall = time.perf_counter() - t0
                    th.join(timeout=120)
                    walls.sort()
                    return {
                        "offered": n_short,
                        "completed": ok,
                        "completion_rate": round(ok / n_short, 3),
                        "p99_s": round(
                            walls[min(len(walls) - 1,
                                      int(0.99 * len(walls)))], 4,
                        ) if walls else None,
                        "deadline_ms": round(deadline_ms, 1),
                        "wall_s": round(wall, 3),
                        "preempted": cont.preempted_total,
                        "hog_status": hog_out.get("r", {}).get("status"),
                    }
                finally:
                    cont.close()

            preempt_leg = overload_leg("swap")
            shed_leg = overload_leg("off")
            if preempt_leg and shed_leg:
                cont_block["overload"] = {
                    "preempt": preempt_leg, "shed_only": shed_leg,
                    "pool_blocks": ov_pool,
                    "working_set_blocks": hog_need + 6 * short_need,
                }
                cont_block["overload_completion_rate"] = preempt_leg[
                    "completion_rate"
                ]
                cont_block["overload_completion_rate_shed_only"] = shed_leg[
                    "completion_rate"
                ]
            _write_sidecar(dict(result, continuous=cont_block))
        except Exception:  # noqa: BLE001 - optional leg, never fatal
            import traceback

            traceback.print_exc(file=sys.stderr)

    # multi-tenant adapter-serving leg (ISSUE 16: engine/adapters.py
    # paged runtime LoRA): one resident base + a refcounted LRU page
    # pool serving three registered adapters, driven by a mixed client
    # fleet where every request carries (adapter, tenant) — base rows
    # and two adapters interleave inside the SAME compiled mixed
    # launches. Measured against the naive alternative the subsystem
    # replaces: serving each adapter's traffic as its own sequential
    # fleet (what merge-at-load forces — one merged model resident at a
    # time). Headlines: mixed_tokens_per_sec vs adapter-sequential
    # tok/s + the consolidation speedup; a mixed-vs-solo greedy
    # identity probe (the same prompt+adapter must emit the same text
    # inside the mix as alone); per-tenant completed-token spread
    # (fairness under the weighted scheduler split); and the pool
    # ledger after an eviction probe (3 adapters through 2 pages ->
    # swaps > 0, referenced == 0 after drain).
    if cont_block and time.perf_counter() - T_START < BATCH_LEG_DEADLINE_S:
        try:
            import numpy as _np

            from distributed_llm_inference_tpu.engine.adapters import (
                adapter_leaf_dims,
                attach_adapter_pool,
            )

            mt_rank = 4
            mt_ads = ["ad-a", "ad-b", "ad-c"]

            def _mt_adapter(seed):
                rng = _np.random.default_rng(seed)
                L = c_cfg.n_layers
                return {
                    leaf: (
                        (rng.standard_normal((L, d_in, mt_rank))
                         * 0.02).astype(_np.float32),
                        (rng.standard_normal((L, mt_rank, d_out))
                         * 0.02).astype(_np.float32),
                    )
                    for leaf, (d_in, d_out)
                    in adapter_leaf_dims(c_cfg).items()
                }

            eng_mt = InferenceEngine(
                c_cfg, params=c_params,
                engine_cfg=EngineConfig(
                    prefix_cache_entries=0,
                    tenant_weights=(("acme", 1.0), ("globex", 1.0)),
                ),
            )
            pool_mt = attach_adapter_pool(eng_mt, slots=2, rank=mt_rank)
            for i, nm in enumerate(mt_ads):
                pool_mt.register(nm, _mt_adapter(11 + i))
            cont = ContinuousEngine(
                eng_mt, n_slots=n_slots, chunk_steps=chunk,
                slot_max_seq=slot_max_seq,
                kv_pool_blocks=pool_blocks, kv_block_size=32,
            )
            try:
                # warm the base and adapter paths (same program — the
                # pages operand is traced — but the first adapter
                # admission pays the page write)
                cont.submit(prompts[0], **kw)
                cont.submit(prompts[0], adapter="ad-a", **kw)
                # identity probe reference: prompt[1] under ad-a, alone
                solo_ref = cont.submit(prompts[1], adapter="ad-a", **kw)

                def mt_churn(jobs):
                    """jobs: [(prompt, adapter|None, tenant|None)].
                    Returns (tok/s, per-tenant tokens, outputs)."""
                    done = [0]
                    per_tenant: dict = {}
                    outs: dict = {}
                    lock = threading.Lock()
                    it = iter(jobs)

                    def client():
                        while True:
                            with lock:
                                j = next(it, None)
                            if j is None:
                                return
                            p, ad, ten = j
                            extra = {}
                            if ad:
                                extra["adapter"] = ad
                            if ten:
                                extra["tenant"] = ten
                            r = cont.submit(p, **kw, **extra)
                            if r.get("status") == "success":
                                with lock:
                                    done[0] += r["tokens_generated"]
                                    key = ten or ""
                                    per_tenant[key] = (
                                        per_tenant.get(key, 0)
                                        + r["tokens_generated"]
                                    )
                                    outs[(p, ad)] = r.get("response")

                    t0 = time.perf_counter()
                    threads = [
                        threading.Thread(target=client) for _ in range(8)
                    ]
                    for t in threads:
                        t.start()
                    for t in threads:
                        t.join()
                    wall = time.perf_counter() - t0
                    tps = (done[0] / wall) if done[0] else None
                    return tps, per_tenant, outs, wall

                mixed_jobs = [
                    (
                        prompts[i % n_req],
                        (None, "ad-a", "ad-b")[i % 3],
                        ("acme", "globex")[i % 2],
                    )
                    for i in range(n_req * 2)
                ]
                mixed_tps, per_tenant, outs, _ = mt_churn(mixed_jobs)

                # the consolidation baseline: the same jobs grouped by
                # adapter and served as three back-to-back fleets (the
                # merge-at-load world — one adapter resident at a time)
                solo_tokens, solo_wall = 0, 0.0
                for ad in (None, "ad-a", "ad-b"):
                    group = [j for j in mixed_jobs if j[1] == ad]
                    tps_g, pt_g, _, wall_g = mt_churn(group)
                    solo_tokens += sum(pt_g.values())
                    solo_wall += wall_g
                solo_tps = (
                    solo_tokens / solo_wall if solo_tokens else None
                )

                # eviction probe: ad-c through the 2-page pool evicts
                # the LRU resident (a swap) — referenced pages stay
                # untouchable, and after the drain nothing holds a page
                cont.submit(prompts[2], adapter="ad-c", **kw)

                mt_block = {
                    "adapters": len(mt_ads),
                    "pool_pages": pool_mt.total,
                    "rank": mt_rank,
                    # CPU proxy caveat: compute here is width-linear, so
                    # co-batching adapter mixes buys no launch
                    # amortization — the consolidation win is
                    # structurally understated vs a TPU, where the
                    # sequential baseline pays one weight stream PER
                    # fleet while the mix pays one total
                    "note": (
                        "consolidation_speedup is launch-amortization "
                        "bound; CPU proxy understates it"
                    ) if platform != "tpu" else None,
                    "mixed_tokens_per_sec": (
                        round(mixed_tps, 3) if mixed_tps else None
                    ),
                    "adapter_sequential_tokens_per_sec": (
                        round(solo_tps, 3) if solo_tps else None
                    ),
                    "mixed_matches_solo": (
                        outs.get((prompts[1], "ad-a"))
                        == solo_ref.get("response")
                    ),
                    "tenant_tokens": dict(sorted(per_tenant.items())),
                    "pool": pool_mt.stats(),
                    "referenced_after_drain": pool_mt.referenced(),
                }
                if mixed_tps and solo_tps:
                    mt_block["consolidation_speedup"] = round(
                        mixed_tps / solo_tps, 3
                    )
                vals = [v for k, v in per_tenant.items() if k]
                if len(vals) >= 2 and max(vals) > 0:
                    mt_block["tenant_fairness_min_over_max"] = round(
                        min(vals) / max(vals), 3
                    )
                cont_block["multi_tenant"] = mt_block
                if mixed_tps:
                    cont_block["mixed_adapter_tokens_per_sec"] = round(
                        mixed_tps, 3
                    )
            finally:
                cont.close()
            _write_sidecar(dict(result, continuous=cont_block))
        except Exception:  # noqa: BLE001 - optional leg, never fatal
            import traceback

            traceback.print_exc(file=sys.stderr)

    # speculative-decoding leg (ISSUE 13: draft-then-verify inside the
    # mixed launch, engine/paged.spec_verify + the scheduler's n-gram
    # planner): drive the REAL compiled mixed program launch for launch,
    # plain 1-token decode rows vs [current + K-draft] verify rows, on a
    # self-repeating stream (drafts accept) and with forced-junk drafts
    # (the rejection worst case — a verify row occupies the same query
    # tile as a plain row, so rejection must cost ~nothing). Headlines:
    # accepted_tokens_per_launch, per-token TPOT p50/p99 per variant,
    # spec_tpot_speedup = plain p99 / spec p99. Launch-normalized on
    # purpose: each launch streams the full weights on a TPU, so
    # tokens-per-launch IS the decode-speed lever; the CPU proxy's
    # width-linear attention understates nothing at this granularity
    # because both variants time the IDENTICAL compiled program.
    if cont_block and time.perf_counter() - T_START < BATCH_LEG_DEADLINE_S:
        try:
            import numpy as _np

            from distributed_llm_inference_tpu.engine import generate as _G
            from distributed_llm_inference_tpu.engine import paged as _EP
            from distributed_llm_inference_tpu.engine.scheduler import (
                ngram_draft,
            )

            sp_bs, sp_MB, sp_W, sp_K = 32, 16, 32, 4
            K1 = sp_K + 1
            sp_table = jnp.asarray([list(range(1, sp_MB + 1))], jnp.int32)
            sp_arm = _EP.idle_mixed_arm(1, c_cfg.vocab_size)
            sp_key = jax.random.PRNGKey(5)
            spec_tokens_target = 64 if platform != "tpu" else 128
            # two prompts, prefilled into the pool (three real ragged
            # extends each) so the drafts verify against real KV: a
            # periodic one — the "repetitive/structured" workload the
            # speculation targets — and a unique-token one, the
            # incompressible leg (the n-gram planner finds no draft →
            # plain decode rows → the machinery must cost nothing)
            sp_ids_rep = ([100, 101, 35] * 33)[:97]
            sp_ids_unique = [
                (40 + 7 * j) % c_cfg.vocab_size for j in range(97)
            ]

            def spec_program_leg(mode, sp_ids):
                """mode: 'plain' | 'ngram' | 'junk'. Returns per-token
                TPOT samples + tokens/launch over a timed window."""
                pool = _EP.init_pool(c_cfg, sp_MB + 2, sp_bs)
                for c in range(3):
                    meta, tok_row, tok_pos, _, _ = _EP.build_ragged_meta(
                        [(0, c * 32, 32, _EP.RAGGED_PREFILL)],
                        width=sp_W, tile=8,
                    )
                    pool = _EP.extend_ragged_paged(
                        c_cfg, c_params,
                        jnp.asarray(sp_ids[c * 32 : (c + 1) * 32],
                                    jnp.int32),
                        jnp.asarray(tok_row), jnp.asarray(tok_pos),
                        jnp.asarray(meta), pool, sp_table,
                    )
                state, sparams = _G.init_slots(1, c_cfg.vocab_size)
                hist = list(sp_ids)
                state = state._replace(
                    token=jnp.asarray([hist[-1]], jnp.int32),
                    pos=jnp.asarray([len(hist) - 1], jnp.int32),
                    active=jnp.asarray([True]),
                    remaining=jnp.asarray([4096], jnp.int32),
                )
                sparams = sparams._replace(greedy=jnp.asarray([True]))
                samples, launches, emitted_total = [], 0, 0
                wall_samples = []  # per-token wall clock (wall / tokens)
                warm_until = 64

                def one_launch(state, pool):
                    pos_h = len(hist) - 1
                    draft = []
                    if mode == "ngram":
                        draft = ngram_draft(hist, sp_K)
                    elif mode == "junk":
                        draft = [
                            (13 + 7 * (pos_h + j)) % c_cfg.vocab_size
                            for j in range(sp_K)
                        ]
                    n_d = len(draft)
                    kind = (
                        _EP.RAGGED_PREFILL if n_d else _EP.RAGGED_DECODE
                    )
                    meta, tok_row, tok_pos, offs, _ = (
                        _EP.build_ragged_meta(
                            [(0, pos_h, 1 + n_d, kind)],
                            width=sp_W, tile=8,
                        )
                    )
                    toks = _np.zeros((sp_W,), _np.int32)
                    dec_flag = _np.zeros((sp_W,), bool)
                    dec_flag[offs[0]] = True
                    spec = None
                    if n_d:
                        toks[offs[0] + 1 : offs[0] + 1 + n_d] = draft
                        idxs = offs[0] + _np.arange(K1, dtype=_np.int32)
                        idxs[n_d + 1:] = offs[0] + n_d
                        spec = _EP.SpecPlan(
                            jnp.asarray([False]), jnp.asarray([True]),
                            jnp.asarray(idxs[None, :]),
                            jnp.asarray([n_d], jnp.int32),
                        )
                    return _EP.mixed_step_ragged(
                        c_cfg, c_params, jnp.asarray(toks),
                        jnp.asarray(tok_row), jnp.asarray(tok_pos),
                        jnp.asarray(dec_flag), jnp.asarray(meta), pool,
                        sp_table, state, sparams, sp_key,
                        jnp.asarray([offs[0] if not n_d else 0],
                                    jnp.int32),
                        sp_arm, spec=spec,
                    ), n_d

                while emitted_total < warm_until + spec_tokens_target:
                    t0 = time.perf_counter()
                    (packed, state, sparams, pool), n_d = one_launch(
                        state, pool
                    )
                    p = _np.asarray(packed)  # the fetch
                    wall = time.perf_counter() - t0
                    if n_d:
                        em = p[5 : 5 + K1, 0]
                        mk = p[5 + K1 : 5 + 2 * K1, 0].astype(bool)
                        got = em[mk].tolist()
                    else:
                        got = [int(p[0, 0])] if p[1, 0] else []
                    if not got:
                        break  # stop token: restart would skew timing
                    hist.extend(int(t) for t in got)
                    emitted_total += len(got)
                    if emitted_total > warm_until:
                        launches += 1
                        samples.append(wall)
                        samples.extend([0.0] * (len(got) - 1))
                        wall_samples.extend(
                            [wall / len(got)] * len(got)
                        )
                if not samples:
                    return None
                s = sorted(samples)
                w = sorted(wall_samples)
                return {
                    "tokens": len(samples),
                    "launches": launches,
                    "tokens_per_launch": round(
                        len(samples) / launches, 3
                    ),
                    "tpot_p50_s": round(s[len(s) // 2], 6),
                    "tpot_p99_s": round(
                        s[min(len(s) - 1, int(0.99 * len(s)))], 6
                    ),
                    "tpot_mean_s": round(sum(s) / len(s), 6),
                    # wall-clock per-token percentiles (each launch's
                    # wall amortized over its emitted tokens): the
                    # cross-leg-comparable TPOT trajectory — the ITL
                    # samples above pin whole launch walls to single
                    # tokens by design, so their p50/p99 are not
                    # comparable to the serving legs' TPOT numbers
                    "wall_tpot_p50_s": round(w[len(w) // 2], 6),
                    "wall_tpot_p99_s": round(
                        w[min(len(w) - 1, int(0.99 * len(w)))], 6
                    ),
                    "wall_tpot_mean_s": round(sum(w) / len(w), 6),
                }

            plain_leg = spec_program_leg("plain", sp_ids_rep)
            ngram_leg = spec_program_leg("ngram", sp_ids_rep)
            plain_u = spec_program_leg("plain", sp_ids_unique)
            ngram_u = spec_program_leg("ngram", sp_ids_unique)
            junk_leg = spec_program_leg("junk", sp_ids_rep)
            if plain_leg and ngram_leg:
                spec_block = {
                    "plain": plain_leg,
                    "speculative": ngram_leg,
                    "incompressible_plain": plain_u,
                    "incompressible_spec": ngram_u,
                    "rejected_drafts": junk_leg,
                    "draft_len": sp_K,
                    "launch_width": sp_W,
                }
                spec_block["accepted_tokens_per_launch"] = ngram_leg[
                    "tokens_per_launch"
                ]
                if ngram_leg["tpot_p99_s"] > 0:
                    spec_block["spec_tpot_speedup"] = round(
                        plain_leg["tpot_p99_s"] / ngram_leg["tpot_p99_s"],
                        3,
                    )
                if ngram_leg["tpot_mean_s"] > 0:
                    # mean TPOT is the steadier headline at this sample
                    # count: ITL-style accounting pins every p99 sample
                    # to a whole launch wall, so p99 can only show the
                    # per-launch delta, never the tokens-per-launch win
                    spec_block["spec_tpot_mean_speedup"] = round(
                        plain_leg["tpot_mean_s"]
                        / ngram_leg["tpot_mean_s"], 3,
                    )
                if (
                    plain_u and ngram_u and plain_u["tpot_p99_s"] > 0
                ):
                    # the production incompressible path: no bigram
                    # match → plain decode rows → ~1.0 (no regression)
                    spec_block["incompressible_tpot_ratio"] = round(
                        ngram_u["tpot_p99_s"] / plain_u["tpot_p99_s"], 3
                    )
                    if ngram_u["tpot_mean_s"] > 0:
                        spec_block["incompressible_tpot_mean_ratio"] = (
                            round(
                                ngram_u["tpot_mean_s"]
                                / plain_u["tpot_mean_s"], 3,
                            )
                        )
                if junk_leg and plain_leg["tpot_p99_s"] > 0:
                    # the FORCED worst case: every launch a verify row,
                    # every draft rejected — bounds the overhead of a
                    # verify row (same query tile as a plain row)
                    spec_block["rejected_tpot_ratio"] = round(
                        junk_leg["tpot_p99_s"] / plain_leg["tpot_p99_s"],
                        3,
                    )
                cont_block["speculative"] = spec_block
            _write_sidecar(dict(result, continuous=cont_block))
        except Exception:  # noqa: BLE001 - optional leg, never fatal
            import traceback

            traceback.print_exc(file=sys.stderr)

    # spec_lag leg (ISSUE 15: device-derived launch metadata): the REAL
    # serving loop — a 4-slot chunked fleet, 3 speculating greedy
    # streams plus one long-lived sampled (spec-ineligible) stream that
    # keeps the scheduler launching throughout — with the
    # skip-until-fetched freeze DELETED (spec_device_meta=True, verify
    # rows back-to-back under lag pipelining) vs the PR-13 baseline
    # (=False: a slot with an unfetched verify row carries no row, so
    # every launch that fires while it waits still streams the full
    # weights WITHOUT it). Speculation runs the draft-model flavor with
    # draft == target, so acceptance is real and equal on both paths
    # (the random-weight proxy's n-gram acceptance is ~0 — real weights
    # would supply it; the freeze cost being measured is identical
    # either way). Headlines: launches-per-accepted-token over the
    # speculating streams' LIFETIME (mixed launches fired until the
    # last one finished / their emitted tokens — LOWER is better; the
    # freeze structurally inflates it) and wall-clock TPOT p50/p99,
    # with greedy output asserted bit-identical across the two paths.
    # Gate: >= 1.3x launches-per-token improvement on this proxy.
    if cont_block and time.perf_counter() - T_START < BATCH_LEG_DEADLINE_S:
        try:
            lag_rep = "the cat sat on the mat " * 10
            lag_bg = " ".join(f"u{j}_{j * 7}" for j in range(24))
            lag_kw = dict(max_tokens=48, greedy=True, chat=False)

            def spec_lag_leg(device_meta):
                eng = InferenceEngine(
                    c_cfg, params=c_params,
                    engine_cfg=EngineConfig(
                        prefix_cache_entries=0, chunked_prefill=True,
                        step_token_budget=64,
                        prefill_buckets=(64, 128, 256),
                        spec_decode=True, spec_draft_len=4,
                        spec_draft_model=c_cfg.name,
                        spec_device_meta=device_meta,
                    ),
                )
                eng.set_draft(c_cfg, c_params)  # draft == target
                cont = ContinuousEngine(
                    eng, n_slots=4, chunk_steps=8,
                    slot_max_seq=slot_max_seq,
                    kv_pool_blocks=pool_blocks, kv_block_size=32,
                )
                try:
                    # warm every program (spec + plain + sampled)
                    cont.submit(lag_rep, max_tokens=8, greedy=True,
                                chat=False)
                    cont.submit(lag_bg, max_tokens=8, greedy=False,
                                temperature=0.9, chat=False)
                    fam = eng.metrics.get("dli_ragged_launches_total")

                    def mixed_launches():
                        return sum(
                            s["value"]
                            for s in fam.snapshot()["series"]
                            if s["labels"].get("phase") == "mixed"
                        )

                    base_launches = mixed_launches()
                    st0 = cont.stats().get("speculative", {})
                    out = [None] * 3
                    lock = threading.Lock()
                    marks = []
                    started = threading.Event()

                    def rep_client(i):
                        started.wait(30)
                        r = cont.submit(lag_rep, **lag_kw)
                        with lock:
                            marks.append(mixed_launches())
                        out[i] = r

                    def bg_client():
                        started.set()
                        cont.submit(lag_bg, max_tokens=200, greedy=False,
                                    temperature=0.9, chat=False)

                    t0 = time.perf_counter()
                    threads = [threading.Thread(target=bg_client)] + [
                        threading.Thread(target=rep_client, args=(i,))
                        for i in range(3)
                    ]
                    for t in threads:
                        t.start()
                    for t in threads:
                        t.join()
                    wall = time.perf_counter() - t0
                    st = cont.stats().get("speculative", {})
                finally:
                    cont.close()
                if any(
                    r is None or r.get("status") != "success" for r in out
                ) or not marks:
                    return None
                launches = max(marks) - base_launches
                tokens = sum(r["tokens_generated"] for r in out)
                tpots = sorted(
                    max(0.0, float(str(r["time_taken"]).rstrip("s"))
                        - r["ttft_s"]) / (r["tokens_generated"] - 1)
                    for r in out if r["tokens_generated"] > 1
                )
                leg = {
                    "device_meta": device_meta,
                    "mixed_launches_in_window": int(launches),
                    "tokens": int(tokens),
                    "accepted_tokens": (
                        st.get("accepted_tokens", 0)
                        - st0.get("accepted_tokens", 0)
                    ),
                    "spec_launches": (
                        st.get("launches", 0) - st0.get("launches", 0)
                    ),
                    "pipelined_launches": st.get("pipelined_launches", 0),
                    "wall_s": round(wall, 4),
                }
                if tokens and launches:
                    leg["launches_per_token"] = round(
                        launches / tokens, 4
                    )
                if tpots:
                    leg["wall_tpot_p50_s"] = round(
                        tpots[len(tpots) // 2], 6
                    )
                    leg["wall_tpot_p99_s"] = round(tpots[-1], 6)
                return leg, sorted(r["response"] for r in out)

            lag_dev = spec_lag_leg(True)
            lag_base = spec_lag_leg(False)
            if lag_dev and lag_base:
                dev_leg, dev_out = lag_dev
                base_leg, base_out = lag_base
                lag_block = {
                    "device_meta": dev_leg,
                    "pr13_frozen_baseline": base_leg,
                    "draft_len": 4,
                    # the two paths are a launch strategy, never a
                    # semantics change
                    "bit_identical": dev_out == base_out,
                }
                if (
                    dev_leg.get("launches_per_token")
                    and base_leg.get("launches_per_token")
                ):
                    imp = (
                        base_leg["launches_per_token"]
                        / dev_leg["launches_per_token"]
                    )
                    lag_block["launches_per_token_improvement"] = round(
                        imp, 3
                    )
                    lag_block["gate_1p3x"] = bool(imp >= 1.3)
                if (
                    dev_leg.get("wall_tpot_p99_s")
                    and base_leg.get("wall_tpot_p99_s")
                ):
                    lag_block["wall_tpot_p99_speedup"] = round(
                        base_leg["wall_tpot_p99_s"]
                        / dev_leg["wall_tpot_p99_s"], 3,
                    )
                cont_block["spec_lag"] = lag_block
            _write_sidecar(dict(result, continuous=cont_block))
        except Exception:  # noqa: BLE001 - optional leg, never fatal
            import traceback

            traceback.print_exc(file=sys.stderr)

    # disagg leg (serving/kv_fabric.py + the router's prefill/decode
    # handoff): 1 prefill-class + 1 decode-class replica vs 2 mixed
    # replicas — REAL HTTP servers behind a real Router — under a
    # prefix-churn workload: a background stream of FRESH long prompts
    # (pure prefill load) while a foreground client sends interactive
    # shared-prefix requests. On the disaggregated topology the fresh
    # prefills run on the prefill replica and the decode replica pulls
    # each finished prefix over the fabric (one scatter + a tiny tail),
    # so the interactive stream's TTFT stops competing with long
    # prefills for the decode replica's step budget. Headlines:
    # interactive TTFT p99 / TPOT p99 per topology + the fabric hit
    # rate. (CPU proxy caveat: compute is width-linear here, so the
    # isolation win is structurally understated vs a TPU.)
    if cont_block and time.perf_counter() - T_START < BATCH_LEG_DEADLINE_S:
        try:
            import urllib.request

            from distributed_llm_inference_tpu.serving.router import (
                Replica, Router, RouterServer,
            )
            from distributed_llm_inference_tpu.serving.server import (
                InferenceServer,
            )

            dis_bs = 32
            shared_head = " ".join(f"warm{j}" for j in range(12)) + " "
            fresh_body = " ".join(f"load{j}" for j in range(28))

            def interactive_prompt(i):
                return shared_head + f"q{i:03d}"

            def fresh_prompt(i):
                return f"fresh{i:04d} " + fresh_body  # unique from byte 0

            def run_topology(classes):
                engines, reps = [], []
                for i, cls in enumerate(classes):
                    eng_x = InferenceEngine(
                        c_cfg, params=c_params,
                        engine_cfg=EngineConfig(
                            prefix_cache_entries=8, replica_class=cls,
                            kv_fabric_timeout_s=5.0,
                        ),
                    )
                    cont_x = ContinuousEngine(
                        eng_x, n_slots=n_slots, chunk_steps=chunk,
                        slot_max_seq=slot_max_seq,
                        kv_pool_blocks=pool_blocks, kv_block_size=dis_bs,
                    )
                    srv = InferenceServer(
                        eng_x, "127.0.0.1", 0, 64, continuous=cont_x
                    )
                    srv.start()
                    reps.append(Replica(
                        f"{cls[0]}{i}", f"http://127.0.0.1:{srv.port}",
                        replica_class=cls,
                    ))
                    engines.append((cont_x, srv))
                router = Router(
                    reps, probe_interval_s=3600.0,
                    request_timeout_s=120.0, handoff_min_bytes=128,
                )
                rserver = RouterServer(router, host="127.0.0.1", port=0)
                rserver.start()
                base = f"http://127.0.0.1:{rserver.port}"

                def post(payload):
                    req = urllib.request.Request(
                        base + "/generate",
                        data=json.dumps(payload).encode(),
                        headers={"Content-Type": "application/json"},
                        method="POST",
                    )
                    try:
                        with urllib.request.urlopen(req, timeout=120) as r:
                            return json.loads(r.read())
                    except Exception:  # noqa: BLE001 - load gen only
                        return {}

                ia_kw = dict(max_tokens=8, greedy=True, chat=False)
                # warm every program + the shared head's blocks before
                # the timed window (standard leg discipline)
                post({"prompt": interactive_prompt(0), **ia_kw})
                post({"prompt": fresh_prompt(9999), "max_tokens": 2,
                      "greedy": True, "chat": False})
                stop = threading.Event()

                def churn():
                    i = 0
                    while not stop.is_set():
                        post({"prompt": fresh_prompt(i), "max_tokens": 2,
                              "greedy": True, "chat": False})
                        i += 1
                        time.sleep(0.01)

                th = threading.Thread(target=churn)
                th.start()
                ttfts, tpots = [], []
                try:
                    for i in range(1, 19):
                        r = post({"prompt": interactive_prompt(i), **ia_kw})
                        if r.get("status") == "success":
                            ttft = float(r["ttft_s"])
                            ttfts.append(ttft)
                            n = r["tokens_generated"]
                            el = float(str(r["time_taken"]).rstrip("s"))
                            if n > 1:
                                tpots.append(
                                    max(0.0, el - ttft) / (n - 1)
                                )
                finally:
                    stop.set()
                    th.join(timeout=120)
                fetches = hits = 0
                for cont_x, _ in engines:
                    st = cont_x.stats().get("kv_fabric") or {}
                    fetches += st.get("fetches", 0)
                    hits += st.get("hits", 0)
                handoffs = sum(
                    s["value"]
                    for s in router.metrics.snapshot().get(
                        "dli_router_handoffs_total", {}
                    ).get("series", [])
                )
                rserver.shutdown()
                for cont_x, srv in engines:
                    srv.shutdown()
                ttfts.sort()
                tpots.sort()

                def p99(xs):
                    return (
                        round(xs[min(len(xs) - 1, int(0.99 * len(xs)))], 5)
                        if xs else None
                    )

                return {
                    "ttft_p99_s": p99(ttfts),
                    "tpot_p99_s": p99(tpots),
                    "interactive_served": len(ttfts),
                    "fabric_fetches": fetches,
                    "fabric_hits": hits,
                    "fabric_hit_rate": (
                        round(hits / fetches, 3) if fetches else 0.0
                    ),
                    "handoffs": int(handoffs),
                }

            dis_leg = run_topology(["prefill", "decode"])
            mix_leg = run_topology(["mixed", "mixed"])
            cont_block["disagg"] = {
                "disaggregated": dis_leg, "mixed": mix_leg,
                "kv_block_size": dis_bs,
                "fresh_prompt_bytes": len(fresh_prompt(0)),
                "interactive_prompt_bytes": len(interactive_prompt(0)),
            }
            if dis_leg["ttft_p99_s"] and mix_leg["ttft_p99_s"]:
                cont_block["disagg_ttft_p99_s"] = dis_leg["ttft_p99_s"]
                cont_block["mixed_ttft_p99_s"] = mix_leg["ttft_p99_s"]
                cont_block["disagg_ttft_p99_improvement"] = round(
                    mix_leg["ttft_p99_s"] / dis_leg["ttft_p99_s"], 3
                )
            cont_block["disagg_fabric_hit_rate"] = dis_leg[
                "fabric_hit_rate"
            ]
            _write_sidecar(dict(result, continuous=cont_block))
        except Exception:  # noqa: BLE001 - optional leg, never fatal
            import traceback

            traceback.print_exc(file=sys.stderr)

    # Tracing-overhead leg (ISSUE 17): the dense continuous churn again,
    # now with every request carrying a client-minted trace context, at
    # --trace-sample-rate 0 / 0.1 / 1.0. Rate 0 is the always-on cost of
    # the seam itself (one deterministic float compare per submit; no
    # spans started, no launch notes) and is gated against this run's
    # OWN dense number (same prompts, same process, same compile cache):
    # off_within_1pct is the <=1% regression gate. The sampled rates
    # price launch-level attribution — launch.* spans keyed by dispatch
    # seq, host-side timestamps only, never a device sync — as tok/s and
    # client-observed TPOT p99.
    if (
        cont_block.get("dense_tokens_per_sec")
        and time.perf_counter() - T_START < BATCH_LEG_DEADLINE_S
    ):
        try:
            from distributed_llm_inference_tpu.utils.tracing import (
                SpanContext,
            )

            def tracing_leg(rate):
                eng_t = InferenceEngine(
                    c_cfg, params=c_params,
                    engine_cfg=EngineConfig(trace_sample_rate=rate),
                )
                cont_t = ContinuousEngine(
                    eng_t, n_slots=n_slots, chunk_steps=chunk,
                    slot_max_seq=slot_max_seq,
                )
                try:
                    cont_t.submit(prompts[0], **kw)  # warm slot programs
                    done = [0]
                    tpots = []
                    lock = threading.Lock()
                    it = iter(prompts)

                    def client():
                        while True:
                            with lock:
                                p = next(it, None)
                            if p is None:
                                return
                            tq = time.perf_counter()
                            r = cont_t.submit(
                                p, trace_ctx=SpanContext.new_root(), **kw
                            )
                            el = time.perf_counter() - tq
                            if r.get("status") == "success":
                                n = r["tokens_generated"]
                                with lock:
                                    done[0] += n
                                    if n > 1:
                                        tpots.append(
                                            max(
                                                0.0,
                                                el - float(r["ttft_s"]),
                                            ) / (n - 1)
                                        )

                    t0 = time.perf_counter()
                    threads = [
                        threading.Thread(target=client) for _ in range(8)
                    ]
                    for t in threads:
                        t.start()
                    for t in threads:
                        t.join()
                    wall = time.perf_counter() - t0
                    tpots.sort()
                    return {
                        "tokens_per_sec": (
                            round(done[0] / wall, 3) if done[0] else None
                        ),
                        "tpot_p99_s": (
                            round(
                                tpots[
                                    min(
                                        len(tpots) - 1,
                                        int(0.99 * len(tpots)),
                                    )
                                ],
                                5,
                            ) if tpots else None
                        ),
                        # proves each rate did what it says: 0 spans at
                        # off, launch.* spans present when sampled
                        "spans_recorded": eng_t.trace_store.stats()[
                            "spans"
                        ],
                    }
                finally:
                    cont_t.close()

            trc = {
                "off": tracing_leg(0.0),
                "rate_0p1": tracing_leg(0.1),
                "rate_1p0": tracing_leg(1.0),
            }
            base = cont_block["dense_tokens_per_sec"]
            off_v = trc["off"]["tokens_per_sec"]
            if off_v:
                trc["off_vs_dense"] = round(off_v / base, 3)
                trc["off_within_1pct"] = bool(off_v >= 0.99 * base)
            on_v = trc["rate_1p0"]["tokens_per_sec"]
            if off_v and on_v:
                trc["sampled_overhead_frac"] = round(
                    1.0 - on_v / off_v, 3
                )
            cont_block["tracing_overhead"] = trc
            _write_sidecar(dict(result, continuous=cont_block))
        except Exception:  # noqa: BLE001 - optional leg, never fatal
            import traceback

            traceback.print_exc(file=sys.stderr)

    if cont_block:
        result["continuous"] = cont_block
        # keep the round-3 flat key so round-over-round comparisons of the
        # dense-fleet number need no schema archaeology
        if "dense_tokens_per_sec" in cont_block:
            result["continuous_tokens_per_sec"] = cont_block[
                "dense_tokens_per_sec"
            ]
    _write_sidecar(result)

    # 1F1B microbatched-pipeline leg (parallel/schedule.py, BASELINE
    # config 5's schedule): pp=2 x microbatches=2 on a 2-virtual-CPU-device
    # mesh in a SUBPROCESS — its own process because the mesh needs
    # xla_force_host_platform_device_count, which must be set before the
    # backend initializes and must not perturb this process's single-device
    # measurements. Tiny model; direction-only round-over-round signal
    # (round-4 review #2). Never fatal.
    if time.perf_counter() - T_START < BATCH_LEG_DEADLINE_S:
        try:
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=2"
            )
            proc = subprocess.run(
                [sys.executable, "-c", _MB_LEG_SRC],
                capture_output=True, text=True, timeout=240, env=env,
            )
            line = next(
                (
                    ln for ln in reversed(proc.stdout.splitlines())
                    if ln.strip().startswith("{")
                ),
                None,
            )
            if proc.returncode == 0 and line:
                result["microbatch_1f1b"] = json.loads(line)
            else:
                sys.stderr.write(
                    f"1f1b leg rc={proc.returncode}: "
                    f"{(proc.stderr or '')[-800:]}\n"
                )
            _write_sidecar(result)
        except Exception:  # noqa: BLE001 - optional leg, never fatal
            import traceback

            traceback.print_exc(file=sys.stderr)

    # comms-contract cross-check leg (analysis/comms.py): derived static
    # bytes/launch per wire link vs the dli_pp_wire_bytes_total deltas a
    # real pp=2 run accumulates, wire off AND on, exact agreement
    # asserted IN the child. Same subprocess pattern as the 1f1b leg
    # (the 2-device mesh needs xla_force_host_platform_device_count
    # before backend init). Never fatal.
    if time.perf_counter() - T_START < BATCH_LEG_DEADLINE_S:
        try:
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=2"
            )
            proc = subprocess.run(
                [sys.executable, "-c", _COMMS_LEG_SRC],
                capture_output=True, text=True, timeout=240, env=env,
            )
            line = next(
                (
                    ln for ln in reversed(proc.stdout.splitlines())
                    if ln.strip().startswith("{")
                ),
                None,
            )
            if proc.returncode == 0 and line:
                result["comms_report"] = json.loads(line)
            else:
                sys.stderr.write(
                    f"comms leg rc={proc.returncode}: "
                    f"{(proc.stderr or '')[-800:]}\n"
                )
            _write_sidecar(result)
        except Exception:  # noqa: BLE001 - optional leg, never fatal
            import traceback

            traceback.print_exc(file=sys.stderr)

    # MPMD stage-pipeline leg (serving/stage_runtime.py): real 2-process
    # stage fleet over the HTTP transport vs the single-process forward
    # loop — TTFT/TPOT p99 per topology, transcript bit-identity, and
    # timed kill -9 recovery (warm block-shadow restore vs cold), see
    # _MPMD_LEG_SRC. Own subprocess (the stage fleet spawns its own
    # children; the leg's jax must not inherit this process's device
    # config). Never fatal.
    if time.perf_counter() - T_START < BATCH_LEG_DEADLINE_S:
        try:
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
            proc = subprocess.run(
                [sys.executable, "-c", _MPMD_LEG_SRC],
                capture_output=True, text=True, timeout=300, env=env,
            )
            line = next(
                (
                    ln for ln in reversed(proc.stdout.splitlines())
                    if ln.strip().startswith("{")
                ),
                None,
            )
            if proc.returncode == 0 and line:
                result["mpmd_pipeline"] = json.loads(line)
            else:
                sys.stderr.write(
                    f"mpmd leg rc={proc.returncode}: "
                    f"{(proc.stderr or '')[-800:]}\n"
                )
            _write_sidecar(result)
        except Exception:  # noqa: BLE001 - optional leg, never fatal
            import traceback

            traceback.print_exc(file=sys.stderr)

    # tiered-KV leg (engine/shadow.py HBM -> host -> disk; ISSUE r16):
    # a Zipf(alpha=1.0) long-tail prefix workload over a population far
    # wider than the HBM pool, served three ways — pool-only (kv_shadow
    # off), +host shadow, +host+disk — giving the hit-rate-vs-tier-depth
    # curve; then disk-warm-vs-cold TTFT on a long chain through a fresh
    # engine over the SAME chunk-file dir (the crash-restart shape), and
    # streamed vs whole-blob /kv pull timing on that chain. CPU: tiny
    # model, direction-only round-over-round signal. Never fatal.
    if cont_block and time.perf_counter() - T_START < BATCH_LEG_DEADLINE_S:
        try:
            import random as _random
            import shutil as _shutil
            import tempfile as _tempfile
            import urllib.request as _urlreq

            from distributed_llm_inference_tpu.serving.server import (
                InferenceServer,
            )

            from distributed_llm_inference_tpu.models import api as _M

            rng = _random.Random(16)
            KBS = 16
            POP, REQS = 24, 48
            zw = [1.0 / (r + 1) for r in range(POP)]  # Zipf alpha=1.0
            fam = [
                f"tier bench family {i:02d} prefix body text " * 2 + "go"
                for i in range(POP)
            ]  # ~80 chars -> 5 full 16-token blocks each
            order = rng.choices(range(POP), weights=zw, k=REQS)
            kw_t = dict(max_tokens=8, greedy=True, chat=False)
            tmp_disk = _tempfile.mkdtemp(prefix="dli-kvtier-")
            tmp_disk2 = _tempfile.mkdtemp(prefix="dli-kvtier-deep-")
            kvt = {
                "model": c_cfg.name, "platform": platform,
                "block_size": KBS, "pool_blocks": 26,
                "slot_max_seq": 128,
                "host_blocks": 48, "population": POP,
                "requests": REQS, "zipf_alpha": 1.0,
            }

            # curve variants: a deliberate capacity LADDER — pool (25
            # usable blocks, ~4 families) < host tier (56 blocks, ~11
            # families) < disk (unbounded) — against a 24-family x
            # 5-block prefix population, so each deeper tier can only
            # add hit rate the shallower one lacks the capacity for,
            # and the host tier churns enough to demote onto disk.
            def tier_variant(shadow, disk_dir, cfg_v=None, params_v=None,
                             pool=26, slot=128, host=48):
                eng_t = InferenceEngine(
                    cfg_v if cfg_v is not None else c_cfg,
                    params=params_v if params_v is not None else c_params,
                    engine_cfg=EngineConfig(
                        prefix_cache_entries=64, kv_shadow=shadow,
                        kv_shadow_blocks=host, kv_disk_dir=disk_dir,
                    ),
                )
                cont_t = ContinuousEngine(
                    eng_t, n_slots=2, chunk_steps=8, slot_max_seq=slot,
                    kv_pool_blocks=pool, kv_block_size=KBS,
                )
                return eng_t, cont_t

            def zipf_pass(cont_t):
                cont_t.submit(fam[0], **kw_t)  # warm slot programs
                cached = total = 0
                for i in order:
                    r = cont_t.submit(fam[i], **kw_t)
                    if r.get("status") == "success":
                        cached += r.get("prefix_cached_tokens", 0)
                        total += 5 * KBS  # full blocks per family prompt
                return (round(cached / total, 3) if total else None)

            curve = {}
            eng_t, cont_t = tier_variant(False, None)
            try:
                curve["pool_only"] = zipf_pass(cont_t)
            finally:
                cont_t.close()
            eng_t, cont_t = tier_variant(True, None)
            try:
                curve["host"] = zipf_pass(cont_t)
                cont_t._shadow.flush(10.0)
                sh = cont_t._shadow.stats()
                # host-only churn ledger: evictions here DROP (no tier
                # below) — the delta the +disk variant recovers
                kvt["host_variant_counters"] = {
                    k: sh[k] for k in ("copied", "evicted", "dropped")
                }
            finally:
                cont_t.close()
            eng_t, cont_t = tier_variant(True, tmp_disk)
            try:
                curve["host_disk"] = zipf_pass(cont_t)
                cont_t._shadow.flush(10.0)
                sh = cont_t._shadow.stats()
                kvt["tier_counters"] = {
                    k: sh[k] for k in (
                        "copied", "evicted", "demoted", "promoted",
                        "disk_hits", "disk_blocks", "disk_bytes", "dropped",
                    )
                }
            finally:
                cont_t.close()
            kvt["hit_rate_curve"] = curve
            _write_sidecar(dict(result, kv_tiers=kvt))

            # disk-warm vs cold TTFT, on a DEEP chain (118 blocks at a
            # 2048-token window — the regime the disk tier exists for:
            # cold re-prefill cost grows superlinearly with depth while
            # promotion stays one parallel chunk-file read + one batched
            # restore launch). Seed engine runs the chain once and
            # gracefully drains its host tier to disk; a FRESH engine
            # over the same chunk dir (the crash-restart shape) rescans
            # tier 2 and promotes at admission; the cold engine
            # re-prefills the whole chain.
            c_cfg_t = get_model_config(
                "test-llama-tiny", dtype="float32", eos_token_id=-1,
                max_seq_len=2048,
            )
            c_params_t = _M.init_params(c_cfg_t, jax.random.PRNGKey(2))
            long_prompt = "deep chain segment data " * 79 + "end!"
            deep_kw = dict(
                cfg_v=c_cfg_t, params_v=c_params_t,
                pool=260, slot=2048, host=160,
            )
            kvt["deep_chain"] = {
                "max_seq_len": 2048, "pool_blocks": 260,
                "host_blocks": 160,
            }
            eng_s, cont_s = tier_variant(True, tmp_disk2, **deep_kw)
            deep = None
            try:
                r_long = cont_s.submit(long_prompt, **kw_t)
                deep = (r_long.get("kv_digests") or [None])[-1]
                cont_s._shadow.flush(10.0)
                kvt["drained_to_disk"] = cont_s._shadow.demote_host_tier()
                kvt["long_chain_tier_at_seed_close"] = (
                    cont_s._shadow.digest_tier(deep) if deep else None
                )
            finally:
                cont_s.close()
            eng_w, cont_w = tier_variant(True, tmp_disk2, **deep_kw)
            try:
                cont_w.submit(fam[0], **kw_t)  # warm slot programs
                r_w = cont_w.submit(long_prompt, **kw_t)
                eng_c, cont_c = tier_variant(True, None, **deep_kw)
                try:
                    cont_c.submit(fam[0], **kw_t)  # warm programs
                    r_c = cont_c.submit(long_prompt, **kw_t)
                finally:
                    cont_c.close()
                if (
                    r_w.get("status") == "success"
                    and r_c.get("status") == "success"
                ):
                    warm, cold = float(r_w["ttft_s"]), float(r_c["ttft_s"])
                    kvt["ttft"] = {
                        "disk_warm_s": round(warm, 5),
                        "cold_s": round(cold, 5),
                        "promoted_blocks": r_w.get(
                            "kv_promoted_blocks", 0
                        ),
                        "speedup": (
                            round(cold / warm, 2) if warm > 0 else None
                        ),
                        "warm_ge_2x": bool(warm > 0 and cold >= 2 * warm),
                    }

                # streamed vs whole-blob /kv pull on the same long chain
                # (now host-resident after the warm promotion): time to
                # first importable byte is the number decode overlap
                # actually sees
                if deep:
                    srv_t = InferenceServer(
                        eng_w, "127.0.0.1", 0, max_tokens_cap=64,
                        continuous=cont_w,
                    )
                    srv_t.start()
                    try:
                        base = f"http://127.0.0.1:{srv_t.port}/kv/{deep}"

                        def pull(streamed):
                            req = _urlreq.Request(base)
                            if streamed:
                                req.add_header("X-KV-Stream", "1")
                            t0 = time.perf_counter()
                            with _urlreq.urlopen(req, timeout=30) as resp:
                                first = resp.read(9)
                                t1 = time.perf_counter()
                                body = first + resp.read()
                                t2 = time.perf_counter()
                            return t1 - t0, t2 - t0, len(body)

                        # warm both paths once (encode caches, TCP stack)
                        pull(False), pull(True)
                        b_first, b_total, b_len = pull(False)
                        s_first, s_total, s_len = pull(True)
                        kvt["pull"] = {
                            "chain_blocks": r_w.get(
                                "kv_promoted_blocks", 0
                            ),
                            "blob_first_byte_s": round(b_first, 5),
                            "blob_total_s": round(b_total, 5),
                            "blob_bytes": b_len,
                            "stream_first_byte_s": round(s_first, 5),
                            "stream_total_s": round(s_total, 5),
                            "stream_bytes": s_len,
                            "stream_first_byte_speedup": (
                                round(b_first / s_first, 2)
                                if s_first > 0 else None
                            ),
                        }
                    finally:
                        srv_t.shutdown()
            finally:
                cont_w.close()
                _shutil.rmtree(tmp_disk, ignore_errors=True)
                _shutil.rmtree(tmp_disk2, ignore_errors=True)
            result["kv_tiers"] = kvt
            _write_sidecar(result)
        except Exception:  # noqa: BLE001 - optional leg, never fatal
            import traceback

            traceback.print_exc(file=sys.stderr)

    # CPU round-over-round drift guard (round-4 review weak #2: 0.24 ->
    # 0.213 -> 0.206 with nothing watching). Compare this run's headline
    # against the newest committed BENCH_r*.json CPU number and FLAG when
    # the drift leaves a ±15% band — the field makes the one number the
    # driver reliably captures self-auditing.
    if not on_tpu:
        prev = _prev_cpu_value()
        if prev:
            result["prev_round_cpu_tokens_per_sec"] = prev["value"]
            result["prev_round_cpu_source"] = prev["source"]
            drift = tok_s / prev["value"] - 1.0
            result["cpu_drift"] = round(drift, 3)
            if abs(drift) > 0.15:
                result["cpu_drift_alert"] = True
    _write_sidecar(result)
    _emit(result)


def _remaining(margin=30.0):
    return WATCHDOG_S - (time.perf_counter() - T_START) - margin


def _parse_child_json(proc_stdout):
    emitted = None
    for line in proc_stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                json.loads(line)
                emitted = line
            except ValueError:
                continue
    return json.loads(emitted) if emitted else None


def _read_sidecar(path):
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except Exception:  # noqa: BLE001 - absent/corrupt sidecar = no result
        return None


def _run_child(env, deadline_s):
    """Run the bench child to completion; (result_dict_or_None, err).

    Three recovery layers for a child that dies mid-run (tunnel wedge, the
    parent's own timeout): the last JSON line it FLUSHED (the primary
    metric is emitted the moment it exists), the TimeoutExpired exception's
    partial stdout, and the sidecar file it rewrites after every completed
    leg. A timed-out child with a solo number therefore still lands a TPU
    headline instead of "child exceeded Ns"."""
    import tempfile

    env = dict(env)
    env["_BENCH_BACKEND_RESOLVED"] = "1"
    env["_BENCH_DEADLINE_S"] = str(max(30.0, deadline_s - 30.0))
    # mkstemp, not mktemp: the parent CREATES and owns the file up front,
    # so no other process can squat the predictable /tmp name between name
    # generation and the child's first atomic replace
    fd, sidecar = tempfile.mkstemp(prefix="bench_sidecar_", suffix=".json")
    os.close(fd)
    env["_BENCH_SIDECAR"] = sidecar
    partial_out = ""
    clean_exit = False
    timed_out = None
    try:
        proc = subprocess.run(
            [sys.executable, __file__], env=env,
            capture_output=True, text=True, timeout=deadline_s,
        )
        partial_out = proc.stdout or ""
        clean_exit = proc.returncode == 0
        sys.stderr.write((proc.stderr or "")[-4000:])
        rc_note = f"child rc={proc.returncode} emitted no JSON line; " \
                  f"stderr tail: {(proc.stderr or '')[-500:]}"
    except subprocess.TimeoutExpired as e:
        # capture_output buffers in-memory: the exception carries whatever
        # the child flushed before the kill
        partial_out = (
            e.stdout.decode() if isinstance(e.stdout, bytes) else e.stdout
        ) or ""
        timed_out = f"child exceeded {deadline_s:.0f}s"
        rc_note = timed_out
    # Precedence: on a CLEAN exit the final stdout line is the complete
    # result. On any other outcome the SIDECAR is at least as fresh as
    # anything stdout held when the child died (it is rewritten after
    # every completed leg, stdout only at the solo emit + the end), so it
    # wins — a kill mid-int8-leg must not drop the batch8 number the
    # sidecar already recorded.
    if clean_exit:
        out = _parse_child_json(partial_out) or _read_sidecar(sidecar)
    else:
        out = _read_sidecar(sidecar) or _parse_child_json(partial_out)
    try:
        os.unlink(sidecar)
    except OSError:
        pass
    if out is None:
        return None, rc_note
    if timed_out:
        out["child_timed_out"] = True
    return out, None


def main():
    done = threading.Event()
    # The child's watchdog must fire BEFORE the parent's subprocess
    # timeout kills it, or its partial result dies with it — the parent
    # passes the remaining budget (minus a margin) down via env.
    budget = float(os.environ.get("_BENCH_DEADLINE_S") or WATCHDOG_S)

    def watchdog():
        if not done.wait(budget):
            partial = _PARTIAL.get("result")
            if partial is not None:
                # the primary metric already exists — land it (minus
                # whatever optional leg was still running) rather than a
                # failure line
                partial["watchdog_truncated"] = True
                _emit(partial)
            else:
                _fail_line(
                    f"watchdog: benchmark exceeded {budget:.0f}s wall clock",
                    platform="unknown",
                )
            os._exit(0)

    threading.Thread(target=watchdog, daemon=True).start()

    if os.environ.get("_BENCH_BACKEND_RESOLVED") != "1":
        rc = _orchestrate()
        done.set()
        return rc

    try:
        run_benchmark()
    except Exception as e:  # noqa: BLE001 - must always land a JSON line
        import traceback

        traceback.print_exc(file=sys.stderr)
        partial = _PARTIAL.get("result")
        if partial is not None:
            # an optional leg died AFTER the primary metric landed: the
            # headline must win over a 0.0 fail line (the consumer takes
            # the LAST parseable stdout line)
            partial["leg_error"] = str(e)[-500:]
            _emit(partial)
            _write_sidecar(partial)
        else:
            _fail_line(e, platform=os.environ.get("JAX_PLATFORMS") or "unknown")
    done.set()
    return 0


def _orchestrate():
    """Parent process: probe TPU, run the measurement child, keep
    re-probing the TPU around/after a CPU fallback, and ALWAYS emit
    exactly one JSON line (with the full probe history attached).

    Flow (round-2 review #2 — the tunnel wedges for hours but can
    recover mid-run, and a recovered tunnel must still yield a TPU
    number):
      1. two quick TPU probes; if up, run the TPU child with the whole
         remaining budget and land its result;
      2. else start the CPU fallback child and, while it runs, probe the
         TPU every ~PROBE_INTERVAL_S;
      3. after the CPU result lands, keep probing until the remaining
         budget drops below MIN_TPU_LEG_S; the moment a probe succeeds,
         run a TPU child with the remaining budget and PREFER its result
         (the CPU number is kept as cpu_fallback_* fields).
    """
    probes = []
    # a probe that RESOLVES to a non-TPU platform means no TPU plugin
    # exists on this host at all (vs. a wedged tunnel, which times out /
    # errors) — further probing is futile and must not delay the CPU line
    no_tpu_ever = [False]

    def probe_tpu():
        t = round(time.perf_counter() - T_START, 1)
        ok, info = _probe_backend(dict(os.environ), PROBE_TIMEOUT_S)
        if ok and info.get("platform") != "tpu":
            no_tpu_ever[0] = True
            ok, info = False, f"resolved platform {info.get('platform')!r}"
        entry = {"t": t, "ok": ok}
        if ok:
            entry["device_kind"] = info.get("device_kind")
        else:
            entry["err"] = str(info)[-200:]
        probes.append(entry)
        return ok

    def finish(result, cpu_result=None):
        result["tpu_probes"] = probes
        if cpu_result is not None and result is not cpu_result:
            # the fallback that ran while the tunnel was down — kept for
            # the record, never as the headline
            result["cpu_fallback_tokens_per_sec"] = cpu_result.get("value")
        _emit(result)
        return 0

    tpu_up = probe_tpu()
    if not tpu_up and not no_tpu_ever[0]:
        tpu_up = probe_tpu()
    if tpu_up:
        result, err = _run_child(os.environ, max(60.0, _remaining()))
        if result is not None and result.get("platform") == "tpu":
            return finish(result)
        # TPU child died or fell over mid-run: fall through to the CPU
        # fallback with whatever budget is left
        if err:
            probes.append(
                {"t": round(time.perf_counter() - T_START, 1), "ok": False,
                 "err": f"tpu child: {err}"[-200:]}
            )

    cpu_env = dict(os.environ, JAX_PLATFORMS="cpu")
    ok, info = _probe_backend(cpu_env, PROBE_TIMEOUT_S)
    if not ok:
        _fail_line(f"cpu fallback probe failed: {info}", tpu_probes=probes)
        return 0

    # CPU child runs detached so the parent can keep probing the TPU in
    # parallel (independent processes; the probe touches only the tunnel).
    # stdout/stderr go to temp FILES, not pipes: an undrained pipe filling
    # with XLA warnings would deadlock the child.
    import tempfile

    cpu_env["_BENCH_BACKEND_RESOLVED"] = "1"
    cpu_budget = max(60.0, min(600.0, _remaining(margin=120.0)))
    cpu_env["_BENCH_DEADLINE_S"] = str(max(30.0, cpu_budget - 30.0))
    fd, cpu_sidecar = tempfile.mkstemp(
        prefix="bench_sidecar_cpu_", suffix=".json"
    )
    os.close(fd)
    cpu_env["_BENCH_SIDECAR"] = cpu_sidecar
    out_f = tempfile.TemporaryFile(mode="w+", encoding="utf-8")
    err_f = tempfile.TemporaryFile(mode="w+", encoding="utf-8")
    child = subprocess.Popen(
        [sys.executable, __file__], env=cpu_env, stdout=out_f, stderr=err_f,
    )
    last_probe_end = time.perf_counter()
    t_child0 = time.perf_counter()
    while child.poll() is None:
        if time.perf_counter() - t_child0 > cpu_budget:
            child.kill()
            break
        if (
            not tpu_up
            and not no_tpu_ever[0]
            and time.perf_counter() - last_probe_end >= PROBE_INTERVAL_S
            and _remaining() > MIN_TPU_LEG_S
        ):
            tpu_up = probe_tpu()  # blocking, up to PROBE_TIMEOUT_S
            last_probe_end = time.perf_counter()
        else:
            time.sleep(2.0)
    child.wait()
    out_f.seek(0)
    err_f.seek(0)
    cpu_out = out_f.read()
    sys.stderr.write(err_f.read()[-4000:])
    out_f.close()
    err_f.close()
    # same precedence rule as _run_child: a clean exit's final stdout line
    # is complete; a killed child's sidecar is fresher than whatever it
    # had flushed (later legs write sidecar-only until the final emit)
    if child.returncode == 0:
        cpu_result = _parse_child_json(cpu_out) or _read_sidecar(cpu_sidecar)
    else:
        cpu_result = _read_sidecar(cpu_sidecar) or _parse_child_json(cpu_out)
    try:
        os.unlink(cpu_sidecar)
    except OSError:
        pass

    # post-CPU probe loop: the whole remaining budget (minus one TPU leg)
    # is probe time — but only while a TPU could still appear (a wedged
    # tunnel can recover; an absent plugin cannot)
    while not tpu_up and not no_tpu_ever[0] and _remaining() > MIN_TPU_LEG_S:
        wait = PROBE_INTERVAL_S - (time.perf_counter() - last_probe_end)
        if wait > 0:
            time.sleep(min(wait, _remaining() - MIN_TPU_LEG_S))
        tpu_up = probe_tpu()
        last_probe_end = time.perf_counter()

    if tpu_up and _remaining() > 60.0:
        result, err = _run_child(os.environ, _remaining())
        if result is not None and result.get("platform") == "tpu":
            return finish(result, cpu_result)
        if err:
            probes.append(
                {"t": round(time.perf_counter() - T_START, 1), "ok": False,
                 "err": f"tpu child: {err}"[-200:]}
            )

    if cpu_result is not None:
        return finish(cpu_result)
    _fail_line(
        "no child produced a result", platform="none", tpu_probes=probes
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
