#!/usr/bin/env python
"""Headline benchmark: TinyLlama-1.1B autoregressive decode throughput.

Apples-to-apples with the reference's own observed number on the same
model (`TinyLlama/TinyLlama-1.1B-Chat-v1.0`): ~0.12-0.2 tokens/sec end to
end across 3 Colab CPU VMs with no KV cache and 4 JSON-over-WAN activation
transfers per token (/root/reference/Test.py:61, orchestration.py:202).
Baseline pinned at the midpoint, 0.16 tok/s.

Here the same architecture runs as one jit-compiled program on one TPU
chip: bf16 params in HBM, prefill in a single call, decode as an on-device
while-loop with a donated KV cache. Weights are random-init (zero network
egress; throughput is weight-value independent).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp

REFERENCE_TOK_S = 0.16  # midpoint of the reference's 0.12-0.2 tok/s
PROMPT_LEN = 128
DECODE_STEPS = 64
# skip the optional batch-8 leg when the single-stream part (compiles
# included) has already used this much wall clock
BATCH_LEG_DEADLINE_S = 420.0
T_START = time.perf_counter()


def _timed(fn):
    t0 = time.perf_counter()
    out = fn()
    return time.perf_counter() - t0, out


def main():
    from distributed_llm_inference_tpu.engine import generate as G
    from distributed_llm_inference_tpu.models import api as M
    from distributed_llm_inference_tpu.models.registry import get_model_config

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    # eos_token_id=-1: no token id can match, so the decode loop never
    # early-exits — every run measures exactly DECODE_STEPS steps.
    cfg = get_model_config(
        "tinyllama-1.1b",
        dtype="bfloat16" if on_tpu else "float32",
        eos_token_id=-1,
    )
    params = M.init_params(cfg, jax.random.PRNGKey(0))

    tokens = jnp.asarray(
        [[cfg.bos_token_id] + [7] * (PROMPT_LEN - 1)], jnp.int32
    )
    plen = jnp.int32(PROMPT_LEN)
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(1))
    limit = jnp.int32(DECODE_STEPS)

    import numpy as np

    # Under the axon TPU tunnel, jax.block_until_ready returns immediately;
    # only a device->host fetch waits for the compute queue. The fetch has a
    # fixed tunnel round-trip (~70 ms), so: time K back-to-back device calls
    # ending in one scalar fetch, subtract the separately-measured RTT, and
    # divide by K. (On a local backend RTT measures ~0 and this is exact.)
    def fetch(x):
        return np.asarray(x)

    trivial = jax.jit(lambda x: x + 1)
    fetch(trivial(jnp.float32(0)))  # warm
    rtt = min(
        _timed(lambda: fetch(trivial(jnp.float32(i))))[0] for i in range(5)
    )

    # warm-up: compile prefill + decode, drain the queue
    cache = M.init_kv_cache(cfg, 1, max_seq=512)
    first, _, cache = G.prefill(cfg, params, tokens, plen, cache, kp, sampling)
    out, n_gen, cache = G.decode(
        cfg, params, first, cache, plen, limit, kd, sampling,
        max_steps=DECODE_STEPS,
    )
    fetch(n_gen)

    # TTFT: one prefill (cache re-init enqueued first), scalar-fetch the token
    def prefill_once():
        c = M.init_kv_cache(cfg, 1, max_seq=512)
        f, _, c = G.prefill(cfg, params, tokens, plen, c, kp, sampling)
        fetch(f)

    ttft = max(min(_timed(prefill_once)[0] for _ in range(3)) - rtt, 0.0)

    # decode throughput: K chained decode calls (donated cache threaded
    # through), one scalar fetch at the end
    K = 4

    def decode_k():
        nonlocal cache
        for _ in range(K):
            out, n_gen, cache = G.decode(
                cfg, params, first, cache, plen, limit, kd, sampling,
                max_steps=DECODE_STEPS,
            )
        fetch(n_gen)

    decode_s = max(min(_timed(decode_k)[0] for _ in range(3)) - rtt, 1e-9) / K
    tok_s = DECODE_STEPS / decode_s

    # batched decode: 8 identical streams through the raw backend decode
    # loop (NOT the engine's generate_batch ragged path — this measures the
    # aggregate-throughput ceiling batching exposes, with no left-pad
    # masking in the program). Weights stream from HBM once per step
    # regardless of batch, so aggregate throughput scales ~linearly until
    # compute-bound. The prefilled B=1 cache is tiled instead of compiling
    # a batched prefill (identical rows; only the decode program costs a
    # compile), and the leg is skipped entirely if the single-stream part
    # already ate the time budget — the primary metric must always land.
    batch_tok_s = None
    if time.perf_counter() - T_START < BATCH_LEG_DEADLINE_S:
        BATCH = 8
        first_b = jnp.tile(first, (BATCH,))
        cache_b = jax.tree.map(
            lambda x: jnp.tile(x, (1, BATCH) + (1,) * (x.ndim - 2)), cache
        )
        out, n_gen_b, cache_b = G.decode(
            cfg, params, first_b, cache_b, plen, limit, kd, sampling,
            max_steps=DECODE_STEPS,
        )
        fetch(n_gen_b)  # warm/compile

        def decode_k_batch():
            nonlocal cache_b
            for _ in range(K):
                out, n_gen, cache_b = G.decode(
                    cfg, params, first_b, cache_b, plen, limit, kd, sampling,
                    max_steps=DECODE_STEPS,
                )
            fetch(n_gen)

        batch_s = max(
            min(_timed(decode_k_batch)[0] for _ in range(3)) - rtt, 1e-9
        ) / K
        batch_tok_s = BATCH * DECODE_STEPS / batch_s

    result = {
        "metric": "tinyllama_1.1b_decode_throughput",
        "value": round(tok_s, 3),
        "unit": "tokens/sec",
        "vs_baseline": round(tok_s / REFERENCE_TOK_S, 1),
        "ttft_s": round(ttft, 4),
        "prompt_len": PROMPT_LEN,
        "decode_steps": DECODE_STEPS,
        "platform": platform,
        "dtype": cfg.dtype,
    }
    if batch_tok_s is not None:
        result["batch8_tokens_per_sec"] = round(batch_tok_s, 3)
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
