#!/usr/bin/env python
"""Continuous-batching benchmark: aggregate throughput + tail latency under
request churn, vs the serialized solo engine.

Drives the real ContinuousEngine (admission, slot recycling, lag-1 chunk
pipelining) with a closed-loop client fleet: `--clients` threads each keep
one request in flight until `--requests` total have been served. The solo
leg serves the same workload one request at a time — the reference's
serving model (/root/reference/orchestration.py:98,144).

Prints one JSON line:
  {"continuous_tok_s": ..., "solo_tok_s": ..., "speedup": ...,
   "p50_latency_s": ..., "p90_latency_s": ..., "slots": N}
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="tinyllama-1.1b")
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--clients", type=int, default=16)
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--max-tokens", type=int, default=64)
    ap.add_argument("--prompt-words", type=int, default=96)
    ap.add_argument("--solo-requests", type=int, default=4)
    args = ap.parse_args()

    import jax

    from distributed_llm_inference_tpu import EngineConfig, get_model_config
    from distributed_llm_inference_tpu.engine.continuous import ContinuousEngine
    from distributed_llm_inference_tpu.engine.engine import InferenceEngine

    platform = jax.devices()[0].platform
    # eos_token_id=-1: no sampled token can match, so every request emits
    # exactly max_tokens — throughput is workload-deterministic.
    cfg = get_model_config(
        args.model,
        dtype="bfloat16" if platform == "tpu" else "float32",
        eos_token_id=-1,
    )
    eng = InferenceEngine(cfg, engine_cfg=EngineConfig())
    prompts = [
        " ".join(f"w{i}_{j}" for j in range(args.prompt_words))
        for i in range(args.requests)
    ]
    kw = dict(max_tokens=args.max_tokens, greedy=True, chat=False)

    # -- solo (serialized) leg, with warm compile
    eng.generate(prompts[0], **kw)
    t0 = time.perf_counter()
    solo_tokens = sum(
        eng.generate(p, **kw)["tokens_generated"]
        for p in prompts[: args.solo_requests]
    )
    solo_tok_s = solo_tokens / (time.perf_counter() - t0)

    # -- continuous leg
    cont = ContinuousEngine(
        eng, n_slots=args.slots, chunk_steps=args.chunk,
        max_queue=args.requests,
    )
    try:
        cont.submit(prompts[0], **kw)  # warm decode_slots/insert programs
        lat: list[float] = []
        tokens = [0]
        lock = threading.Lock()
        it = iter(prompts)

        def client():
            while True:
                with lock:
                    p = next(it, None)
                if p is None:
                    return
                t = time.perf_counter()
                r = cont.submit(p, **kw)
                dt = time.perf_counter() - t
                assert r["status"] == "success", r
                with lock:
                    lat.append(dt)
                    tokens[0] += r["tokens_generated"]

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client) for _ in range(args.clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        lat.sort()
        from distributed_llm_inference_tpu.utils.metrics import (
            latency_summary,
        )

        out = {
            "continuous_tok_s": round(tokens[0] / wall, 2),
            "solo_tok_s": round(solo_tok_s, 2),
            "speedup": round(tokens[0] / wall / solo_tok_s, 2),
            "p50_latency_s": round(lat[len(lat) // 2], 3),
            "p90_latency_s": round(lat[int(len(lat) * 0.9)], 3),
            "requests": len(lat),
            "slots": args.slots,
            "chunk_steps": args.chunk,
            "max_tokens": args.max_tokens,
            "platform": platform,
            "peak_occupancy": cont.stats()["peak_occupancy"],
            # the registry's view of the same run: TTFT/TPOT/step-time
            # percentiles + occupancy — the per-request stage signal the
            # aggregate tok/s number cannot show
            "metrics": latency_summary(eng.metrics),
        }
        print(json.dumps(out))
    finally:
        cont.close()


if __name__ == "__main__":
    main()
