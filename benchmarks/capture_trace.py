#!/usr/bin/env python
"""Capture a jax.profiler trace of the flagship decode loop as committed
evidence of the compiled program structure.

Round-4 review #1: with the TPU tunnel dead for four straight driver
windows, the repo carries no judge-verifiable artifact behind its perf
claims. This script produces the best capturable proxy on whatever
backend is reachable: a profiler trace directory showing the ONE
jit-compiled while-loop per decode call (zero Python per token — the
design claim every throughput number rests on), plus a JSON summary with
the raw per-rep timings. On TPU it additionally records device_kind so
the trace doubles as primary evidence for the tok/s measurements.

Usage: python benchmarks/capture_trace.py [--out traces/<name>]
       [--steps 8] [--reps 3]
Prints one JSON line; writes the trace under --out.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None, help="trace dir (default: traces/<platform>_solo)")
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--model", default="tinyllama-1.1b")
    args = ap.parse_args()

    import jax

    if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
        # the axon site pin overrides the env var; a pre-backend-init
        # config update wins (same workaround as tests/conftest.py)
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from distributed_llm_inference_tpu.engine import generate as G
    from distributed_llm_inference_tpu.models import api as M
    from distributed_llm_inference_tpu.models.registry import get_model_config

    dev = jax.devices()[0]
    platform = dev.platform
    out_dir = args.out or os.path.join(REPO, "traces", f"{platform}_solo")
    os.makedirs(out_dir, exist_ok=True)

    cfg = get_model_config(
        args.model,
        dtype="bfloat16" if platform == "tpu" else "float32",
        eos_token_id=-1,  # never early-exits: every rep runs exactly --steps
    )
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray([[cfg.bos_token_id] + [7] * 127], jnp.int32)
    plen = jnp.int32(tokens.shape[1])
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(1))
    limit = jnp.int32(args.steps)

    # warm/compile outside the trace so the capture shows steady-state
    # dispatch: one XLA while-loop per decode call, no per-token Python
    cache = M.init_kv_cache(cfg, 1, max_seq=256)
    first, _, cache = G.prefill(cfg, params, tokens, plen, cache, kp, sampling)
    out, n_gen, cache = G.decode(
        cfg, params, first, cache, plen, limit, kd, sampling,
        max_steps=args.steps,
    )
    jax.block_until_ready(n_gen)

    per_rep = []
    with jax.profiler.trace(out_dir):
        for _ in range(args.reps):
            t0 = time.perf_counter()
            out, n_gen, cache = G.decode(
                cfg, params, first, cache, plen, limit, kd, sampling,
                max_steps=args.steps,
            )
            jax.block_until_ready(n_gen)
            per_rep.append(round(time.perf_counter() - t0, 4))

    best = min(per_rep)
    result = {
        "artifact": "decode_trace",
        "model": cfg.name,
        "platform": platform,
        "device_kind": dev.device_kind,
        "dtype": cfg.dtype,
        "decode_steps": args.steps,
        "per_rep_s": per_rep,
        "tokens_per_sec_best": round(args.steps / best, 3),
        "trace_dir": os.path.relpath(out_dir, REPO),
    }
    line = json.dumps(result)
    print(line)
    with open(os.path.join(out_dir, "summary.json"), "w", encoding="utf-8") as f:
        f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
