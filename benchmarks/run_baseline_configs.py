#!/usr/bin/env python
"""Benchmark harness for the five BASELINE.json configs.

The reference publishes no benchmarks (SURVEY.md §6) — its only number is
the client-side note "100-125 seconds expected" for 15-20 tokens across
Colab VMs (/root/reference/Test.py:61). This harness measures OUR stack on
the five target configs:

  1. single-worker GPT-2-small, greedy, 128-tok prompt
  2. 2-stage pipeline: GPT-2-medium, greedy
  3. 4-stage pipeline: Llama-2-7B, greedy, HBM KV cache
  4. 8-stage pipeline: Llama-2-13B, top-p sampling, batch=1
  5. 8-stage microbatched (1F1B) pipeline: Llama-3-8B, batch=8

Two scales:
  --scale tiny  (default) CI-sized models of the same architecture family
                on an 8-device VIRTUAL CPU mesh — validates every config's
                parallel structure on any host, numbers are NOT chip perf.
  --scale full  the real models on real devices (a v5e-8 for configs 2-5);
                requires the devices and the HBM to exist.

Prints one JSON line per config:
  {"config": N, "desc": ..., "tokens_per_sec": ..., "ttft_s": ...,
   "aggregate_tokens_per_sec": ..., "scale": ..., "mesh": ..., ...}
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _force_cpu_mesh(n: int):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + f" --xla_force_host_platform_device_count={n}"
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


# (desc, model_tiny, model_full, mesh kwargs, microbatches, batch, greedy)
CONFIGS = [
    ("single-worker GPT-2-small, greedy, 128-tok prompt",
     "test-gpt2-tiny", "gpt2-small", {}, 1, 1, True),
    ("2-stage pipeline: GPT-2-medium, greedy",
     "test-gpt2-tiny", "gpt2-medium", {"pp": 2}, 1, 1, True),
    ("4-stage pipeline: Llama-2-7B, greedy, HBM KV-cache",
     "test-llama-tiny", "llama2-7b", {"pp": 4}, 1, 1, True),
    ("8-stage pipeline: Llama-2-13B, top-p, batch=1",
     "test-llama-tiny", "llama2-13b", {"pp": 8}, 1, 1, False),
    ("8-stage microbatched 1F1B: Llama-3-8B, batch=8",
     "test-llama-tiny", "llama3-8b", {"pp": 8}, 8, 8, True),
]


def run_config(i, desc, model, mesh_kwargs, microbatches, batch, greedy,
               scale, prompt_len, steps):
    import jax
    import jax.numpy as jnp

    from distributed_llm_inference_tpu.config import MeshConfig
    from distributed_llm_inference_tpu.engine import generate as G
    from distributed_llm_inference_tpu.models.registry import get_model_config
    from distributed_llm_inference_tpu.runtime import create_backend

    pp = mesh_kwargs.get("pp", 1)
    cfg = get_model_config(model)
    if cfg.n_layers % max(pp, 1) != 0:
        # tiny models keep their family but need a pp-divisible depth
        cfg = cfg.replace(n_layers=max(pp, 1) * max(1, cfg.n_layers // max(pp, 1)))
    on_tpu = jax.default_backend() == "tpu"
    cfg = cfg.replace(dtype="bfloat16" if on_tpu else "float32", eos_token_id=-1)

    _, backend = create_backend(
        cfg, mesh_cfg=MeshConfig(**mesh_kwargs), microbatches=microbatches
    )

    max_seq = prompt_len + steps + 8
    tokens = jnp.asarray(
        [[cfg.bos_token_id] + [7] * (prompt_len - 1)] * batch, jnp.int32
    )
    plen = jnp.int32(prompt_len)
    sampling = G.default_sampling(
        temperature=0.7, top_k=0, top_p=0.9, greedy=greedy
    )
    kp, kd = jax.random.split(jax.random.PRNGKey(0))

    cache = backend.init_cache(batch, max_seq)
    # warm / compile
    first, logits, cache = backend.prefill(tokens, plen, cache, kp, sampling)
    out, n_gen, cache = backend.decode(
        first, cache, plen, jnp.int32(steps), kd, sampling, max_steps=steps
    )
    jax.block_until_ready(out)

    # TTFT: fresh prefill
    t0 = time.perf_counter()
    first, logits, cache = backend.prefill(tokens, plen, cache, kp, sampling)
    jax.block_until_ready(first)
    ttft = time.perf_counter() - t0

    # decode throughput
    t0 = time.perf_counter()
    out, n_gen, cache = backend.decode(
        first, cache, plen, jnp.int32(steps), kd, sampling, max_steps=steps
    )
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0

    per_stream = steps / dt
    print(json.dumps({
        "config": i + 1,
        "desc": desc,
        "model": cfg.name,
        "scale": scale,
        "mesh": {"pp": pp, "microbatches": microbatches},
        "batch": batch,
        "sampler": "greedy" if greedy else "top-p",
        "tokens_per_sec": round(per_stream, 3),
        "aggregate_tokens_per_sec": round(per_stream * batch, 3),
        "ttft_s": round(ttft, 4),
        "decode_steps": steps,
        "prompt_len": prompt_len,
        "platform": jax.default_backend(),
    }), flush=True)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scale", choices=["tiny", "full"], default="tiny")
    ap.add_argument("--configs", default="1,2,3,4,5",
                    help="comma-separated subset, e.g. 1,3")
    ap.add_argument("--steps", type=int, default=0,
                    help="decode steps (default: 32 tiny / 64 full)")
    args = ap.parse_args(argv)

    if args.scale == "tiny":
        _force_cpu_mesh(8)
    steps = args.steps or (32 if args.scale == "tiny" else 64)
    prompt_len = 32 if args.scale == "tiny" else 128

    wanted = {int(x) for x in args.configs.split(",")}
    for i, (desc, tiny, full, mesh_kwargs, mb, batch, greedy) in enumerate(CONFIGS):
        if i + 1 not in wanted:
            continue
        model = tiny if args.scale == "tiny" else full
        run_config(i, desc, model, mesh_kwargs, mb, batch, greedy,
                   args.scale, prompt_len, steps)


if __name__ == "__main__":
    sys.exit(main())
