#!/usr/bin/env python
"""Real-weights serving artifact: HF checkpoint -> convert -> serve -> measure.

Round-3 review #4 asked for a real-model-scale proof of the serving path:
a checkpoint that exists as FILES in the HF format, converted by the
conversion CLI, mmap-shard-loaded by the server CLI, served over HTTP,
and measured end to end (warm TTFT + decode tok/s) — BASELINE config 3's
shape. This environment has zero network egress, so weight VALUES are
random-initialized; everything else — architecture, file format, the
convert -> store -> sharded-restore -> serve pipeline, and the
measurement — is the real path, and decode throughput is weight-value
independent. The artifact records that provenance explicitly.

Scales:
  test — CI-sized (64-dim, 3 layers): seconds, exercises every step.
  1b   — the REAL TinyLlama-1.1B architecture (vocab 32000, hidden 2048,
         inter 5632, 22 layers, 32 heads / 4 kv): the reference's model.
  7b   — the REAL Llama-2-7B architecture (vocab 32000, hidden 4096,
         inter 11008, 32 layers, 32 heads): BASELINE config 3's class.
         Feasible on TPU; on CPU expect minutes per request.

Usage: python benchmarks/real_weights_serve.py --scale 1b --pp 2 \
           [--quant int8] [--dtype bfloat16] [--out ARTIFACT.json]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCALES = {
    # (vocab, hidden, inter, layers, heads, kv_heads)
    "test": (256, 64, 128, 3, 4, 2),
    "1b": (32000, 2048, 5632, 22, 32, 4),
    "7b": (32000, 4096, 11008, 32, 32, 32),
}


def build_hf_dir(scale: str, dst: str) -> int:
    """Random-init an HF LlamaForCausalLM of the given architecture and
    save_pretrained it (safetensors). Returns the parameter count."""
    import torch
    import transformers

    vocab, hidden, inter, layers, heads, kv = SCALES[scale]
    cfg = transformers.LlamaConfig(
        vocab_size=vocab,
        hidden_size=hidden,
        intermediate_size=inter,
        num_hidden_layers=layers,
        num_attention_heads=heads,
        num_key_value_heads=kv,
        max_position_embeddings=2048,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    n_params = sum(p.numel() for p in model.parameters())
    model.save_pretrained(dst, safe_serialization=True)
    del model
    return n_params


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def post(port, payload, timeout=3600):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _elapsed_s(resp) -> float:
    """`time_taken` crosses the API as the reference's human string
    ("12.34s", orchestration.py:211-218); parse it back to seconds."""
    return float(str(resp.get("time_taken", "0")).rstrip("s"))


def serve_and_measure(work, store, pp, quant, max_tokens, tag="main") -> dict:
    """Start the server CLI on the store, warm every serving program with a
    cold request, then measure a warm request — reporting compile overhead
    (cold TTFT - warm TTFT), warm TTFT (pure prefill compute), the
    STEADY-STATE decode rate tokens/(elapsed - ttft), AND the warm
    END-TO-END rate tokens/elapsed (prompt pass included). The end-to-end
    number is the apples-to-apples comparison against the reference's
    0.12-0.2 tok/s (/root/reference/Test.py:61 measures whole-request
    wall time including the prompt pass); steady-state isolates the
    decode roofline. Round-5 advice #3: record both in the artifact so
    the headline comparison never silently favors this framework."""
    port = free_port()
    cmd = [
        sys.executable, "-m", "distributed_llm_inference_tpu.serving.server",
        "--checkpoint", store, "--host", "127.0.0.1", "--port", str(port),
        "--pp", str(pp),
        # raise the reference-compat 30-token default cap: the steady-state
        # split needs >= 64 decode steps to amortize per-request overhead
        "--max-tokens-cap", str(max(max_tokens, 30)),
    ]
    if quant:
        cmd += ["--quant", quant]
    print("⏳ serving:", " ".join(cmd))
    leg: dict = {"quant": quant}
    t_start = time.time()
    # log FILE, not a pipe: an undrained pipe filling with XLA/server logs
    # would block the child before /health ever answers
    srv_log = os.path.join(work, f"server_{tag}.log")
    log_f = open(srv_log, "w", encoding="utf-8")
    env = dict(os.environ)
    if pp > 1 and env.get("JAX_PLATFORMS", "").startswith("cpu"):
        # a pp-mesh on the CPU backend needs pp virtual devices; on TPU
        # the real chip count is the mesh's problem, not ours
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={pp}"
        )
    srv = subprocess.Popen(
        cmd, cwd=REPO, stdout=log_f, stderr=subprocess.STDOUT, text=True,
        env=env,
    )
    try:
        deadline = time.time() + 900
        while True:
            if srv.poll() is not None or time.time() > deadline:
                log_f.flush()
                with open(srv_log, encoding="utf-8") as f:
                    out = f.read()
                why = "died" if srv.poll() is not None else "never came up"
                raise SystemExit(f"server {why}:\n{out[-3000:]}")
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/health", timeout=2
                ) as r:
                    h = json.loads(r.read())
                    if h["status"] in ("healthy", "degraded"):
                        break
            except (OSError, ValueError):
                pass
            time.sleep(2)
        leg["startup_s"] = round(time.time() - t_start, 1)
        leg["backend"] = h.get("backend")

        prompt = "The quick brown fox jumps over the lazy dog. " * 4
        kw = dict(prompt=prompt, max_tokens=max_tokens, greedy=True,
                  chat=False)
        # cold request: compiles the prefill bucket + decode program for
        # this (prompt bucket, max_tokens) pair — every program the warm
        # request will touch
        cold = post(port, kw)
        if cold.get("status") != "success":
            raise SystemExit(f"cold request failed: {cold}")
        leg["cold_ttft_s"] = cold.get("ttft_s")
        warm = post(port, kw)
        if warm.get("status") != "success":
            raise SystemExit(f"warm request failed: {warm}")
        leg["warm_ttft_s"] = warm.get("ttft_s")
        # compile overhead = what the cold request paid that the warm one
        # didn't (XLA compile + first-touch); warm TTFT is prefill compute
        leg["compile_overhead_s"] = round(
            float(cold.get("ttft_s", 0.0)) - float(warm.get("ttft_s", 0.0)), 3
        )
        n = int(warm.get("tokens_generated", 0))
        elapsed = _elapsed_s(warm)
        decode_s = max(elapsed - float(warm.get("ttft_s", 0.0)), 1e-9)
        leg["warm_tokens_per_sec"] = float(warm.get("tokens_per_sec", 0.0))
        # warm END-TO-END tokens/elapsed, prompt pass included — the
        # number directly comparable to the reference's whole-request
        # 0.12-0.2 tok/s (its stats cannot split prefill from decode)
        leg["warm_end_to_end_tokens_per_sec"] = round(
            n / max(elapsed, 1e-9), 3
        )
        leg["warm_elapsed_s"] = round(elapsed, 2)
        leg["steady_tokens_per_sec"] = round(n / decode_s, 3)
        leg["decode_s"] = round(decode_s, 2)
        leg["tokens_generated"] = n
        leg["prompt_tokens"] = warm.get("prompt_tokens")
        # the SERVER's platform is what matters; read it off /workers
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/workers", timeout=60
        ) as r:
            workers = json.loads(r.read())
        leg["stages"] = {
            k: v for k, v in workers.items() if k != "detail"
        }
        leg["devices"] = [
            d for s in workers.get("detail", []) for d in s.get("devices", [])
        ]
    finally:
        srv.kill()
        try:
            srv.wait(timeout=30)
        except subprocess.TimeoutExpired:
            pass
        log_f.close()
    return leg


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", choices=sorted(SCALES), default="1b")
    ap.add_argument("--pp", type=int, default=1)
    ap.add_argument("--quant", default=None, choices=[None, "int8", "int4"])
    ap.add_argument("--dtype", default=None, choices=[None, "float32", "bfloat16"])
    # 64+ decode steps: enough to amortize per-request overhead so the
    # steady-state decode rate is measurable separately from TTFT
    # (round-4 review #3 — the 8-token artifact read as a regression
    # because nothing separated compile from steady-state)
    ap.add_argument("--max-tokens", type=int, default=64)
    ap.add_argument(
        "--int8", action="store_true",
        help="add an int8 weight-quant leg (second server on the same store)",
    )
    ap.add_argument("--work", default=None, help="scratch dir (default: mkdtemp)")
    ap.add_argument("--out", default=None, help="artifact JSON path")
    ap.add_argument("--keep", action="store_true", help="keep the work dir")
    args = ap.parse_args(argv)

    if args.dtype is None and "cpu" in os.environ.get("JAX_PLATFORMS", ""):
        # bf16 matmuls are EMULATED on CPU (per-op fp32 convert): the
        # round-4 artifact's 0.07 tok/s came from serving the default
        # bf16 store on a CPU host, ~3x under the fp32 decode rate the
        # bench measures on the same hardware. On a CPU run convert to
        # fp32 unless the caller explicitly asked otherwise; on TPU the
        # bf16 default stands (that's what the MXU wants).
        args.dtype = "float32"
    work = args.work or tempfile.mkdtemp(prefix=f"realweights_{args.scale}_")
    os.makedirs(work, exist_ok=True)
    hf_dir = os.path.join(work, "hf")
    store = os.path.join(work, "store")
    art: dict = {
        "artifact": "real_weights_serve",
        "scale": args.scale,
        "architecture": dict(
            zip(("vocab", "hidden", "inter", "layers", "heads", "kv_heads"),
                SCALES[args.scale])
        ),
        "pp": args.pp,
        "quant": args.quant,
        "provenance": (
            "HF-format LlamaForCausalLM checkpoint, RANDOM-initialized "
            "(zero-egress environment: no downloaded weights exist here); "
            "architecture matches the named model class exactly, and the "
            "convert -> store -> mmap-sharded-load -> HTTP-serve pipeline "
            "is the real-weights path bit for bit. Decode throughput is "
            "weight-value independent."
        ),
    }

    t0 = time.time()
    if not os.path.exists(os.path.join(hf_dir, "config.json")):
        print(f"⏳ building HF {args.scale} checkpoint in {hf_dir}")
        art["n_params"] = build_hf_dir(args.scale, hf_dir)
    art["hf_build_s"] = round(time.time() - t0, 1)

    t0 = time.time()
    # the store's marker is manifest.json (models/convert.py writes no
    # config.json) — the old check re-converted on every --work reuse
    if not os.path.exists(os.path.join(store, "manifest.json")):
        print("⏳ converting with models/convert.py")
        conv = [
            sys.executable, "-m", "distributed_llm_inference_tpu.models.convert",
            "--in", hf_dir, "--out", store,
        ]
        if args.dtype:
            conv += ["--dtype", args.dtype]
        subprocess.run(conv, check=True, cwd=REPO)
    art["convert_s"] = round(time.time() - t0, 1)
    art["store_bytes"] = sum(
        os.path.getsize(os.path.join(store, f)) for f in os.listdir(store)
    )

    try:
        leg = serve_and_measure(
            work, store, args.pp, args.quant, args.max_tokens, tag="main"
        )
        art.update(leg)
        if args.int8 and not args.quant:
            # int8 leg: same store, second server with --quant int8 — the
            # lever that halves weight bytes/token (decode's roofline)
            art["int8"] = serve_and_measure(
                work, store, args.pp, "int8", args.max_tokens, tag="int8"
            )
    finally:
        # failure path included: a 1b-scale work dir holds several GB of
        # HF checkpoint + converted store, and build/convert finish
        # before serving — a failed health wait must not leak it
        if not args.keep and not args.work:
            import shutil

            shutil.rmtree(work, ignore_errors=True)

    line = json.dumps(art)
    print(line)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
