#!/usr/bin/env python
"""Real-weights serving artifact: HF checkpoint -> convert -> serve -> measure.

Round-3 review #4 asked for a real-model-scale proof of the serving path:
a checkpoint that exists as FILES in the HF format, converted by the
conversion CLI, mmap-shard-loaded by the server CLI, served over HTTP,
and measured end to end (warm TTFT + decode tok/s) — BASELINE config 3's
shape. This environment has zero network egress, so weight VALUES are
random-initialized; everything else — architecture, file format, the
convert -> store -> sharded-restore -> serve pipeline, and the
measurement — is the real path, and decode throughput is weight-value
independent. The artifact records that provenance explicitly.

Scales:
  test — CI-sized (64-dim, 3 layers): seconds, exercises every step.
  1b   — the REAL TinyLlama-1.1B architecture (vocab 32000, hidden 2048,
         inter 5632, 22 layers, 32 heads / 4 kv): the reference's model.
  7b   — the REAL Llama-2-7B architecture (vocab 32000, hidden 4096,
         inter 11008, 32 layers, 32 heads): BASELINE config 3's class.
         Feasible on TPU; on CPU expect minutes per request.

Usage: python benchmarks/real_weights_serve.py --scale 1b --pp 2 \
           [--quant int8] [--dtype bfloat16] [--out ARTIFACT.json]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCALES = {
    # (vocab, hidden, inter, layers, heads, kv_heads)
    "test": (256, 64, 128, 3, 4, 2),
    "1b": (32000, 2048, 5632, 22, 32, 4),
    "7b": (32000, 4096, 11008, 32, 32, 32),
}


def build_hf_dir(scale: str, dst: str) -> int:
    """Random-init an HF LlamaForCausalLM of the given architecture and
    save_pretrained it (safetensors). Returns the parameter count."""
    import torch
    import transformers

    vocab, hidden, inter, layers, heads, kv = SCALES[scale]
    cfg = transformers.LlamaConfig(
        vocab_size=vocab,
        hidden_size=hidden,
        intermediate_size=inter,
        num_hidden_layers=layers,
        num_attention_heads=heads,
        num_key_value_heads=kv,
        max_position_embeddings=2048,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    n_params = sum(p.numel() for p in model.parameters())
    model.save_pretrained(dst, safe_serialization=True)
    del model
    return n_params


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def post(port, payload, timeout=3600):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", choices=sorted(SCALES), default="1b")
    ap.add_argument("--pp", type=int, default=1)
    ap.add_argument("--quant", default=None, choices=[None, "int8", "int4"])
    ap.add_argument("--dtype", default=None, choices=[None, "float32", "bfloat16"])
    ap.add_argument("--max-tokens", type=int, default=16)
    ap.add_argument("--work", default=None, help="scratch dir (default: mkdtemp)")
    ap.add_argument("--out", default=None, help="artifact JSON path")
    ap.add_argument("--keep", action="store_true", help="keep the work dir")
    args = ap.parse_args(argv)

    work = args.work or tempfile.mkdtemp(prefix=f"realweights_{args.scale}_")
    os.makedirs(work, exist_ok=True)
    hf_dir = os.path.join(work, "hf")
    store = os.path.join(work, "store")
    art: dict = {
        "artifact": "real_weights_serve",
        "scale": args.scale,
        "architecture": dict(
            zip(("vocab", "hidden", "inter", "layers", "heads", "kv_heads"),
                SCALES[args.scale])
        ),
        "pp": args.pp,
        "quant": args.quant,
        "provenance": (
            "HF-format LlamaForCausalLM checkpoint, RANDOM-initialized "
            "(zero-egress environment: no downloaded weights exist here); "
            "architecture matches the named model class exactly, and the "
            "convert -> store -> mmap-sharded-load -> HTTP-serve pipeline "
            "is the real-weights path bit for bit. Decode throughput is "
            "weight-value independent."
        ),
    }

    t0 = time.time()
    if not os.path.exists(os.path.join(hf_dir, "config.json")):
        print(f"⏳ building HF {args.scale} checkpoint in {hf_dir}")
        art["n_params"] = build_hf_dir(args.scale, hf_dir)
    art["hf_build_s"] = round(time.time() - t0, 1)

    t0 = time.time()
    if not os.path.exists(os.path.join(store, "config.json")):
        print("⏳ converting with models/convert.py")
        conv = [
            sys.executable, "-m", "distributed_llm_inference_tpu.models.convert",
            "--in", hf_dir, "--out", store,
        ]
        if args.dtype:
            conv += ["--dtype", args.dtype]
        subprocess.run(conv, check=True, cwd=REPO)
    art["convert_s"] = round(time.time() - t0, 1)
    art["store_bytes"] = sum(
        os.path.getsize(os.path.join(store, f)) for f in os.listdir(store)
    )

    port = free_port()
    cmd = [
        sys.executable, "-m", "distributed_llm_inference_tpu.serving.server",
        "--checkpoint", store, "--host", "127.0.0.1", "--port", str(port),
        "--pp", str(args.pp),
    ]
    if args.quant:
        cmd += ["--quant", args.quant]
    print("⏳ serving:", " ".join(cmd))
    t_start = time.time()
    # log FILE, not a pipe: an undrained pipe filling with XLA/server logs
    # would block the child before /health ever answers
    srv_log = os.path.join(work, "server.log")
    log_f = open(srv_log, "w", encoding="utf-8")
    srv = subprocess.Popen(
        cmd, cwd=REPO, stdout=log_f, stderr=subprocess.STDOUT, text=True,
    )
    try:
        deadline = time.time() + 900
        while True:
            if srv.poll() is not None or time.time() > deadline:
                log_f.flush()
                with open(srv_log, encoding="utf-8") as f:
                    out = f.read()
                why = "died" if srv.poll() is not None else "never came up"
                raise SystemExit(f"server {why}:\n{out[-3000:]}")
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/health", timeout=2
                ) as r:
                    h = json.loads(r.read())
                    if h["status"] in ("healthy", "degraded"):
                        break
            except (OSError, ValueError):
                pass
            time.sleep(2)
        art["startup_s"] = round(time.time() - t_start, 1)
        art["backend"] = h.get("backend")

        prompt = "The quick brown fox jumps over the lazy dog. " * 4
        kw = dict(prompt=prompt, max_tokens=args.max_tokens, greedy=True,
                  chat=False)
        cold = post(port, kw)
        if cold.get("status") != "success":
            raise SystemExit(f"cold request failed: {cold}")
        art["cold_ttft_s"] = cold.get("ttft_s")
        warm = post(port, kw)
        art["warm_ttft_s"] = warm.get("ttft_s")
        art["warm_tokens_per_sec"] = float(warm.get("tokens_per_sec", 0.0))
        art["tokens_generated"] = warm.get("tokens_generated")
        art["prompt_tokens"] = warm.get("prompt_tokens")
        # the SERVER's platform is what matters; read it off /workers
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/workers", timeout=60
        ) as r:
            workers = json.loads(r.read())
        art["stages"] = {
            k: v for k, v in workers.items() if k != "detail"
        }
        art["devices"] = [
            d for s in workers.get("detail", []) for d in s.get("devices", [])
        ]
    finally:
        srv.kill()
        try:
            srv.wait(timeout=30)
        except subprocess.TimeoutExpired:
            pass
        log_f.close()
        if not args.keep and not args.work:
            import shutil

            shutil.rmtree(work, ignore_errors=True)

    line = json.dumps(art)
    print(line)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
